"""Image extraction from workload resources (reference:
pkg/utils/api/image.go).

Standard extractors cover initContainers/containers/ephemeralContainers of
the 8 pod-controller kinds; policies may override per-kind extraction with
``imageExtractors`` configs (path/value/key/name).
"""

from __future__ import annotations

from typing import Dict, List, Optional

from .image import ImageInfo, get_image_info


class ImageExtractor:
    __slots__ = ('fields', 'key', 'value', 'name')

    def __init__(self, fields: List[str], key: str, value: str, name: str):
        self.fields = fields
        self.key = key
        self.value = value
        self.name = name


def build_standard_extractors(*tags: str) -> List[ImageExtractor]:
    """reference: image.go:105 BuildStandardExtractors"""
    out = []
    for tag in ('initContainers', 'containers', 'ephemeralContainers'):
        out.append(ImageExtractor(list(tags) + [tag, '*'], 'name', 'image', tag))
    return out


_POD = build_standard_extractors('spec')
_POD_CONTROLLER = build_standard_extractors('spec', 'template', 'spec')
_CRONJOB = build_standard_extractors('spec', 'jobTemplate', 'spec',
                                     'template', 'spec')

REGISTERED_EXTRACTORS: Dict[str, List[ImageExtractor]] = {
    'Pod': _POD,
    'DaemonSet': _POD_CONTROLLER,
    'Deployment': _POD_CONTROLLER,
    'ReplicaSet': _POD_CONTROLLER,
    'ReplicationController': _POD_CONTROLLER,
    'StatefulSet': _POD_CONTROLLER,
    'CronJob': _CRONJOB,
    'Job': _POD_CONTROLLER,
}


def _lookup_extractors(kind: str, configs: Optional[dict]
                       ) -> Optional[List[ImageExtractor]]:
    """reference: image.go:117 lookupImageExtractor"""
    if configs and kind in configs:
        out = []
        for c in configs[kind]:
            fields = [seg.strip() for seg in (c.get('path') or '').split('/')
                      if seg.strip()]
            value = c.get('value') or ''
            if not value and fields:
                value = fields[-1]
                fields = fields[:-1]
            out.append(ImageExtractor(fields, c.get('key') or '',
                                      value, c.get('name') or 'custom'))
        return out
    return REGISTERED_EXTRACTORS.get(kind)


def _extract(obj, path: List[str], key_path: str, value_path: str,
             fields: List[str], infos: Dict[str, ImageInfo],
             default_registry: str, registry_mutation: bool) -> None:
    """reference: image.go:51 extract"""
    if obj is None:
        return
    if fields and fields[0] == '*':
        if isinstance(obj, list):
            for i, v in enumerate(obj):
                _extract(v, path + [str(i)], key_path, value_path, fields[1:],
                         infos, default_registry, registry_mutation)
        elif isinstance(obj, dict):
            for k, v in obj.items():
                _extract(v, path + [k], key_path, value_path, fields[1:],
                         infos, default_registry, registry_mutation)
        else:
            raise ValueError('invalid type')
        return
    if not isinstance(obj, dict):
        raise ValueError('invalid image config')
    if not fields:
        pointer = '/' + '/'.join(path) + '/' + value_path
        key = pointer
        if key_path:
            key = obj.get(key_path)
            if not isinstance(key, str):
                raise ValueError('invalid key')
        value = obj.get(value_path)
        if not isinstance(value, str):
            raise ValueError('invalid value')
        infos[key] = get_image_info(value, default_registry,
                                    registry_mutation, pointer)
        return
    _extract(obj.get(fields[0]), path + [fields[0]], key_path, value_path,
             fields[1:], infos, default_registry, registry_mutation)


def extract_images_from_resource(resource: dict,
                                 configs: Optional[dict] = None,
                                 default_registry: str = 'docker.io',
                                 registry_mutation: bool = True
                                 ) -> Dict[str, Dict[str, ImageInfo]]:
    """reference: image.go:154 ExtractImagesFromResource — returns
    {extractor_name: {container_name_or_pointer: ImageInfo}}."""
    kind = resource.get('kind', '')
    extractors = _lookup_extractors(kind, configs)
    if extractors is not None and len(extractors) == 0:
        raise ValueError(f'no extractors found for {kind}')
    infos: Dict[str, Dict[str, ImageInfo]] = {}
    for extractor in extractors or []:
        sub: Dict[str, ImageInfo] = {}
        _extract(resource, [], extractor.key, extractor.value,
                 list(extractor.fields), sub, default_registry,
                 registry_mutation)
        if sub:
            infos.setdefault(extractor.name, {}).update(sub)
    return infos
