"""Kubernetes resource.Quantity parsing and comparison.

Re-implements the subset of k8s.io/apimachinery/pkg/api/resource used by the
reference's leaf pattern comparisons (reference: pkg/engine/pattern/pattern.go:239
compareQuantity) and JMESPath arithmetic (pkg/engine/jmespath/arithmetic.go).

Quantities are exact decimal numbers with an optional suffix:
  binary SI:  Ki Mi Gi Ti Pi Ei      (2**10 ..)
  decimal SI: n u m "" k M G T P E   (1e-9 ..)
  scientific: 12e6, 1.5E3

Internally represented as an exact ``fractions.Fraction`` so comparisons are
bit-exact like the reference's infinite-precision math.
"""

from __future__ import annotations

import re
from fractions import Fraction

_BINARY = {
    'Ki': 2 ** 10, 'Mi': 2 ** 20, 'Gi': 2 ** 30,
    'Ti': 2 ** 40, 'Pi': 2 ** 50, 'Ei': 2 ** 60,
}
_DECIMAL = {
    'n': Fraction(1, 10 ** 9), 'u': Fraction(1, 10 ** 6), 'm': Fraction(1, 1000),
    '': Fraction(1), 'k': Fraction(10 ** 3), 'M': Fraction(10 ** 6),
    'G': Fraction(10 ** 9), 'T': Fraction(10 ** 12), 'P': Fraction(10 ** 15),
    'E': Fraction(10 ** 18),
}

_DEC_EXP = {'n': -9, 'u': -6, 'm': -3, '': 0, 'k': 3, 'M': 6,
            'G': 9, 'T': 12, 'P': 15, 'E': 18}

_QTY_RE = re.compile(
    r'^(?P<sign>[+-]?)(?P<num>\d+(?:\.\d*)?|\.\d+)'
    r'(?P<suffix>(?:[eE][+-]?\d+)|(?:Ki|Mi|Gi|Ti|Pi|Ei)|[numkMGTPE]?)$'
)


def _fraction_scale(v: Fraction) -> int:
    """Decimal digits after the point of the exact value (denominators
    here are always 2^a·5^b products)."""
    d = v.denominator
    twos = 0
    while d % 2 == 0:
        d //= 2
        twos += 1
    fives = 0
    while d % 5 == 0:
        d //= 5
        fives += 1
    return max(twos, fives)


class Quantity:
    """An exact Kubernetes quantity."""

    __slots__ = ('value', 'suffix')

    def __init__(self, value: Fraction, suffix: str = ''):
        self.value = value
        self.suffix = suffix

    @classmethod
    def parse(cls, s: str) -> 'Quantity':
        if not isinstance(s, str):
            raise ValueError(f"cannot parse quantity from {type(s)}")
        s = s.strip()
        m = _QTY_RE.match(s)
        if not m:
            raise ValueError(f"unable to parse quantity's suffix: {s!r}")
        sign = -1 if m.group('sign') == '-' else 1
        num = Fraction(m.group('num'))
        suffix = m.group('suffix')
        if suffix and suffix[0] in 'eE':
            mult = Fraction(10) ** int(suffix[1:])
        elif suffix in _BINARY:
            mult = Fraction(_BINARY[suffix])
        elif suffix in _DECIMAL:
            mult = _DECIMAL[suffix]
        else:  # pragma: no cover - regex prevents this
            raise ValueError(f"unknown suffix {suffix!r}")
        return cls(sign * num * mult, suffix)

    def inf_scale(self) -> int:
        """``resource.Quantity.AsDec().Scale()`` of the Go reference:
        the int64Amount keeps (mantissa-digits, base-10 exponent), and
        AsDec is ``inf.NewDec(value, -scale)`` — so decimal suffixes
        yield NEGATIVE inf scales ('3G' → -9) and sub-unit forms
        positive ones ('100m' → 3).  Binary-suffix quantities parse to
        plain integers (scale from any fractional remainder only).
        Drives the QuoRound truncation scale of quantity division
        (reference: pkg/engine/jmespath/arithmetic.go:197)."""
        sfx = self.suffix
        if sfx in _BINARY:
            return _fraction_scale(self.value)
        if sfx and sfx[0] in 'eE':
            e = int(sfx[1:])
        else:
            e = _DEC_EXP[sfx]
        mantissa = self.value / Fraction(10) ** e
        return _fraction_scale(mantissa) - e

    def cmp(self, other: 'Quantity') -> int:
        if self.value < other.value:
            return -1
        if self.value > other.value:
            return 1
        return 0

    def __repr__(self):
        return f"Quantity({self.value}{self.suffix and ' ' + self.suffix})"

    def to_float(self) -> float:
        return float(self.value)


def parse_quantity(s: str) -> Quantity:
    return Quantity.parse(s)


def is_quantity(s: str) -> bool:
    try:
        Quantity.parse(s)
        return True
    except (ValueError, TypeError):
        return False
