"""Image reference parsing (reference: pkg/utils/image/infos.go).

Pure-Python equivalent of the distribution/reference parse the reference
relies on: splits a ref into registry / path / name / tag / digest with
the default-registry and default-tag rules.
"""

from __future__ import annotations

import re
from typing import Optional

DEFAULT_REGISTRY = 'docker.io'

_DIGEST_RE = re.compile(r'^[A-Za-z][A-Za-z0-9]*(?:[-_+.][A-Za-z][A-Za-z0-9]*)*:[0-9a-fA-F]{32,}$')
_TAG_RE = re.compile(r'^[\w][\w.-]{0,127}$')


class ImageInfo:
    """reference: pkg/utils/image/infos.go:15 ImageInfo (+ Pointer from
    pkg/utils/api/image.go:14)."""

    __slots__ = ('registry', 'name', 'path', 'tag', 'digest', 'pointer')

    def __init__(self, registry: str = '', name: str = '', path: str = '',
                 tag: str = '', digest: str = '', pointer: str = ''):
        self.registry = registry
        self.name = name
        self.path = path
        self.tag = tag
        self.digest = digest
        self.pointer = pointer

    def __str__(self) -> str:
        image = f'{self.registry}/{self.path}' if self.registry else self.path
        if self.digest:
            return f'{image}@{self.digest}'
        return f'{image}:{self.tag}'

    def reference_with_tag(self) -> str:
        image = f'{self.registry}/{self.path}' if self.registry else self.path
        return f'{image}:{self.tag}'

    def to_dict(self) -> dict:
        out = {'name': self.name, 'path': self.path}
        if self.registry:
            out['registry'] = self.registry
        if self.tag:
            out['tag'] = self.tag
        if self.digest:
            out['digest'] = self.digest
        return out


def _has_domain(name: str) -> bool:
    i = name.find('/')
    if i == -1:
        return False
    first = name[:i]
    return ('.' in first or ':' in first or first == 'localhost'
            or first.lower() != first)


def add_default_registry(name: str, default_registry: str = DEFAULT_REGISTRY,
                         ) -> str:
    """reference: infos.go:110 addDefaultRegistry"""
    if not _has_domain(name):
        name = f'{default_registry}/{name}'
    return name


def get_image_info(image: str,
                   default_registry: str = DEFAULT_REGISTRY,
                   enable_default_registry_mutation: bool = True,
                   pointer: str = '') -> ImageInfo:
    """reference: infos.go:54 GetImageInfo. Raises ValueError on a bad ref."""
    if not image or image != image.strip():
        raise ValueError(f'bad image: {image!r}')
    full = add_default_registry(image, default_registry)

    rest = full
    digest = ''
    at = rest.find('@')
    if at != -1:
        digest = rest[at + 1:]
        rest = rest[:at]
        if not _DIGEST_RE.match(digest):
            raise ValueError(f'bad image digest: {image!r}')

    tag = ''
    # the tag separator is a ':' after the last '/'
    last_slash = rest.rfind('/')
    colon = rest.rfind(':')
    if colon > last_slash:
        tag = rest[colon + 1:]
        rest = rest[:colon]
        if not _TAG_RE.match(tag):
            raise ValueError(f'bad image tag: {image!r}')

    slash = rest.find('/')
    registry, path = rest[:slash], rest[slash + 1:]
    if not path or any(not seg for seg in path.split('/')):
        raise ValueError(f'bad image: {image!r}')
    name = path.rsplit('/', 1)[-1]

    if not digest and not tag:
        tag = 'latest'
    if full != image and not enable_default_registry_mutation:
        registry = ''
    return ImageInfo(registry=registry, name=name, path=path, tag=tag,
                     digest=digest, pointer=pointer)


def image_matches(image: str, patterns: list) -> bool:
    """reference: pkg/engine/imageVerify.go:314 imageMatches"""
    from . import wildcard
    return any(wildcard.match(p, image) for p in patterns or [])
