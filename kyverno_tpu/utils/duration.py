"""Go-style duration parsing (time.ParseDuration semantics).

Used by leaf pattern comparisons (reference: pkg/engine/pattern/pattern.go:213
compareDuration) and the JMESPath time/arithmetic functions.

A duration string is a possibly signed sequence of decimal numbers, each with
optional fraction and a mandatory unit suffix, e.g. "300ms", "-1.5h", "2h45m".
Valid units: ns, us (or µs/μs), ms, s, m, h.  "0" is valid without a unit.
Returns integer nanoseconds.
"""

from __future__ import annotations

_UNITS = {
    'ns': 1,
    'us': 1000, 'µs': 1000, 'μs': 1000,
    'ms': 1000 * 1000,
    's': 1000 * 1000 * 1000,
    'm': 60 * 1000 * 1000 * 1000,
    'h': 3600 * 1000 * 1000 * 1000,
}


class DurationError(ValueError):
    pass


def parse_duration(s: str) -> int:
    """Parse a Go duration string to integer nanoseconds."""
    if not isinstance(s, str):
        raise DurationError(f"invalid duration {s!r}")
    orig = s
    neg = False
    if s and s[0] in '+-':
        neg = s[0] == '-'
        s = s[1:]
    if s == '0':
        return 0
    if not s:
        raise DurationError(f"invalid duration {orig!r}")
    total = 0
    while s:
        # leading digits (integer part)
        i = 0
        while i < len(s) and s[i].isdigit():
            i += 1
        int_part = s[:i]
        s = s[i:]
        frac_part = ''
        if s.startswith('.'):
            s = s[1:]
            j = 0
            while j < len(s) and s[j].isdigit():
                j += 1
            frac_part = s[:j]
            s = s[j:]
        if not int_part and not frac_part:
            raise DurationError(f"invalid duration {orig!r}")
        # unit: longest match first
        unit = None
        for u in ('ns', 'us', 'µs', 'μs', 'ms', 's', 'm', 'h'):
            if s.startswith(u):
                # 'm' must not shadow 'ms'; ordering above handles it since we
                # try two-char units first, but 's'/'m'/'h' are one char.
                unit = u
                break
        if unit is None:
            raise DurationError(f"missing unit in duration {orig!r}")
        s = s[len(unit):]
        scale = _UNITS[unit]
        v = int(int_part or '0') * scale
        if frac_part:
            v += int(round(float('0.' + frac_part) * scale))
        total += v
    return -total if neg else total


def is_duration(s: str) -> bool:
    try:
        parse_duration(s)
        return True
    except (DurationError, TypeError):
        return False


def format_duration(ns: int) -> str:
    """Format nanoseconds as a Go duration string (time.Duration.String)."""
    if ns == 0:
        return '0s'
    neg = ns < 0
    ns = abs(ns)
    out = ''
    if ns < 1000:
        out = f'{ns}ns'
    elif ns < 10 ** 6:
        out = _fmt_frac(ns, 1000, 'µs')
    elif ns < 10 ** 9:
        out = _fmt_frac(ns, 10 ** 6, 'ms')
    else:
        secs, rem = divmod(ns, 10 ** 9)
        h, secs = divmod(secs, 3600)
        m, secs = divmod(secs, 60)
        out = ''
        if h:
            out += f'{h}h'
        if h or m:
            out += f'{m}m'
        out += _fmt_frac(secs * 10 ** 9 + rem, 10 ** 9, 's')
    return ('-' + out) if neg else out


def _fmt_frac(value: int, scale: int, unit: str) -> str:
    whole, frac = divmod(value, scale)
    if frac == 0:
        return f'{whole}{unit}'
    fs = str(frac).rjust(len(str(scale)) - 1, '0').rstrip('0')
    return f'{whole}.{fs}{unit}'
