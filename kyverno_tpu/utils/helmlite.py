"""Minimal Helm-template renderer for the kyverno-policies chart.

Renders /root/reference/charts/kyverno-policies/templates/{baseline,
restricted} (reference layout) with the chart's default values — enough
of Go template semantics for that chart: ``{{- if/with/else/end }}``
blocks, backtick-escaped literals (``{{`{{ ... }}`}}`` — how the chart
embeds kyverno variables), and the handful of ``.Values`` pipelines the
templates use.  Not a general Helm implementation.
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, Optional

DEFAULT_VALUES: Dict[str, Any] = {
    # chart defaults (reference: charts/kyverno-policies/values.yaml)
    'podSecurityStandard': 'baseline',
    'podSecuritySeverity': 'medium',
    'podSecurityPolicies': [],
    'includeOtherPolicies': [],
    'includeRestrictedPolicies': [],
    'failurePolicy': 'Fail',
    'validationFailureAction': 'audit',
    'validationFailureActionByPolicy': {},
    'validationFailureActionOverrides': {'all': []},
    'policyExclude': {},
    'policyPreconditions': {},
    'autogenControllers': '',
    'background': True,
    'customLabels': {},
}

_ESCAPED = re.compile(r'\{\{`(.*?)`\}\}', re.DOTALL)
_ACTION = re.compile(r'\{\{-?\s*(.*?)\s*-?\}\}')


def render(text: str, name: str, values: Optional[Dict[str, Any]] = None,
           restricted: bool = False) -> str:
    vals = dict(DEFAULT_VALUES)
    if restricted:
        vals['podSecurityStandard'] = 'restricted'
    if values:
        vals.update(values)
    # protect backtick-escaped literals before template processing
    protected: List[str] = []

    def keep(m: re.Match) -> str:
        protected.append(m.group(1))
        return f'\x00{len(protected) - 1}\x00'

    text = _ESCAPED.sub(keep, text)
    lines = text.split('\n')
    out: List[str] = []
    _render_block(lines, 0, len(lines), out, vals, name, emit=True)
    result = '\n'.join(out)
    return re.sub(r'\x00(\d+)\x00',
                  lambda m: protected[int(m.group(1))], result)


def _directive(line: str) -> Optional[str]:
    s = line.strip()
    m = _ACTION.fullmatch(s)
    return m.group(1).strip() if m else None


def _render_block(lines: List[str], i: int, end: int, out: List[str],
                  vals: Dict[str, Any], name: str, emit: bool) -> int:
    """Render lines[i:end]; returns the index after the consumed block."""
    while i < end:
        line = lines[i]
        d = _directive(line)
        if d is None:
            if emit:
                rendered = _subst(line, vals, name)
                if rendered is not None:
                    out.append(rendered)
            i += 1
            continue
        if d.startswith('$') and ':=' in d:  # {{- $name := "..." }}
            i += 1
            continue
        if d.startswith('include'):
            i += 1
            continue
        if d.startswith('if ') or d.startswith('with '):
            cond = _truthy(d.split(' ', 1)[1], vals, name)
            # find matching else/end at this nesting level
            j, else_at = i + 1, None
            depth = 0
            while j < end:
                dj = _directive(lines[j])
                if dj is not None:
                    if dj.startswith(('if ', 'with ', 'range ')):
                        depth += 1
                    elif dj == 'end':
                        if depth == 0:
                            break
                        depth -= 1
                    elif dj == 'else' and depth == 0:
                        else_at = j
                j += 1
            body_end = else_at if else_at is not None else j
            _render_block(lines, i + 1, body_end, out, vals, name,
                          emit and bool(cond))
            if else_at is not None:
                _render_block(lines, else_at + 1, j, out, vals, name,
                              emit and not cond)
            i = j + 1
            continue
        if d in ('end', 'else'):
            i += 1
            continue
        i += 1  # unknown standalone directive: drop
    return i


def _lookup(expr: str, vals: Dict[str, Any], name: str) -> Any:
    expr = expr.strip()
    if expr.startswith('.Values.'):
        cur: Any = vals
        for part in expr[len('.Values.'):].split('.'):
            if not isinstance(cur, dict):
                return None
            cur = cur.get(part)
        return cur
    m = re.fullmatch(r'index \.Values "([^"]+)"(?: \$name)?', expr)
    if m:
        v = vals.get(m.group(1))
        if expr.endswith('$name') and isinstance(v, dict):
            return v.get(name)
        return v
    if expr == '$name':
        return name
    return None


def _truthy(expr: str, vals: Dict[str, Any], name: str) -> bool:
    expr = expr.strip()
    if expr.startswith('eq (include "kyverno-policies.podSecurity'):
        return True  # policy enabled under the selected standard
    if expr.startswith('include'):
        return True
    m = re.fullmatch(r'concat \(index \.Values "([^"]+)" "all"\).*', expr)
    if m:
        return bool((vals.get(m.group(1)) or {}).get('all'))
    v = _lookup(expr, vals, name)
    return bool(v)


def _subst(line: str, vals: Dict[str, Any], name: str) -> Optional[str]:
    def repl(m: re.Match) -> str:
        expr = m.group(1).strip()
        if expr == '$name':
            return name
        if expr == '.':
            return ''  # {{ . }} inside with-blocks: dropped with the block
        if expr.startswith('include "kyverno-policies.labels"'):
            return "{'app.kubernetes.io/part-of': kyverno-policies}"
        expr = expr.split('|')[0].strip()
        if expr.startswith('toYaml '):
            expr = expr[len('toYaml '):].strip()
        v = _lookup(expr, vals, name)
        if v is None:
            return ''
        if isinstance(v, bool):
            return 'true' if v else 'false'
        return str(v)

    return _ACTION.sub(repl, line)


def load_chart_policies(chart_dir: str, profiles=('baseline',),
                        values: Optional[Dict[str, Any]] = None) -> List[dict]:
    """Render and parse the kyverno-policies chart templates."""
    import os
    import yaml
    out: List[dict] = []
    for profile in profiles:
        tdir = os.path.join(chart_dir, 'templates', profile)
        for fn in sorted(os.listdir(tdir)):
            if not fn.endswith('.yaml'):
                continue
            name = fn[:-len('.yaml')]
            text = open(os.path.join(tdir, fn)).read()
            rendered = render(text, name, values,
                              restricted=(profile == 'restricted'))
            for doc in yaml.safe_load_all(rendered):
                if doc and doc.get('kind') in ('ClusterPolicy', 'Policy'):
                    out.append(doc)
    return out
