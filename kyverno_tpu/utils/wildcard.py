"""Glob wildcard matching.

Semantics match the reference's wildcard helper (reference:
pkg/utils/wildcard/wildcard.go, IGLOU-EU/go-wildcard): ``*`` matches any
sequence of characters (including empty), ``?`` matches exactly one
character.  An empty pattern matches only the empty string.
"""

from __future__ import annotations

from functools import lru_cache


def match(pattern: str, name: str) -> bool:
    """Return True if ``name`` matches glob ``pattern``."""
    return _match_impl(pattern, name)


@lru_cache(maxsize=65536)
def _match_impl(pattern: str, name: str) -> bool:
    # Iterative two-pointer glob matcher with backtracking on '*'.
    p = n = 0
    star = -1  # index in pattern of last '*'
    mark = 0   # index in name to resume from after backtrack
    lp, ln = len(pattern), len(name)
    while n < ln:
        if p < lp and (pattern[p] == '?' or pattern[p] == name[n]):
            p += 1
            n += 1
        elif p < lp and pattern[p] == '*':
            star = p
            mark = n
            p += 1
        elif star != -1:
            p = star + 1
            mark += 1
            n = mark
        else:
            return False
    while p < lp and pattern[p] == '*':
        p += 1
    return p == lp


def contains_wildcard(s: str) -> bool:
    return '*' in s or '?' in s


def check_patterns(patterns: list[str], key: str) -> bool:
    """True if key matches any pattern in the list."""
    return any(match(p, key) for p in patterns)
