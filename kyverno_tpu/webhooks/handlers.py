"""Resource admission handlers + middleware chain.

The serving pipeline mirrors the reference's handler composition
(reference: pkg/webhooks/handlers/*.go, pkg/webhooks/resource/handlers.go):
``with_admission`` decodes/encodes AdmissionReview JSON, ``with_filter``
drops config-excluded resources, ``with_protection`` denies edits to
kyverno-managed resources, ``with_dump`` keeps a debug ring buffer; the
terminal handlers run the engine over the policy cache.
"""

from __future__ import annotations

import collections
import json
import threading
import time
from typing import Any, Callable, Dict, List, Optional

import yaml

from ..api.unstructured import Resource
from ..engine.api import EngineResponse, RuleStatus
from ..engine.engine import Engine
from ..engine.match import matches_resource_description
from ..policycache import cache as pcache
from . import admission

Handler = Callable[[dict], dict]  # AdmissionRequest -> AdmissionResponse

SCANNER_HOT_SWAPS = 'kyverno_tpu_scanner_hot_swaps_total'
BREAKER_MIGRATIONS = 'kyverno_tpu_breaker_migrations_total'


# ---------------------------------------------------------------------------
# block / warning assembly (reference: pkg/webhooks/utils/block.go,
# warning.go; pkg/utils/engine/response.go:21)

def block_request(responses: List[EngineResponse],
                  failure_policy: str) -> bool:
    for er in responses:
        if er.is_failed() and _enforce(er):
            return True
        if er.is_error() and failure_policy == 'Fail':
            return True
    return False


def _enforce(er: EngineResponse) -> bool:
    action = er.get_validation_failure_action()
    return str(action).lower() == 'enforce'


import re as _re

_PLAIN_SCALAR_RE = _re.compile(r'^[A-Za-z0-9][A-Za-z0-9 _./()\[\]-]*$')
_NUMBERISH_RE = _re.compile(r'^[+-]?[0-9][0-9_.eE+-]*$')


def _yaml_scalar(s: str) -> str:
    """Block-style scalar: plain when unambiguous, single-quoted
    otherwise.  PyYAML's emitter costs ~0.5ms per rule message
    (analyze_scalar); deny messages at 1k policies made it the single
    largest admission-latency term, so the common map-of-strings shape
    is emitted directly."""
    if _PLAIN_SCALAR_RE.match(s) and not s.endswith(' ') and \
            not _NUMBERISH_RE.match(s) and \
            s.lower() not in ('null', 'true', 'false', 'yes', 'no', 'on',
                              'off'):
        return s
    return "'" + s.replace("'", "''") + "'"


_CTRL_CHAR_RE = _re.compile(r'[\x00-\x1f]')


def _dump_failures(failures: Dict[str, Dict[str, str]]) -> str:
    # multi-line / control-character scalars need real YAML escaping —
    # rare enough that the slow emitter handles the whole map then
    search = _CTRL_CHAR_RE.search
    for rules in failures.values():
        for k, v in rules.items():
            if search(k) or search(v):
                return yaml.safe_dump(failures, default_flow_style=False)
    lines = []
    for pol in sorted(failures):
        lines.append(f'{_yaml_scalar(pol)}:')
        rules = failures[pol]
        for rule in sorted(rules):
            lines.append(f'  {_yaml_scalar(rule)}: {_yaml_scalar(rules[rule])}')
    return '\n'.join(lines) + '\n'


def get_blocked_messages(responses: List[EngineResponse]) -> str:
    """reference: pkg/webhooks/utils/block.go:38 GetBlockedMessages"""
    if not responses:
        return ''
    failures: Dict[str, Dict[str, str]] = {}
    has_violations = False
    for er in responses:
        rule_to_reason: Dict[str, str] = {}
        for rule in er.policy_response.rules:
            if rule.status != RuleStatus.PASS:
                rule_to_reason[rule.name] = rule.message
                if rule.status == RuleStatus.FAIL:
                    has_violations = True
        if rule_to_reason:
            failures[er.policy_response.policy_name] = rule_to_reason
    if not failures:
        return ''
    pr = responses[0].policy_response
    resource_name = f'{pr.resource_kind}/{pr.resource_namespace}/' \
                    f'{pr.resource_name}'
    action = 'violation' if has_violations else 'error'
    if len(failures) > 1:
        action += 's'
    results = _dump_failures(failures)
    return f'\n\npolicy {resource_name} for resource {action}: ' \
           f'\n\n{results}'


def get_warning_messages(responses: List[EngineResponse]) -> List[str]:
    """reference: pkg/webhooks/utils/warning.go:9 GetWarningMessages"""
    warnings = []
    for er in responses:
        for rule in er.policy_response.rules:
            if rule.status not in (RuleStatus.PASS, RuleStatus.SKIP):
                warnings.append(
                    f'policy {er.policy_response.policy_name}.{rule.name}: '
                    f'{rule.message}')
    return warnings


# ---------------------------------------------------------------------------
# middleware (reference: pkg/webhooks/handlers/{filter,protect,dump}.go)

def with_filter(configuration, inner: Handler) -> Handler:
    """Skip resources excluded by the dynamic configuration
    (reference: pkg/webhooks/handlers/filter.go)."""
    def handler(request: dict) -> dict:
        if configuration is not None:
            kind = (request.get('kind') or {}).get('kind', '')
            ns = request.get('namespace', '')
            name = request.get('name', '') or \
                Resource(admission.request_resource(request)).name
            if configuration.to_filter(kind, ns, name):
                return admission.response(request.get('uid', ''), True)
        return inner(request)
    return handler


def with_protection(enabled: bool, inner: Handler) -> Handler:
    """Deny user modifications of kyverno-managed resources
    (reference: pkg/webhooks/handlers/protect.go)."""
    def handler(request: dict) -> dict:
        if enabled:
            new = admission.request_resource(request)
            old = admission.request_old_resource(request)
            for obj in (new, old):
                labels = (obj.get('metadata') or {}).get('labels') or {}
                if labels.get('app.kubernetes.io/managed-by') == 'kyverno':
                    username = (request.get('userInfo') or {}).get(
                        'username', '')
                    if not username.startswith(
                            'system:serviceaccount:kyverno:'):
                        return admission.response(
                            request.get('uid', ''), False,
                            'A kyverno managed resource can only be '
                            'modified by kyverno')
        return inner(request)
    return handler


class DumpBuffer:
    """Debug payload ring buffer (reference: handlers/dump.go)."""

    def __init__(self, size: int = 20):
        self._items = collections.deque(maxlen=size)
        self._lock = threading.Lock()

    def add(self, item: dict) -> None:
        with self._lock:
            self._items.append(item)

    def items(self) -> List[dict]:
        with self._lock:
            return list(self._items)


def with_dump(buffer: Optional[DumpBuffer], inner: Handler) -> Handler:
    def handler(request: dict) -> dict:
        resp = inner(request)
        if buffer is not None:
            buffer.add({'request': {
                'uid': request.get('uid'),
                'kind': request.get('kind'),
                'namespace': request.get('namespace'),
                'name': request.get('name'),
                'operation': request.get('operation'),
            }, 'response': {k: v for k, v in resp.items() if k != 'patch'},
                'timestamp': time.time()})
        return resp
    return handler


def with_admission(inner: Handler) -> Callable[[bytes], bytes]:
    """AdmissionReview JSON decode/encode wrapper
    (reference: pkg/webhooks/handlers/admission.go:18)."""
    def handler(body: bytes) -> bytes:
        review = json.loads(body)
        request = admission.parse_review(review)
        resp = inner(request)
        return json.dumps(
            admission.review_response(request, resp)).encode('utf-8')
    return handler


# ---------------------------------------------------------------------------
# resource handlers (reference: pkg/webhooks/resource/handlers.go)

class ResourceHandlers:
    """Terminal Validate / Mutate admission handlers.

    ``audit_sink`` receives (request, responses) for async audit-report
    construction; ``ur_sink`` receives UpdateRequest specs spawned for
    generate / mutate-existing policies (reference: handlers.go:146-155).
    """

    # consecutive device-scan failures before the set's circuit
    # breaker opens and the host loop serves it for an exponential
    # backoff window (each failure already pays a scanner rebuild; a
    # persistently broken backend must not recompile the policy set on
    # every request).  A half-open probe after the backoff decides
    # between recovery and a re-trip (serving/breaker.py)
    DEVICE_FAILURE_LIMIT = 3
    # ceiling on simultaneous background scanner compiles (jax trace +
    # XLA compile are memory-heavy; a burst across many policy sets
    # serves the host loop rather than forking a compile per set)
    MAX_CONCURRENT_BUILDS = 2
    # distinct policy sets whose breakers are simultaneously open
    # before the failure is treated as systemic and the device path
    # disables globally
    GLOBAL_DEAD_LIMIT = 3

    def __init__(self, cache: 'pcache.Cache', engine: Optional[Engine] = None,
                 pc_builder: Optional[admission.PolicyContextBuilder] = None,
                 configuration=None,
                 namespace_labels: Optional[Callable[[str], dict]] = None,
                 audit_sink: Optional[Callable] = None,
                 ur_sink: Optional[Callable] = None,
                 event_sink: Optional[Callable] = None,
                 registry_client=None,
                 device: bool = True,
                 openapi_manager=None,
                 client=None,
                 serving_mode: Optional[str] = None):
        if openapi_manager is None:
            from ..openapi.manager import Manager
            openapi_manager = Manager()
        self.openapi_manager = openapi_manager
        self.cache = cache
        if engine is None and client is not None:
            # wire the engine's context loaders (ConfigMap resolution +
            # APICall urlPath entries) to the cluster client the daemon
            # serves (reference: cmd/kyverno/main.go engine construction
            # → pkg/engine/jsonContext.go:23 ContextLoaderFactory)
            from ..engine.apicall import make_context_loader
            engine = Engine(context_loader=make_context_loader(
                dclient=client, registry_client=registry_client))
        self.engine = engine or Engine()
        if pc_builder is None and client is not None:
            # short-TTL cache: the reference serves exceptions from an
            # informer cache — per-request LIST round trips would hammer
            # the API server under admission load
            _exc_cache = {'at': 0.0, 'items': []}

            def _list_exceptions():
                now = time.time()
                if now - _exc_cache['at'] > 1.0:
                    out = []
                    for api_version in ('kyverno.io/v2alpha1',
                                        'kyverno.io/v2beta1'):
                        try:
                            out += client.list_resource(
                                api_version, 'PolicyException')
                        except Exception:  # noqa: BLE001
                            pass
                    _exc_cache['items'] = out
                    _exc_cache['at'] = now
                return _exc_cache['items']
            pc_builder = admission.PolicyContextBuilder(
                configuration, exception_lister=_list_exceptions)
        self.pc_builder = pc_builder or admission.PolicyContextBuilder(
            configuration)
        self.configuration = configuration
        if namespace_labels is None and client is not None:
            # namespaceSelector match needs the live namespace's labels
            # (reference: pkg/utils/kube GetNamespaceSelectorsFromNamespaceLister
            # wired through the resource handlers)
            namespace_labels = client.get_namespace_labels
        self.namespace_labels = namespace_labels or (lambda ns: {})
        self.audit_sink = audit_sink
        self.ur_sink = ur_sink
        self.event_sink = event_sink
        self.registry_client = registry_client
        # the compiled device evaluator handles enforce validation for
        # CREATE/UPDATE requests; rebuilt when the cached policy set
        # changes
        self.device = device
        self._scanner_lock = threading.Lock()
        # LRU of compiled scanners keyed per (kind, policy set): a
        # policy set can compile both a validate BatchScanner and a
        # mutate MutateScanner, and admission traffic alternating
        # kinds/namespaces yields different policy lists which must not
        # rebuild (compile!) per request
        self._scanners: 'collections.OrderedDict[tuple, Any]' = \
            collections.OrderedDict()
        self._scanners_max = 8
        # (namespace, name) identity sets per cached scanner key: policy
        # churn replaces the Policy OBJECTS (so the id()-tuple key never
        # matches), but the logical set persists — the hot-swap
        # predecessor search matches on identity overlap
        self._scanner_ident: Dict[tuple, frozenset] = {}
        self._building: set = set()
        # per-policy-set circuit breakers (serving/breaker.py): a set
        # that keeps failing (build or scan) opens and serves the host
        # loop for an exponential backoff window, then a single
        # half-open probe decides between recovery — the set is
        # re-admitted to the device path — and a re-trip with doubled
        # backoff.  Per key, so one broken set cannot disable (nor
        # reset the counter of) a healthy one; entries pin their
        # policy objects (keys are id() tuples, so CPython id reuse
        # must not circuit-break a healthy set) and the registry is
        # size-bounded with counted evictions.  When several distinct
        # sets are open at once the failure is systemic (broken
        # backend): _breaker_opened turns the global device switch off
        # so policy churn cannot spawn an endless stream of doomed
        # compiles.
        from ..serving.breaker import BreakerRegistry
        self._breakers = BreakerRegistry(
            failure_limit=self.DEVICE_FAILURE_LIMIT,
            on_open=self._breaker_opened)
        # admission serving mode: 'batch' routes CREATE/UPDATE-path
        # validate AND mutate scans through the micro-batching scheduler
        # (serving/), 'sync' keeps the per-request dispatch
        import os as _os
        self.serving_mode = serving_mode or \
            _os.environ.get('KTPU_SERVING', 'sync')
        # device-side mutate (kyverno_tpu/mutate/): lowered strategic-
        # merge / json6902 policy sets serve the admission mutate chain
        # as batched device dispatches; 0 keeps every mutate request on
        # the host engine loop (the bit-identity oracle)
        self.mutate_device = _os.environ.get(
            'KTPU_MUTATE_DEVICE', '1') not in ('0', 'false', 'off')
        self._batcher = None
        self._batcher_lock = threading.Lock()

    @staticmethod
    def _policy_key(policies):
        return tuple(id(p) for p in policies)

    def _device_scanner(self, policies, kind: str = 'validate'):
        """Scanner for ``policies``, or None while one is still compiling.

        ``kind`` selects the program: ``validate`` builds a
        ``BatchScanner``, ``mutate`` a ``MutateScanner`` (a mutate set
        that does not lower is cached too — callers check ``.ok`` — so
        the lowering never re-runs per request).  Building pays jax
        trace + XLA compile (seconds to minutes on a policy-set change);
        doing that on the request path would blow the webhook timeout
        (reference: 10s cap, spec_types.go:95).  The build runs on a
        background thread and requests serve the host engine loop —
        identical verdicts — until the compiled path is ready.  The
        circuit breaker is keyed per policy set (kindless): a backend
        broken for one program kind is broken for the other."""
        from ..observability import coverage
        from ..serving import breaker as breaker_mod
        base = self._policy_key(policies)
        key = (kind,) + base
        decision = self._breakers.allow(base)
        if decision == breaker_mod.OPEN:
            # circuit open: host loop serves until the backoff elapses
            # (or this window's single probe is already in flight)
            coverage.record_fallback('serving',
                                     coverage.REASON_BREAKER_OPEN)
            return None
        with self._scanner_lock:
            scanner = self._scanners.get(key)
            if scanner is not None:
                self._scanners.move_to_end(key)
                # a PROBE grant rides this scanner: the caller's scan
                # outcome reaches record_success/_record_key_failure
                # downstream and resolves the half-open window
                return scanner
            if key in self._building:
                if decision == breaker_mod.PROBE:
                    # the probe cannot scan until the rebuild lands;
                    # free the slot so the next window re-probes
                    self._breakers.probe_abort(base)
                return None  # still compiling; host loop serves meanwhile
            if len(self._building) >= self.MAX_CONCURRENT_BUILDS:
                # a compile burst across many policy sets must not fork
                # unbounded trace+compile threads; later requests retry
                if decision == breaker_mod.PROBE:
                    self._breakers.probe_abort(base)
                return None
            self._building.add(key)

        def build():
            try:
                if kind == 'mutate':
                    from ..mutate import MutateScanner
                    scanner = MutateScanner(policies, engine=self.engine)
                    if scanner.ok:
                        scanner.warmup()
                else:
                    from ..compiler.scan import BatchScanner
                    scanner = BatchScanner(policies, engine=self.engine)
                    # pre-warm the small-batch shape an admission request
                    # hits (AOT-loads from the persistent executable store
                    # when a prior process already compiled this set)
                    scanner.warmup()
                self._install_scanner(key, base, kind, policies,
                                      scanner)
            except Exception as e:  # noqa: BLE001
                # a policy set that cannot compile must trip the circuit
                # breaker, or every request re-spawns a doomed
                # multi-second compile
                self._record_key_failure(base, policies,
                                         f'build failed ({kind}): {e}')
            finally:
                with self._scanner_lock:
                    self._building.discard(key)
        threading.Thread(target=build, name='ktpu-scanner-build',
                         daemon=True).start()
        if decision == breaker_mod.PROBE:
            # the probe's real verdict is the rebuild just spawned: a
            # build failure re-trips via _record_key_failure; success
            # caches the scanner for the next probe to ride.  Either
            # way this caller serves the host loop now, so the slot
            # frees for the next window
            self._breakers.probe_abort(base)
        return None

    def _install_scanner(self, key: tuple, base: tuple, kind: str,
                         policies, scanner) -> None:
        """Insert a freshly built scanner, hot-swapping any live
        predecessor serving the same logical policy set.

        Policy churn replaces the Policy objects, so the successor's
        id()-tuple key never matches the predecessor's — the logical
        set is matched by (namespace, name) identity overlap instead.
        The swap is atomic under the scanner lock AFTER the successor
        is fully built and warmed: requests keep riding the predecessor
        (or the host loop, with identical verdicts) until the flip, so
        a churn event never sheds and never 500s.  In-flight batches
        hold direct references to the predecessor and drain naturally.
        Breaker state migrates to the successor's key instead of
        resetting to closed — a backend fault that tripped the old
        serial must not be forgiven by recompiling the policy set."""
        from ..observability.metrics import global_registry
        ident = frozenset((p.namespace, p.name) for p in policies)
        swapped = None
        with self._scanner_lock:
            best, best_ratio = None, 0.0
            for k in self._scanners:
                if k[0] != key[0] or k == key:
                    continue
                prev = self._scanner_ident.get(k)
                if not prev:
                    continue
                ratio = len(ident & prev) / max(len(ident), len(prev), 1)
                if ratio > best_ratio:
                    best, best_ratio = k, ratio
            if best is not None and best_ratio >= 0.5:
                old = self._scanners.pop(best)
                self._scanner_ident.pop(best, None)
                state = self._breakers.migrate(best[1:], base,
                                               policies=policies)
                swapped = (old, state)
            while len(self._scanners) >= self._scanners_max:
                evicted, _ = self._scanners.popitem(last=False)
                self._scanner_ident.pop(evicted, None)
            self._scanners[key] = scanner
            self._scanner_ident[key] = ident
        if swapped is None:
            return
        old, state = swapped
        reg = global_registry()
        if reg is not None:
            reg.inc(SCANNER_HOT_SWAPS, kind=kind)
            reg.inc(BREAKER_MIGRATIONS)
        touched = None
        old_pset = getattr(old, '_pset', None)
        new_pset = getattr(scanner, '_pset', None)
        if old_pset is not None and new_pset is not None:
            from ..partition.plan import diff_plans
            touched = diff_plans(old_pset.plan, new_pset.plan).touched
        from ..partition import census as partition_census
        partition_census.record_swap(
            kind, getattr(old, 'serial', None),
            getattr(scanner, 'serial', None),
            breaker_state=state, touched=touched)
        import logging
        from ..observability.logging import with_values
        with_values(logging.getLogger('kyverno.webhooks'),
                    'scanner hot-swap', kind=kind,
                    old_serial=getattr(old, 'serial', None),
                    new_serial=getattr(scanner, 'serial', None),
                    breaker_state=state)

    def _record_key_failure(self, key: tuple, policies, reason: str) -> None:
        import logging
        from ..observability.logging import with_values
        from ..serving import breaker as breaker_mod
        log = logging.getLogger('kyverno.webhooks')
        state = self._breakers.record_failure(key, policies, reason)
        with_values(log, 'device path failure', level=logging.ERROR,
                    error=reason, breaker_state=state)
        if state == breaker_mod.OPEN:
            with_values(log, 'circuit open: policy set quarantined to '
                        'the host loop until the backoff elapses',
                        level=logging.ERROR)

    def _breaker_opened(self, open_count: int) -> None:
        """BreakerRegistry trip callback: several distinct policy sets
        open at once means the backend itself is broken — flip the
        global device switch off so churn cannot spawn an endless
        stream of doomed compiles (individual breakers still recover
        per set if the operator re-enables the device path)."""
        if open_count >= self.GLOBAL_DEAD_LIMIT and self.device:
            import logging
            from ..observability.logging import with_values
            self.device = False
            with_values(logging.getLogger('kyverno.webhooks'),
                        'device path disabled globally: multiple '
                        'policy sets failing (systemic backend failure)',
                        level=logging.ERROR)

    def wait_device_ready(self, policies, timeout: float = 600.0) -> bool:
        """Block until the compiled scanner for ``policies`` is serving
        (benchmarks / tests measuring steady-state latency).  Returns
        False immediately while the set's circuit breaker is open."""
        from ..serving import breaker as breaker_mod
        key = self._policy_key(policies)
        deadline = time.time() + timeout
        while time.time() < deadline:
            if not self.device:
                return False
            if self._breakers.state(key) == breaker_mod.OPEN:
                return False
            if self._device_scanner(policies) is not None:
                # readiness polling never scans: release any half-open
                # probe slot the allow() check granted on our behalf
                self._breakers.probe_abort(key)
                return True
            time.sleep(0.05)
        return False

    # -- admission micro-batching (serving/) -------------------------------

    def _get_batcher(self):
        batcher = self._batcher
        if batcher is None:
            with self._batcher_lock:
                batcher = self._batcher
                if batcher is None:
                    from ..serving.batcher import AdmissionBatcher
                    batcher = AdmissionBatcher(
                        on_success=self._batch_scan_ok,
                        on_failure=self._batch_scan_failed)
                    self._batcher = batcher
        return batcher

    def _batch_scan_ok(self, policies) -> None:
        # mirror of the sync path's success bookkeeping: a successful
        # dispatch closes the set's breaker (half-open probe recovery)
        # or forgets its consecutive-failure count
        self._breakers.record_success(self._policy_key(policies))

    def _batch_scan_failed(self, policies, error) -> None:
        # mirror of the sync path's failure recovery: drop the broken
        # scanner so the next request rebuilds it, and count one breaker
        # failure for the set (the whole batch sheds on one dispatch, so
        # a broken backend trips the breaker per dispatch, not per
        # rider).  Both program kinds are dropped — the callback only
        # knows the policy set, and a rebuild of the innocent kind is
        # cheap next to a broken backend
        base = self._policy_key(policies)
        with self._scanner_lock:
            for kind in ('validate', 'mutate'):
                self._scanners.pop((kind,) + base, None)
        self._record_key_failure(
            base, policies,
            f'batched scan failed, shedding to host engine: {error}')

    def _batched_scan(self, scanner, policies, request, pctx,
                      old_resource: Optional[dict] = None,
                      resource: Optional[dict] = None):
        """Route one validate or mutate scan through the micro-batcher.

        The ticket key is the scanner's monotonic serial alone:
        validate and mutate compile distinct scanners so those
        dispatches never mix, while distinct users, roles, namespaces
        AND verbs coalesce — each rider's admission tuple rides to the
        scanner as a per-row column (compiler/admission.py), so a
        shared dispatch stays bit-identical to every request's own
        sync scan.  Returns ``(responses, prov)``: this request's result
        rows (None when the request shed to the host engine loop —
        queue full, deadline blown, dispatch failed, or batcher stopped
        — the caller then serves the identical-verdict host path, never
        a 500) and the decision-provenance fields of whatever happened:
        ``path`` is ``batch`` with the batcher-filled batch id /
        occupancy / amortized device share on success, or
        ``shed:<reason>`` with the time spent waiting otherwise."""
        import time as _time
        from ..serving import shed as shed_policy
        from ..serving.queue import QueueFull, Stopped
        batcher = self._get_batcher()
        if resource is None:
            resource = admission.request_resource(request)
        adm = (pctx.admission_info, pctx.exclude_group_roles,
               pctx.namespace_labels, request.get('operation') or 'CREATE')
        try:
            ticket = batcher.submit(
                resource=resource, context=pctx.json_context._data,
                pctx=pctx, admission=adm, scanner=scanner,
                policies=policies, old_resource=old_resource)
        except QueueFull:
            batcher.record_shed(shed_policy.REASON_QUEUE_FULL)
            return None, {'path':
                          f'shed:{shed_policy.REASON_QUEUE_FULL}'}
        except Stopped:
            batcher.record_shed(shed_policy.REASON_SHUTDOWN)
            return None, {'path': f'shed:{shed_policy.REASON_SHUTDOWN}'}
        deadline_s = batcher.shed_deadline_s
        ts = request.get('timeoutSeconds')
        if ts:
            # the API server aborts the whole call at the webhook's own
            # timeoutSeconds (reference: spec_types.go:95): shed at half
            # that budget so the host-loop fallback still fits in the
            # remainder, never loosening the KTPU_SHED_DEADLINE_MS cap
            try:
                deadline_s = min(deadline_s, max(0.01, float(ts) / 2.0))
            except (TypeError, ValueError):
                pass
        responses = ticket.wait(deadline_s)
        if responses is None:
            reason = ticket.shed_reason or shed_policy.REASON_DEADLINE
            return None, {
                'path': f'shed:{reason}',
                'queue_wait_s': _time.monotonic() - ticket.enqueued_at}
        prov = dict(ticket.prov) if ticket.prov is not None else {}
        prov['path'] = 'batch'
        return responses, prov

    def shutdown(self) -> None:
        """Drain and stop the admission batcher: pending futures get
        their batched responses before the process exits (wired through
        WebhookServer.stop and cmd/internal.Setup shutdown hooks)."""
        batcher = self._batcher
        if batcher is not None:
            batcher.stop(drain=True)

    # -- validate ---------------------------------------------------------

    def validate(self, request: dict,
                 failure_policy: str = 'Fail') -> dict:
        """reference: pkg/webhooks/resource/handlers.go:110 Validate"""
        uid = request.get('uid', '')
        kind = (request.get('kind') or {}).get('kind', '')
        ns = request.get('namespace', '')
        policies = self.cache.get_policies(pcache.VALIDATE_ENFORCE, kind, ns)
        generate_policies = self.cache.get_policies(pcache.GENERATE, kind, ns)
        from ..observability import provenance
        from ..observability import slo
        prov_on = provenance.enabled()
        slo_on = slo.enabled()
        t_start = time.monotonic() if (prov_on or slo_on) else 0.0
        # decision provenance: which serving path answered this request
        # (batch | sync | shed:<reason> | host_fallback) plus the
        # batch/cache attribution that path produced
        prov_path = 'host_fallback'
        prov_extra: Dict[str, Any] = {}
        try:
            pctx = self.pc_builder.build(request)
        except Exception as e:  # noqa: BLE001
            if prov_on or slo_on:
                duration_s = time.monotonic() - t_start
                slo.record('host_fallback', duration_s)
                if prov_on:
                    provenance.record_decision(
                        path='host_fallback', uid=uid, kind=kind,
                        namespace=ns,
                        name=request.get('name', '') or '',
                        operation=request.get('operation', '') or '',
                        duration_s=duration_s,
                        error=f'policy context build failed: {e}')
            return admission.response(uid, False,
                                      f'failed to build policy context: {e}')
        pctx.namespace_labels = self.namespace_labels(ns)

        responses: List[EngineResponse] = []
        # device fast path: CREATE and UPDATE requests with no policy
        # exceptions run through the compiled batch evaluator (exact via
        # host fallback); UPDATE rows carry oldObject for the scanner's
        # old-match retry; DELETE keeps the engine loop (no new object)
        operation = request.get('operation') or ''
        use_device = (self.device and policies and
                      operation in ('CREATE', 'UPDATE') and
                      not pctx.exceptions)
        old_doc = (admission.request_old_resource(request) or None) \
            if operation == 'UPDATE' else None
        if use_device:
            try:
                from .. import faults
                faults.check(faults.SITE_WEBHOOK_HANDLER)
                scanner = self._device_scanner(policies)
                if scanner is None:
                    # compiled path still building — or the set's
                    # circuit breaker is open: host loop this request
                    from ..serving import breaker as breaker_mod
                    if self._breakers.state(self._policy_key(
                            policies)) != breaker_mod.CLOSED:
                        from ..serving import shed as shed_policy
                        prov_path = \
                            f'shed:{shed_policy.REASON_BREAKER_OPEN}'
                        if self.serving_mode == 'batch':
                            self._get_batcher().record_shed(
                                shed_policy.REASON_BREAKER_OPEN)
                    use_device = False
                elif self.serving_mode == 'batch':
                    # micro-batching scheduler: this request coalesces
                    # with concurrent same-policy-set same-verb requests
                    # into one shared device dispatch
                    # (serving/batcher.py); a shed comes back as None
                    # and the host loop serves
                    batched, bprov = self._batched_scan(
                        scanner, policies, request, pctx,
                        old_resource=old_doc)
                    prov_path = bprov.pop('path')
                    prov_extra = bprov
                    prov_extra['fingerprint'] = getattr(
                        scanner, 'fingerprint', '')
                    if batched is None:
                        use_device = False
                    else:
                        responses = batched
                else:
                    from ..observability import device as devtel
                    resource = admission.request_resource(request)
                    cap = devtel.ScanCapture() if prov_on else None
                    with devtel.install_capture(cap):
                        [responses] = scanner.scan(
                            [resource],
                            contexts=[pctx.json_context._data],
                            admission=(pctx.admission_info,
                                       pctx.exclude_group_roles,
                                       pctx.namespace_labels, operation),
                            pctx_factory=lambda doc: pctx,
                            old_resources=[old_doc] if old_doc else None)
                    prov_path = 'sync'
                    if cap is not None:
                        device_eval_s = cap.stage_s('device_eval')
                        prov_extra = {
                            'occupancy': 1,
                            'device_share_s': device_eval_s,
                            'device_eval_s': device_eval_s,
                            'aot_cache': cap.aot,
                            'coverage_ratio': cap.coverage_ratio,
                            'fingerprint': getattr(scanner,
                                                   'fingerprint', ''),
                        }
                    # success closes the set's breaker (recovery) or
                    # forgets its consecutive-failure count
                    self._breakers.record_success(
                        self._policy_key(policies))
            except Exception as e:  # noqa: BLE001
                # device failure must not turn into a 500: drop to the
                # host engine loop and discard the broken scanner so the
                # next request rebuilds it (failure recovery, SURVEY §5.3).
                # Repeated failures trip the per-set circuit breaker —
                # otherwise every request would pay a full policy-set
                # recompile before falling back.
                base = self._policy_key(policies)
                with self._scanner_lock:
                    self._scanners.pop(('validate',) + base, None)
                self._record_key_failure(
                    base, policies,
                    f'scan failed, falling back to host engine: {e}')
                provenance.notify_scan_error(e)
                use_device = False
                responses = []
                prov_path = 'host_fallback'
                prov_extra = {'error': f'scan failed: {e}'}
        if not use_device:
            for policy in policies:
                ctx = pctx.copy()
                ctx.policy = policy
                responses.append(self.engine.validate(ctx))
        # annotate the handler span with the serving path so a trace
        # distinguishes compiled-device requests from host-loop ones
        from ..observability import tracing
        span = tracing.current_span()
        if span is not None:
            span.set_attribute('device_path', bool(use_device))
        if prov_on or slo_on:
            duration_s = time.monotonic() - t_start
            # feed the admission-latency SLO digest (shed:<reason>
            # folds to the shed path inside record); no-op when the
            # engine is off (KTPU_SLO_WINDOW_S=0)
            slo.record(prov_path, duration_s)
            if prov_on:
                provenance.record_decision(
                    path=prov_path, uid=uid, kind=kind, namespace=ns,
                    name=request.get('name', '') or '',
                    operation=request.get('operation', '') or '',
                    duration_s=duration_s, **prov_extra)
        blocked = block_request(responses, failure_policy)
        if self.event_sink is not None and responses:
            # reference: handlers.go Validate -> webhooks/utils/event.go
            # GenerateEvents fed to the event controller
            self.event_sink(responses, blocked)
        if blocked:
            return admission.response(uid, False,
                                      get_blocked_messages(responses))
        # async hand-offs: audit-mode policies and generate URs
        if self.audit_sink is not None:
            self.audit_sink(request, responses)
        if self.ur_sink is not None and generate_policies:
            self._create_update_requests(request, pctx, generate_policies)
        if self.ur_sink is not None:
            # mutate-existing policies ride UpdateRequests too
            # (reference: pkg/webhooks/resource/updaterequest.go:20
            # handleMutateExisting; DELETE triggers use the old object)
            trigger_doc = admission.request_resource(request) or \
                admission.request_old_resource(request)
            trigger_res = Resource(trigger_doc)
            mutate_existing = [
                p for p in self.cache.get_policies(pcache.MUTATE, kind, ns)
                if any((r.raw.get('mutate') or {}).get('targets') and
                       matches_resource_description(
                           trigger_res, r, pctx.admission_info,
                           pctx.exclude_group_roles, pctx.namespace_labels,
                           p.namespace) is None
                       for r in p.rules)]
            if mutate_existing:
                self._create_update_requests(request, pctx,
                                             mutate_existing,
                                             ur_type='mutate')
        warnings = get_warning_messages(responses)
        return admission.response(uid, True, '', warnings)

    def audit_responses(self, request: dict) -> List[EngineResponse]:
        """Audit-mode engine responses for report construction
        (reference: validation.go:156 buildAuditResponses)."""
        kind = (request.get('kind') or {}).get('kind', '')
        ns = request.get('namespace', '')
        policies = self.cache.get_policies(pcache.VALIDATE_AUDIT, kind, ns)
        pctx = self.pc_builder.build(request)
        pctx.namespace_labels = self.namespace_labels(ns)
        out = []
        for policy in policies:
            ctx = pctx.copy()
            ctx.policy = policy
            out.append(self.engine.validate(ctx))
        return out

    def _create_update_requests(self, request: dict, pctx, policies,
                                ur_type: str = 'generate') -> None:
        """Spawn UpdateRequests for generate / mutate-existing policies
        on admission (reference: pkg/webhooks/resource/updaterequest.go:20)."""
        resource = admission.request_resource(request)
        if not resource and request.get('operation') == 'DELETE':
            resource = admission.request_old_resource(request)
        r = Resource(resource)
        for policy in policies:
            policy_key = f'{policy.namespace}/{policy.name}' \
                if policy.namespace else policy.name
            self.ur_sink({
                'type': ur_type,
                'policy': policy_key,
                'resource': {
                    'kind': r.kind, 'apiVersion': r.api_version,
                    'namespace': r.namespace, 'name': r.name,
                },
                'context': {
                    'userInfo': request.get('userInfo') or {},
                    'admissionRequestInfo': {
                        'operation': request.get('operation', ''),
                        # the background processors rebuild the admission
                        # context — DELETE triggers resolve from oldObject
                        # (reference: pkg/background/common/context.go:32)
                        'admissionRequest': {
                            'operation': request.get('operation', ''),
                            'object': request.get('object'),
                            'oldObject': request.get('oldObject'),
                            'userInfo': request.get('userInfo') or {},
                        },
                    },
                },
            })

    # -- mutate -----------------------------------------------------------

    @staticmethod
    def _canonicalize_context_images(pctx) -> None:
        from ..engine.mutate.jsonpatch import apply_patch
        from ..utils.image_extract import extract_images_from_resource
        try:
            infos = extract_images_from_resource(pctx.new_resource, None)
        except Exception:  # noqa: BLE001 - no images is the common case
            return
        ops = [{'op': 'replace', 'path': info.pointer, 'value': str(info)}
               for group in infos.values() for info in group.values()
               if info.pointer]
        if not ops:
            return
        import copy as _copy
        try:
            patched = apply_patch(_copy.deepcopy(pctx.new_resource), ops)
            pctx.json_context.add_resource(patched)
        except Exception:  # noqa: BLE001 - context stays unpatched
            pass

    def _post_mutate_policy(self, uid: str, policy, er: EngineResponse,
                            patches: List[dict],
                            responses: List[EngineResponse],
                            failure_policy: str) -> Optional[dict]:
        """Per-policy admission bookkeeping shared by the host mutate
        loop and the device fast path: deny on failure, collect patches,
        schema-validate the patched resource.  Returns the deny response
        or None to continue the chain."""
        if not er.is_successful():
            # a failed/errored mutate rule fails the admission —
            # failurePolicy only covers webhook transport failures
            # (reference: mutation.go:163 applyMutation →
            # mutation.go:112 'mutation policy %s error')
            failed = er.get_failed_rules()
            return admission.response(
                uid, False,
                f'mutation policy {policy.name} error: failed to '
                f'apply policy {policy.name} rules {failed}')
        policy_patches = [p for rr in er.policy_response.rules
                          for p in (rr.patches or [])]
        if policy_patches:
            patches.extend(policy_patches)
            # the mutated resource must stay schema-valid
            # (reference: mutation.go → openapi.ValidateResource,
            # pkg/openapi/manager.go:88)
            if self.openapi_manager is not None and er.patched_resource:
                from ..openapi.manager import ValidationError
                try:
                    self.openapi_manager.validate_resource(
                        er.patched_resource)
                except ValidationError as e:
                    return admission.response(
                        uid, False,
                        f'mutated resource failed schema validation: '
                        f'{e}')
        responses.append(er)
        if er.is_error() and failure_policy == 'Fail':
            return admission.response(
                uid, False, get_blocked_messages(responses))
        return None

    def _device_mutate_steps(self, request: dict, pctx,
                             mutate_policies) -> Optional[list]:
        """The device mutate chain for one request, or None when the
        host engine loop must serve it (knob off, verb outside
        CREATE/UPDATE, exceptions/subresource in play, set not lowered,
        scanner still building, shed, or scan failure — never a 500).
        Returns the ordered ``[(policy, EngineResponse), ...]`` steps,
        bit-identical to the host loop by construction
        (kyverno_tpu/mutate/scanner.py)."""
        operation = request.get('operation') or ''
        if not (self.device and self.mutate_device and mutate_policies and
                operation in ('CREATE', 'UPDATE') and
                not pctx.exceptions and not request.get('subResource')):
            return None
        try:
            scanner = self._device_scanner(mutate_policies, kind='mutate')
            if scanner is None or not scanner.ok:
                # still lowering, or the set does not lower (the
                # placement records on the coverage ledger name why)
                return None
            if self.serving_mode == 'batch':
                row, _prov = self._batched_scan(
                    scanner, mutate_policies, request, pctx,
                    resource=pctx.new_resource)
                return row  # None on shed -> host loop
            [row] = scanner.scan(
                [pctx.new_resource],
                admission=(pctx.admission_info,
                           pctx.exclude_group_roles,
                           pctx.namespace_labels, operation),
                pctx_factory=lambda doc: pctx)
            self._breakers.record_success(
                self._policy_key(mutate_policies))
            return row
        except Exception as e:  # noqa: BLE001
            # identical never-500 recovery to the validate path: drop
            # the broken scanner, count one breaker failure, host loop
            base = self._policy_key(mutate_policies)
            with self._scanner_lock:
                self._scanners.pop(('mutate',) + base, None)
            self._record_key_failure(
                base, mutate_policies,
                f'mutate scan failed, falling back to host engine: {e}')
            return None

    def mutate(self, request: dict, failure_policy: str = 'Fail') -> dict:
        """reference: pkg/webhooks/resource/handlers.go:157 Mutate +
        mutation.go:80 applyMutations (sequential, cumulative)."""
        uid = request.get('uid', '')
        kind = (request.get('kind') or {}).get('kind', '')
        ns = request.get('namespace', '')
        mutate_policies = self.cache.get_policies(pcache.MUTATE, kind, ns)
        verify_policies = self.cache.get_policies(
            pcache.VERIFY_IMAGES_MUTATE, kind, ns)
        try:
            pctx = self.pc_builder.build(request)
        except Exception as e:  # noqa: BLE001
            return admission.response(uid, False,
                                      f'failed to build policy context: {e}')
        pctx.namespace_labels = self.namespace_labels(ns)
        # canonicalize images in the JSON context's request.object so
        # {{request.object...image}} variables resolve to the full
        # registry form; the stored resource and emitted patches keep the
        # original spelling (reference: handlers.go:174 →
        # pkg/engine/context/imageutils.go:12 MutateResourceWithImageInfo)
        self._canonicalize_context_images(pctx)

        patches: List[dict] = []
        responses: List[EngineResponse] = []
        # device fast path: a lowered mutate policy set evaluates its
        # whole cumulative chain as one batched device dispatch
        # (kyverno_tpu/mutate/) whose rows coalesce with concurrent
        # mutate requests in batch serving mode
        device_row = self._device_mutate_steps(request, pctx,
                                               mutate_policies)
        if device_row is not None:
            steps, patched = device_row
            for policy, er in steps:
                deny = self._post_mutate_policy(uid, policy, er, patches,
                                                responses, failure_policy)
                if deny is not None:
                    return deny
            if steps:
                # verify-images policies see the chain's cumulative
                # output, exactly as the host loop threads it
                pctx = pctx.copy()
                pctx.new_resource = patched or pctx.new_resource
                pctx.json_context.add_resource(pctx.new_resource)
        else:
            for policy in mutate_policies:
                if not any(r.has_mutate() for r in policy.rules):
                    continue
                ctx = pctx.copy()
                ctx.policy = policy
                er = self.engine.mutate(ctx)
                deny = self._post_mutate_policy(uid, policy, er, patches,
                                                responses, failure_policy)
                if deny is not None:
                    return deny
                # mutations apply cumulatively: the patched resource
                # re-enters the context for the next policy
                # (mutation.go:123)
                pctx = pctx.copy()
                pctx.new_resource = er.patched_resource or \
                    pctx.new_resource
                pctx.json_context.add_resource(pctx.new_resource)
        for policy in verify_policies:
            ctx = pctx.copy()
            ctx.policy = policy
            er, _meta = self.engine.verify_and_patch_images(
                ctx, self.registry_client)
            iv_patches = [p for rr in er.policy_response.rules
                          for p in (rr.patches or [])]
            patches.extend(iv_patches)
            responses.append(er)
            if er.is_failed():
                return admission.response(
                    uid, False, get_blocked_messages(responses))
        warnings = get_warning_messages(responses)
        return admission.mutation_response(uid, patches, warnings)
