"""Admission serving layer (L4): AdmissionReview protocol, handler
middleware chain, resource/policy/exception handlers and the HTTPS
webhook server (reference: pkg/webhooks)."""

from . import admission  # noqa: F401
from .handlers import ResourceHandlers  # noqa: F401
from .server import WebhookServer  # noqa: F401
