"""AdmissionReview protocol helpers + PolicyContext construction.

Mirrors the reference's admission utilities
(reference: pkg/utils/admission/response.go, pkg/webhooks/utils/
policy_context_builder.go:57) for the K8s admission webhook protocol:
requests arrive as AdmissionReview JSON, responses carry uid / allowed /
status.message / JSONPatch (base64) / warnings.
"""

from __future__ import annotations

import base64
import json
from typing import Any, Callable, Dict, List, Optional

from ..engine.api import PolicyContext


def parse_review(body: dict) -> dict:
    """Extract the AdmissionRequest from an AdmissionReview document."""
    request = body.get('request')
    if not isinstance(request, dict):
        raise ValueError('admission review without request')
    return request


def review_response(request: dict, response: dict) -> dict:
    """Wrap an AdmissionResponse in the review envelope the API server
    expects (same apiVersion/kind as the request review)."""
    return {
        'apiVersion': 'admission.k8s.io/v1',
        'kind': 'AdmissionReview',
        'response': response,
    }


def response(uid: str, allowed: bool = True, message: str = '',
             warnings: Optional[List[str]] = None) -> dict:
    """reference: pkg/utils/admission/response.go:11 Response"""
    out: Dict[str, Any] = {'uid': uid, 'allowed': allowed}
    if message:
        out['status'] = {'message': message}
    if warnings:
        out['warnings'] = warnings
    return out


def mutation_response(uid: str, patches: List[dict],
                      warnings: Optional[List[str]] = None) -> dict:
    """reference: pkg/utils/admission/response.go:30 MutationResponse"""
    out = response(uid, True, '', warnings)
    if patches:
        raw = json.dumps(patches, separators=(',', ':')).encode('utf-8')
        out['patch'] = base64.b64encode(raw).decode('ascii')
        out['patchType'] = 'JSONPatch'
    return out


def decode_patch(resp: dict) -> List[dict]:
    """Decode the base64 JSONPatch of an AdmissionResponse (tests)."""
    if 'patch' not in resp:
        return []
    return json.loads(base64.b64decode(resp['patch']))


def request_resource(request: dict) -> dict:
    obj = request.get('object')
    return obj if isinstance(obj, dict) else {}


def request_old_resource(request: dict) -> dict:
    obj = request.get('oldObject')
    return obj if isinstance(obj, dict) else {}


class PolicyContextBuilder:
    """Builds a PolicyContext from an AdmissionRequest
    (reference: pkg/webhooks/utils/policy_context_builder.go:57).

    ``role_resolver`` maps (username, groups) → (roles, cluster_roles) —
    the reference resolves these through RBAC listers
    (pkg/userinfo/roleRef.go:25); injectable so serving stays hermetic.
    """

    def __init__(self, configuration=None,
                 role_resolver: Optional[Callable] = None,
                 exception_lister: Optional[Callable] = None):
        self.configuration = configuration
        self.role_resolver = role_resolver
        self.exception_lister = exception_lister

    def build(self, request: dict, policy=None) -> PolicyContext:
        user_info = request.get('userInfo') or {}
        roles: List[str] = []
        cluster_roles: List[str] = []
        if self.role_resolver is not None:
            roles, cluster_roles = self.role_resolver(
                user_info.get('username', ''), user_info.get('groups') or [])
        admission_info = {
            'roles': roles,
            'clusterRoles': cluster_roles,
            'userInfo': user_info,
        }
        exclude_group_roles: List[str] = []
        if self.configuration is not None:
            exclude_group_roles = list(
                self.configuration.get_exclude_group_role())
        exceptions = None
        if self.exception_lister is not None:
            exceptions = list(self.exception_lister())
        new = request_resource(request)
        old = request_old_resource(request)
        operation = request.get('operation', '')
        ctx = PolicyContext(
            policy, new_resource=new, old_resource=old,
            admission_info=admission_info,
            exclude_group_roles=exclude_group_roles,
            exceptions=exceptions,
            admission_operation=operation,
            subresource=request.get('subResource', ''))
        ctx.json_context.add_user_info({
            'userInfo': user_info, 'roles': roles,
            'clusterRoles': cluster_roles})
        if request.get('namespace'):
            ctx.json_context.add_namespace(request['namespace'])
        # the `images.` context variable is available to every rule
        # (reference: NewPolicyContextFromAdmissionRequest →
        # AddImageInfos; mutate foreach preconditions rely on it)
        from ..engine.image_verify import _add_resource_images
        _add_resource_images(ctx)
        return ctx
