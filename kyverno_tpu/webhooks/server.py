"""HTTPS admission webhook server.

Route table and lifecycle mirror the reference's server
(reference: pkg/webhooks/server.go:69 NewServer, routes :102-115):

  POST /validate[/fail|/ignore]     resource validation
  POST /mutate[/fail|/ignore]       resource mutation
  POST /policyvalidate              policy CR validation
  POST /policymutate                policy CR defaulting
  POST /exceptionvalidate           PolicyException validation
  POST /verifymutate                lease heartbeat mutation
  GET  /health/liveness             liveness probe
  GET  /health/readiness            readiness probe
  GET  /health                      aggregate health JSON (readiness +
                                    warm-up + SLO verdict)

TLS is loaded from cert/key PEM files when provided (the reference reads
its pair per-handshake from the certmanager secret, server.go:155-177).
"""

from __future__ import annotations

import json
import ssl
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Optional

from . import admission
from .handlers import (DumpBuffer, Handler, ResourceHandlers, with_dump,
                       with_filter, with_protection)


def _allow_all(request: dict) -> dict:
    return admission.response(request.get('uid', ''), True)


class PolicyHandlers:
    """Policy CR admission (validate/mutate) — overridden by the policy
    lifecycle module (reference: pkg/webhooks/policy/handlers.go).

    ``client`` enables SSAR-backed generate permission pre-flight
    (reference: pkg/policy/actions.go validateActions, mock=false)."""

    def __init__(self, client=None):
        self.client = client

    def validate(self, request: dict) -> dict:
        from ..policy.validate import validate_policy_admission
        return validate_policy_admission(request, self.client)

    def mutate(self, request: dict) -> dict:
        return _allow_all(request)


class ExceptionHandlers:
    def validate(self, request: dict) -> dict:
        from ..policy.validate import validate_exception_admission
        return validate_exception_admission(request)


class WebhookServer:
    """Threaded admission server over the handler chain.

    ``routes()`` exposes the request→response callables directly so tests
    and the in-process latency benchmark can drive the full middleware
    stack without sockets.
    """

    def __init__(self, resource_handlers: ResourceHandlers,
                 policy_handlers: Optional[PolicyHandlers] = None,
                 exception_handlers: Optional[ExceptionHandlers] = None,
                 configuration=None,
                 protection_enabled: Optional[bool] = None,
                 dump: bool = False,
                 host: str = '127.0.0.1', port: int = 9443,
                 certfile: Optional[str] = None,
                 keyfile: Optional[str] = None,
                 warmer=None):
        self.resource_handlers = resource_handlers
        self.policy_handlers = policy_handlers or PolicyHandlers()
        self.exception_handlers = exception_handlers or ExceptionHandlers()
        self.configuration = configuration
        self.dump_buffer = DumpBuffer() if dump else None
        self.host = host
        self.port = port
        self.certfile = certfile
        self.keyfile = keyfile
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._ready = False
        # aotcache.warmer.Warmer (or None): /health/warmup reports its
        # state.  Warm-up never gates /health/readiness — the host
        # engine loop serves identical verdicts while compiling.
        self.warmer = warmer
        if protection_enabled is None:
            # env-tier feature toggle (reference: pkg/toggle/toggle.go:21
            # ProtectManagedResources, consumed by handlers/protect.go)
            from ..config.toggle import PROTECT_MANAGED_RESOURCES
            protection_enabled = PROTECT_MANAGED_RESOURCES.enabled()
        self._routes = self._build_routes(protection_enabled)

    # -- handler chain ----------------------------------------------------

    def _chain(self, terminal: Handler, protect: bool) -> Handler:
        h = terminal
        h = with_protection(protect, h)
        h = with_filter(self.configuration, h)
        h = with_dump(self.dump_buffer, h)
        return h

    def _build_routes(self, protect: bool) -> Dict[str, Handler]:
        rh = self.resource_handlers
        routes: Dict[str, Handler] = {}
        for suffix, fp in (('', 'Fail'), ('/fail', 'Fail'),
                           ('/ignore', 'Ignore')):
            routes[f'/validate{suffix}'] = self._chain(
                lambda req, fp=fp: rh.validate(req, fp), protect)
            routes[f'/mutate{suffix}'] = self._chain(
                lambda req, fp=fp: rh.mutate(req, fp), protect)
        routes['/policyvalidate'] = self.policy_handlers.validate
        routes['/policymutate'] = self.policy_handlers.mutate
        routes['/exceptionvalidate'] = self.exception_handlers.validate
        routes['/verifymutate'] = _allow_all
        return routes

    def routes(self) -> Dict[str, Handler]:
        return dict(self._routes)

    def handle(self, path: str, body: bytes) -> bytes:
        """Dispatch one POST body through the route's handler chain
        (the in-process form ``routes()`` consumers and tests use; the
        HTTP layer goes through :meth:`handle_request` for the status
        code)."""
        out, _status = self.handle_request(path, body)
        return out

    @staticmethod
    def _observe_review(operation: str, allowed: str,
                        seconds: float) -> None:
        from ..observability.metrics import (ADMISSION_REQUESTS,
                                             ADMISSION_REVIEW_DURATION,
                                             global_registry)
        registry = global_registry()
        if registry is None:
            return
        registry.observe(ADMISSION_REVIEW_DURATION, seconds,
                         operation=operation, allowed=allowed)
        registry.inc(ADMISSION_REQUESTS, operation=operation,
                     allowed=allowed)

    def handle_request(self, path: str, body: bytes):
        """Dispatch one POST body; returns ``(response bytes, status)``.

        Each request runs under an HTTP-handler span (reference:
        pkg/webhooks/handlers/trace.go:16 WithTrace); engine rule spans
        nest under it via context propagation.

        A body that is not JSON or not an AdmissionReview with a
        ``request`` object gets a structured, uid-echoing denied
        response at HTTP 400 — the API server always receives an
        AdmissionReview it can correlate, never a raw traceback.
        Handler-chain exceptions still propagate (the HTTP layer 500s)
        but are recorded with ``allowed=error`` so shed/error traffic
        is visible on the admission instruments."""
        handler = self._routes.get(path)
        if handler is None:
            raise KeyError(path)
        import time as _time
        from ..observability import tracing
        t0 = _time.monotonic()
        review = None
        try:
            review = json.loads(body)
            request = admission.parse_review(review)
        except Exception as e:  # noqa: BLE001 - malformed input → 400
            uid = ''
            if isinstance(review, dict):
                req = review.get('request')
                if isinstance(req, dict):
                    uid = str(req.get('uid', '') or '')
            self._observe_review('', 'error', _time.monotonic() - t0)
            resp = admission.response(
                uid, False, f'malformed admission review: {e}')
            return (json.dumps(
                admission.review_response({}, resp)).encode('utf-8'), 400)
        operation = request.get('operation', '') or ''
        with tracing.start_span(
                f'webhooks{path}',
                {'uid': request.get('uid', ''),
                 'kind': (request.get('kind') or {}).get('kind', ''),
                 'operation': request.get('operation', '')}) as span:
            try:
                resp = handler(request)
            except Exception:
                self._observe_review(operation, 'error',
                                     _time.monotonic() - t0)
                raise
            span.set_attribute('allowed', resp.get('allowed'))
        self._observe_review(operation,
                             str(bool(resp.get('allowed'))).lower(),
                             _time.monotonic() - t0)
        return (json.dumps(
            admission.review_response(request, resp)).encode('utf-8'), 200)

    def health_status(self):
        """(json body, http status) for the aggregate ``GET /health``:
        readiness + warm-up state + the SLO verdict when the engine is
        on.  The status code tracks readiness ONLY — a degraded SLO is
        a payload-level signal for operators/alerting, never a reason
        for the orchestrator to restart a pod that is still answering
        admission requests (on the host loop if nothing else)."""
        body = {'ready': self._ready}
        w = self.warmer
        if w is not None:
            body['warmup'] = w.state
        from ..observability import slo
        verdict = slo.verdict()
        if verdict is not None:
            body['slo'] = verdict
        return body, 200 if self._ready else 503

    def warmup_status(self):
        """(json body, http status) for /health/warmup."""
        w = self.warmer
        if w is None:
            return {'state': 'disabled'}, 200
        body = {'state': w.state}
        if w.duration_s is not None:
            body['duration_s'] = round(w.duration_s, 3)
        if w.detail:
            body['detail'] = w.detail
        if w.error:
            body['error'] = w.error
        return body, 200 if w.state in ('ready', 'disabled', 'failed') \
            else 503

    # -- http lifecycle ---------------------------------------------------

    def start(self) -> None:
        server = self

        class _Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # noqa: A003 - quiet
                pass

            def do_GET(self):  # noqa: N802
                if self.path in ('/health/liveness', '/health/readiness'):
                    ok = self.path == '/health/liveness' or server._ready
                    self.send_response(200 if ok else 503)
                    self.end_headers()
                    self.wfile.write(b'ok' if ok else b'not ready')
                    return
                if self.path == '/health':
                    # aggregate health JSON (readiness + warm-up + SLO
                    # verdict); the byte contracts of /health/liveness
                    # and /health/readiness above stay untouched
                    body, code = server.health_status()
                    payload = json.dumps(body).encode('utf-8')
                    self.send_response(code)
                    self.send_header('Content-Type', 'application/json')
                    self.send_header('Content-Length', str(len(payload)))
                    self.end_headers()
                    self.wfile.write(payload)
                    return
                if self.path == '/health/warmup':
                    # 200 once the warm pass finished (ready), was
                    # disabled, or failed (serving is unaffected: the
                    # host loop covers it); 503 only while in flight —
                    # deployments that want compiled-path latency from
                    # the first request gate rollout on this endpoint
                    body, code = server.warmup_status()
                    payload = json.dumps(body).encode('utf-8')
                    self.send_response(code)
                    self.send_header('Content-Type', 'application/json')
                    self.send_header('Content-Length', str(len(payload)))
                    self.end_headers()
                    self.wfile.write(payload)
                    return
                self.send_response(404)
                self.end_headers()

            def do_POST(self):  # noqa: N802
                length = int(self.headers.get('Content-Length', 0))
                body = self.rfile.read(length)
                try:
                    out, status = server.handle_request(self.path, body)
                except KeyError:
                    self.send_response(404)
                    self.end_headers()
                    return
                except Exception as e:  # noqa: BLE001
                    self.send_response(500)
                    self.end_headers()
                    self.wfile.write(str(e).encode('utf-8'))
                    return
                self.send_response(status)
                self.send_header('Content-Type', 'application/json')
                self.send_header('Content-Length', str(len(out)))
                self.end_headers()
                self.wfile.write(out)

        self._httpd = ThreadingHTTPServer((self.host, self.port), _Handler)
        if self.certfile:
            # per-handshake pair pickup (reference: server.go:155-177 reads
            # the certmanager secret per TLS handshake): before each
            # accept, a rotated cert/key pair is reloaded into the live
            # SSLContext, so new handshakes serve the fresh pair without
            # restart.  This covers every client — an SNI callback alone
            # would miss clients that connect by IP and send no SNI.
            outer = self
            state = {'mtime': None}
            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)

            def reload_if_rotated():
                import os
                try:
                    mtime = (os.stat(outer.certfile).st_mtime_ns,
                             os.stat(outer.keyfile).st_mtime_ns)
                except OSError:
                    return
                if mtime != state['mtime']:
                    try:
                        ctx.load_cert_chain(outer.certfile, outer.keyfile)
                        state['mtime'] = mtime
                    except Exception:  # noqa: BLE001 - keep old pair
                        if state['mtime'] is None:
                            raise  # first load must succeed

            reload_if_rotated()
            # the listener stays plaintext; each accepted connection is
            # wrapped AFTER the rotation check, so a pair rotated while
            # the server sat idle in accept() is picked up by the very
            # next connection.  The handshake is deferred to the handler
            # thread (do_handshake_on_connect=False) so a slow client
            # cannot stall the accept loop.
            inner_get_request = self._httpd.get_request

            def get_request():
                sock, addr = inner_get_request()
                reload_if_rotated()
                return (ctx.wrap_socket(sock, server_side=True,
                                        do_handshake_on_connect=False),
                        addr)
            self._httpd.get_request = get_request
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True)
        self._thread.start()
        self._ready = True

    def stop(self) -> None:
        self._ready = False
        # drain the admission micro-batcher before tearing the listener
        # down: handler threads blocked on batched futures resolve with
        # real responses instead of timing out mid-shutdown
        shutdown = getattr(self.resource_handlers, 'shutdown', None)
        if shutdown is not None:
            shutdown()
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
