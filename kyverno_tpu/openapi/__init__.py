"""OpenAPI schema validation (reference: pkg/openapi)."""

from .manager import Manager, ValidationError  # noqa: F401
