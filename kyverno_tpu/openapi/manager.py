"""Schema validation of mutated resources.

Mirrors the reference's openapi manager (reference:
pkg/openapi/manager.go:60 NewManager, :88 ValidateResource, :120
ValidatePolicyMutation): mutated resources are validated before the
patches are admitted, and policy mutations are dry-run against a
skeleton resource so broken overlays are rejected at policy admission.

Schemas: the reference syncs cluster OpenAPI documents and falls back to
a baked-in snapshot (pkg/openapi/data/apiResources.go); here a built-in
structural schema covers the core kinds' spines (typed metadata, typed
well-known fields), extended at runtime via ``add_schema`` — unknown
fields are tolerated exactly like Kubernetes does for unstructured
content.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional


class ValidationError(Exception):
    pass


# structural spine: field path → expected type ('object', 'array',
# 'string', 'integer', 'boolean', 'string-map')
_COMMON = {
    'metadata': 'object',
    'metadata.name': 'string',
    'metadata.namespace': 'string',
    'metadata.labels': 'string-map',
    'metadata.annotations': 'string-map',
    'metadata.finalizers': 'array',
    'spec': 'object',
}

_BUILTIN_SCHEMAS: Dict[str, Dict[str, str]] = {
    'Pod': {
        **_COMMON,
        'spec.containers': 'array',
        'spec.initContainers': 'array',
        'spec.ephemeralContainers': 'array',
        'spec.volumes': 'array',
        'spec.hostNetwork': 'boolean',
        'spec.hostPID': 'boolean',
        'spec.hostIPC': 'boolean',
        'spec.serviceAccountName': 'string',
        'spec.nodeSelector': 'string-map',
    },
    'Deployment': {
        **_COMMON,
        'spec.replicas': 'integer',
        'spec.selector': 'object',
        'spec.template': 'object',
        'spec.template.spec.containers': 'array',
    },
    'StatefulSet': {**_COMMON, 'spec.replicas': 'integer',
                    'spec.template': 'object'},
    'DaemonSet': {**_COMMON, 'spec.template': 'object'},
    'Job': {**_COMMON, 'spec.template': 'object'},
    'CronJob': {**_COMMON, 'spec.schedule': 'string',
                'spec.jobTemplate': 'object'},
    'Service': {**_COMMON, 'spec.ports': 'array',
                'spec.selector': 'string-map', 'spec.type': 'string'},
    'ConfigMap': {'metadata': 'object', 'metadata.name': 'string',
                  'metadata.labels': 'string-map', 'data': 'string-map'},
    'Namespace': {'metadata': 'object', 'metadata.name': 'string',
                  'metadata.labels': 'string-map'},
    'NetworkPolicy': {**_COMMON, 'spec.podSelector': 'object'},
    'ResourceQuota': {**_COMMON, 'spec.hard': 'object'},
    'LimitRange': {**_COMMON, 'spec.limits': 'array'},
}


def _type_ok(value: Any, expected: str) -> bool:
    if expected == 'object':
        return isinstance(value, dict)
    if expected == 'array':
        return isinstance(value, list)
    if expected == 'string':
        return isinstance(value, str)
    if expected == 'integer':
        return isinstance(value, int) and not isinstance(value, bool)
    if expected == 'boolean':
        return isinstance(value, bool)
    if expected == 'string-map':
        return isinstance(value, dict) and all(
            isinstance(k, str) and isinstance(v, str)
            for k, v in value.items())
    return True


class Manager:
    """reference: pkg/openapi/manager.go:60"""

    def __init__(self):
        self._schemas: Dict[str, Dict[str, str]] = dict(_BUILTIN_SCHEMAS)
        # CRD-synced tier keyed by (group, kind): same-kind CRDs in
        # different groups must not collide, and a re-synced CRD replaces
        # its prior schema (no stale field types)
        self._crd_schemas: Dict[tuple, Dict[str, str]] = {}

    def add_schema(self, kind: str, fields: Dict[str, str]) -> None:
        """Extend/override the schema for a kind."""
        self._schemas.setdefault(kind, {}).update(fields)

    def replace_crd_schemas(self,
                            schemas: Dict[tuple, Dict[str, str]]) -> None:
        """Swap in the freshly synced CRD schema set (the reference's
        periodic sync semantics — deleted/retyped CRDs leave no residue;
        pkg/controllers/openapi/controller.go:148)."""
        self._crd_schemas = dict(schemas)

    def validate_resource(self, resource: dict,
                          kind: Optional[str] = None) -> None:
        """Raises ValidationError on structural violations
        (reference: manager.go:88 ValidateResource)."""
        if not isinstance(resource, dict):
            raise ValidationError('resource must be an object')
        kind = kind or resource.get('kind', '')
        api_version = resource.get('apiVersion', '') \
            if isinstance(resource.get('apiVersion'), str) else ''
        group = api_version.split('/')[0] if '/' in api_version else ''
        schema = self._crd_schemas.get((group, kind))
        if schema is None and group == '':
            # resources often omit apiVersion in fixtures: a kind-unique
            # CRD schema still applies
            hits = [s for (g, k), s in self._crd_schemas.items()
                    if k == kind]
            if len(hits) == 1:
                schema = hits[0]
        if schema is None:
            schema = self._schemas.get(kind)
        if schema is None:
            return  # unknown kinds are not schema-validated
        for path, expected in schema.items():
            value = _walk(resource, path)
            if value is _MISSING or value is None:
                continue
            if not _type_ok(value, expected):
                raise ValidationError(
                    f'ValidationError(io.k8s.api {kind}.{path}): invalid '
                    f'type for {path}: expected {expected}, got '
                    f'{type(value).__name__}')

    def validate_policy_mutation(self, policy) -> None:
        """Dry-run each mutate rule's overlay against a skeleton of its
        matched kinds (reference: manager.go:120 ValidatePolicyMutation)."""
        from ..api.policy import Policy, Rule
        from ..engine.api import PolicyContext
        from ..engine.engine import Engine
        if not isinstance(policy, Policy):
            policy = Policy(policy)
        engine = Engine()
        for rule in policy.rules:
            if not rule.has_mutate():
                continue
            match = rule.raw.get('match') or {}
            kinds: List[str] = []
            for f in [match] + (match.get('any') or []) + \
                    (match.get('all') or []):
                kinds += [str(k).split('/')[-1] for k in
                          (f.get('resources') or {}).get('kinds') or []]
            for kind in kinds:
                if kind not in self._schemas:
                    continue
                skeleton = {'apiVersion': 'v1', 'kind': kind,
                            'metadata': {'name': 'dry-run',
                                         'namespace': 'default'},
                            'spec': {}}
                try:
                    resp = engine.mutate(PolicyContext(
                        policy, new_resource=skeleton))
                except Exception as e:  # noqa: BLE001
                    raise ValidationError(
                        f'mutation dry-run failed for rule '
                        f'{rule.name}/{kind}: {e}')
                patched = resp.patched_resource or skeleton
                self.validate_resource(patched, kind)


_MISSING = object()


def _walk(doc: dict, dotted: str):
    cur: Any = doc
    for part in dotted.split('.'):
        if not isinstance(cur, dict) or part not in cur:
            return _MISSING
        cur = cur[part]
    return cur
