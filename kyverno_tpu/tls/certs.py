"""Self-signed CA + TLS pair generation and renewal.

Mirrors the reference's certmanager (reference: pkg/tls/cert.go,
pkg/tls/renewer.go:77,109): a 10-year self-signed CA and a 1-year
server pair stored as kubernetes.io/tls Secrets, renewed when inside
the renewal window; the webhook server reads the pair per handshake and
the webhook configurations embed the CA bundle.
"""

from __future__ import annotations

import datetime
import ipaddress
from typing import List, Optional, Tuple

from cryptography import x509
from cryptography.hazmat.primitives import hashes, serialization
from cryptography.hazmat.primitives.asymmetric import rsa
from cryptography.x509.oid import NameOID

CA_VALIDITY = datetime.timedelta(days=365 * 10)   # reference: cert.go CA 10y
TLS_VALIDITY = datetime.timedelta(days=365)       # server pair 1y
RENEWAL_WINDOW = datetime.timedelta(days=15)      # renewer.go CertRenewalInterval

CA_SECRET = 'kyverno-svc.kyverno.svc.kyverno-tls-ca'
TLS_SECRET = 'kyverno-svc.kyverno.svc.kyverno-tls-pair'


def _key() -> rsa.RSAPrivateKey:
    return rsa.generate_private_key(public_exponent=65537, key_size=2048)


def _pem_cert(cert: x509.Certificate) -> bytes:
    return cert.public_bytes(serialization.Encoding.PEM)


def _pem_key(key: rsa.RSAPrivateKey) -> bytes:
    return key.private_bytes(
        serialization.Encoding.PEM,
        serialization.PrivateFormat.TraditionalOpenSSL,
        serialization.NoEncryption())


def generate_ca(now: Optional[datetime.datetime] = None
                ) -> Tuple[bytes, bytes]:
    """Self-signed CA; returns (cert_pem, key_pem)."""
    now = now or datetime.datetime.now(datetime.timezone.utc)
    key = _key()
    name = x509.Name([x509.NameAttribute(NameOID.COMMON_NAME,
                                         '*.kyverno.svc')])
    cert = (x509.CertificateBuilder()
            .subject_name(name).issuer_name(name)
            .public_key(key.public_key())
            .serial_number(x509.random_serial_number())
            .not_valid_before(now - datetime.timedelta(minutes=5))
            .not_valid_after(now + CA_VALIDITY)
            .add_extension(x509.BasicConstraints(ca=True, path_length=None),
                           critical=True)
            .add_extension(x509.KeyUsage(
                digital_signature=True, key_cert_sign=True,
                crl_sign=True, content_commitment=False,
                key_encipherment=False, data_encipherment=False,
                key_agreement=False, encipher_only=False,
                decipher_only=False), critical=True)
            .sign(key, hashes.SHA256()))
    return _pem_cert(cert), _pem_key(key)


def generate_tls_pair(ca_cert_pem: bytes, ca_key_pem: bytes,
                      service: str = 'kyverno-svc',
                      namespace: str = 'kyverno',
                      now: Optional[datetime.datetime] = None
                      ) -> Tuple[bytes, bytes]:
    """Server certificate for the webhook service DNS names."""
    now = now or datetime.datetime.now(datetime.timezone.utc)
    ca_cert = x509.load_pem_x509_certificate(ca_cert_pem)
    ca_key = serialization.load_pem_private_key(ca_key_pem, password=None)
    key = _key()
    dns_names: List[x509.GeneralName] = [
        x509.DNSName(service),
        x509.DNSName(f'{service}.{namespace}'),
        x509.DNSName(f'{service}.{namespace}.svc'),
    ]
    cert = (x509.CertificateBuilder()
            .subject_name(x509.Name([x509.NameAttribute(
                NameOID.COMMON_NAME, f'{service}.{namespace}.svc')]))
            .issuer_name(ca_cert.subject)
            .public_key(key.public_key())
            .serial_number(x509.random_serial_number())
            .not_valid_before(now - datetime.timedelta(minutes=5))
            .not_valid_after(now + TLS_VALIDITY)
            .add_extension(x509.SubjectAlternativeName(dns_names),
                           critical=False)
            .add_extension(x509.ExtendedKeyUsage(
                [x509.ExtendedKeyUsageOID.SERVER_AUTH]), critical=False)
            .sign(ca_key, hashes.SHA256()))
    return _pem_cert(cert), _pem_key(key)


def cert_expiry(cert_pem: bytes) -> datetime.datetime:
    return x509.load_pem_x509_certificate(cert_pem).not_valid_after_utc


class CertRenewer:
    """Stores/renews the CA + pair Secrets
    (reference: pkg/tls/renewer.go:77 RenewCA, :109 RenewTLS)."""

    def __init__(self, client, namespace: str = 'kyverno',
                 service: str = 'kyverno-svc'):
        self.client = client
        self.namespace = namespace
        self.service = service

    def _get_secret(self, name: str) -> Optional[dict]:
        try:
            return self.client.get_resource('v1', 'Secret',
                                            self.namespace, name)
        except Exception:  # noqa: BLE001
            return None

    def _put_secret(self, name: str, cert: bytes, key: bytes) -> dict:
        import base64
        secret = self._get_secret(name)
        data = {'tls.crt': base64.b64encode(cert).decode(),
                'tls.key': base64.b64encode(key).decode()}
        if secret is None:
            return self.client.create_resource('v1', 'Secret',
                                               self.namespace, {
                'apiVersion': 'v1', 'kind': 'Secret',
                'type': 'kubernetes.io/tls',
                'metadata': {'name': name, 'namespace': self.namespace},
                'data': data})
        secret['data'] = data
        return self.client.update_resource('v1', 'Secret',
                                           self.namespace, secret)

    def _read_secret(self, name: str) -> Optional[Tuple[bytes, bytes]]:
        import base64
        secret = self._get_secret(name)
        if secret is None:
            return None
        data = secret.get('data') or {}
        try:
            return (base64.b64decode(data['tls.crt']),
                    base64.b64decode(data['tls.key']))
        except Exception:  # noqa: BLE001
            return None

    def renew(self, now: Optional[datetime.datetime] = None
              ) -> Tuple[bytes, bytes, bytes]:
        """Ensure valid CA + pair; returns (ca_cert, tls_cert, tls_key)."""
        now = now or datetime.datetime.now(datetime.timezone.utc)
        ca = self._read_secret(CA_SECRET)
        if ca is None or cert_expiry(ca[0]) - now < RENEWAL_WINDOW:
            ca = generate_ca(now)
            self._put_secret(CA_SECRET, *ca)
            pair = None  # a new CA invalidates the old pair
        else:
            pair = self._read_secret(TLS_SECRET)
        if pair is None or cert_expiry(pair[0]) - now < RENEWAL_WINDOW:
            pair = generate_tls_pair(ca[0], ca[1], self.service,
                                     self.namespace, now)
            self._put_secret(TLS_SECRET, *pair)
        return ca[0], pair[0], pair[1]

    def ca_bundle(self) -> bytes:
        ca = self._read_secret(CA_SECRET)
        return ca[0] if ca else b''
