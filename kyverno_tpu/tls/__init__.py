"""TLS certificate management (reference: pkg/tls)."""

from .certs import CertRenewer, generate_ca, generate_tls_pair  # noqa: F401
