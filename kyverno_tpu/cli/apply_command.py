"""``kyverno apply`` — apply policies to resources from files.

Reference: cmd/cli/kubectl-kyverno/apply/apply_command.go — loads policies
and resources from paths, runs the engine per (policy, resource) pair, and
prints mutated output plus a pass/fail/warn/error/skip summary.
"""

from __future__ import annotations

import os
import sys
from typing import Dict, List

import yaml

from ..engine.api import RuleStatus, RuleType
from ..engine.engine import Engine
from ..reports.results import (calculate_summary,
                               engine_response_to_report_results,
                               sort_report_results)
from .common import (MockContextLoader, Values, apply_policy_on_resource,
                     load_policies_from_paths, load_resources_from_paths,
                     load_user_info, load_values)
from .store import reset_store


class ResultCounts:
    """reference: common.go ResultCounts"""

    def __init__(self):
        self.pass_ = 0
        self.fail = 0
        self.warn = 0
        self.error = 0
        self.skip = 0


def command(args) -> int:
    store = reset_store()
    store.mock = True
    store.registry_access = getattr(args, 'registry', False)

    values = Values()
    if args.values_file:
        values = load_values(args.values_file)
    store.set_policies(values.policies)
    store.subresources = values.subresources

    set_vars: Dict[str, str] = {}
    for kv in args.set or []:
        for pair in kv.split(','):
            if '=' in pair:
                k, v = pair.split('=', 1)
                set_vars[k.strip()] = v.strip()

    user_info = None
    if getattr(args, 'userinfo', None):
        user_info = load_user_info(args.userinfo)

    policies = load_policies_from_paths(args.paths)
    if not policies:
        print('no policies found')
        return 1
    resource_paths = args.resource or []
    resources = load_resources_from_paths(resource_paths)
    if not resources:
        print('no resources found')
        return 1

    # -o handling (reference: apply_command.go:298-318 checkMutateLogPath +
    # createFileOrFolder): a path whose last segment ends in .yml/.yaml is a
    # file — created (with parents) and truncated once per invocation, then
    # appended to; any other path is a directory — created if missing, and
    # each resource overwrites its own <name>-mutated.yaml inside it
    out_path = getattr(args, 'output', None)
    if out_path:
        try:
            if _mutate_path_is_dir(out_path):
                os.makedirs(out_path, exist_ok=True)
            else:
                parent = os.path.dirname(out_path)
                if parent:
                    os.makedirs(parent, exist_ok=True)
                open(out_path, 'w', encoding='utf-8').close()
        except OSError as exc:
            print(f'failed to create file/folder at {out_path}: {exc}')
            return 1

    rule_count = sum(
        len(p.spec.get('rules') or []) for p in policies)
    if not getattr(args, 'policy_report', False):
        print(f'\nApplying {len(policies)} policy rule(s) to '
              f'{len(resources)} resource(s)...\n')

    engine = Engine(context_loader=MockContextLoader(store))
    ns_map = values.namespace_selector_map()
    rc = ResultCounts()
    responses = []
    for policy in policies:
        for resource in resources:
            rname = (resource.get('metadata') or {}).get('name', '')
            variables = dict(values.global_values)
            variables.update(set_vars)
            variables.update(values.resource_values(policy.name, rname))
            result = apply_policy_on_resource(
                policy, resource, engine=engine, variables=variables,
                user_info=user_info, namespace_selector_map=ns_map,
                subresources=values.subresources)
            responses.extend(result.engine_responses)
            _count(result, rc, audit_warn=getattr(args, 'audit_warn', False))
            if getattr(args, 'output_mutate', True):
                _print_mutation(result, policy, resource, args)

    if getattr(args, 'policy_report', False):
        results: List[dict] = []
        for resp in responses:
            results.extend(engine_response_to_report_results(resp))
        sort_report_results(results)
        report = {
            'apiVersion': 'wgpolicyk8s.io/v1alpha2',
            'kind': 'ClusterPolicyReport',
            'metadata': {'name': 'clusterpolicyreport'},
            'results': results,
            'summary': calculate_summary(results),
        }
        print(yaml.safe_dump(report, sort_keys=False))
    else:
        for resp in responses:
            for rule in resp.policy_response.rules:
                if rule.status in (RuleStatus.FAIL, RuleStatus.ERROR):
                    pr = resp.policy_response
                    print(f'policy {pr.policy_name} -> resource '
                          f'{pr.resource_namespace}/{pr.resource_kind}/'
                          f'{pr.resource_name} failed: ')
                    print(f'{rule.name}: {rule.message}')
                    print()
    print(f'pass: {rc.pass_}, fail: {rc.fail}, warn: {rc.warn}, '
          f'error: {rc.error}, skip: {rc.skip}')
    return 1 if rc.fail or rc.error else 0


def _count(result, rc: ResultCounts, audit_warn: bool = False) -> None:
    for resp in result.engine_responses:
        audit = resp.get_validation_failure_action() == 'Audit' \
            if resp.policy is not None else False
        for rule in resp.policy_response.rules:
            if rule.status == RuleStatus.PASS:
                rc.pass_ += 1
            elif rule.status == RuleStatus.FAIL:
                if audit_warn and audit:
                    rc.warn += 1
                else:
                    rc.fail += 1
            elif rule.status == RuleStatus.WARN:
                rc.warn += 1
            elif rule.status == RuleStatus.ERROR:
                rc.error += 1
            elif rule.status == RuleStatus.SKIP:
                rc.skip += 1


def _mutate_path_is_dir(path: str) -> bool:
    """Extension-based dir/file split for -o (reference:
    apply_command.go:448 checkMutateLogPath — last dot-suffix of the last
    path segment must be yml/yaml for file mode)."""
    # no slash-stripping: "logs.yaml/" has last segment "" → directory,
    # exactly as the reference's strings.Split behaves
    last = path.split('/')[-1]
    return last.split('.')[-1] not in ('yml', 'yaml')


def _print_mutation(result, policy, resource, args) -> None:
    mutated = result.patched_resource
    if mutated is None or mutated == resource:
        return
    has_mutation = any(
        rule.rule_type == RuleType.MUTATION and rule.status == RuleStatus.PASS
        for resp in result.engine_responses
        for rule in resp.policy_response.rules)
    if not has_mutation:
        return
    text = yaml.safe_dump(mutated, sort_keys=False)
    rname = (resource.get('metadata') or {}).get('name', '')
    if getattr(args, 'output', None):
        # file mode appends within the run; dir mode overwrites one
        # <resource>-mutated.yaml per resource (reference:
        # utils/common/common.go:567-577 PrintMutatedOutput, filename from
        # common.go:934)
        path = args.output
        if _mutate_path_is_dir(path):
            path = os.path.join(path, f'{rname}-mutated.yaml')
            mode = 'w'
        else:
            mode = 'a'
        with open(path, mode, encoding='utf-8') as f:
            f.write(text + '\n---\n\n')
    else:
        print(f'\nmutate policy {policy.name} applied to '
              f'{resource.get("kind")}/{rname}:')
        sys.stdout.write(text + '\n---\n\n')
