"""``kyverno test`` — run YAML-defined fixtures and compare expected results.

Reference: cmd/cli/kubectl-kyverno/test/test_command.go — loads
``kyverno-test.yaml`` (policies, resources, variables, userinfo, results),
applies each policy to each resource through the engine with the mock
context loader, then checks every expected (policy, rule, resource) row
against the actual rule statuses (buildPolicyResults, test_command.go:430).
"""

from __future__ import annotations

import fnmatch
import os
from typing import Any, Dict, List, Optional, Tuple

import yaml

from ..autogen.autogen import compute_rules
from ..engine.api import EngineResponse, RuleStatus, RuleType
from ..engine.engine import Engine
from .common import (ApplyResult, MockContextLoader, Values,
                     apply_policy_on_resource, load_policies_from_paths,
                     load_resources_from_paths, load_user_info, load_values)
from .store import get_store, reset_store

TEST_FILE_NAMES = ('kyverno-test.yaml', 'kyverno-test.yml')


class TestCase:
    """One expected-result row (reference: test/api/types.go TestResults)."""

    def __init__(self, raw: dict):
        self.policy = raw.get('policy', '')
        self.rule = raw.get('rule', '')
        self.resource = raw.get('resource', '')
        self.resources = raw.get('resources') or []
        self.kind = raw.get('kind', '')
        self.namespace = raw.get('namespace', '')
        self.status = raw.get('status') or raw.get('result') or ''
        self.patched_resource = raw.get('patchedResource', '')
        self.generated_resource = raw.get('generatedResource', '')
        self.clone_source_resource = raw.get('cloneSourceResource', '')

    def target_resources(self) -> List[str]:
        return self.resources if self.resources else [self.resource]


class TestRow:
    def __init__(self, policy: str, rule: str, resource: str,
                 expected: str, actual: str):
        self.policy = policy
        self.rule = rule
        self.resource = resource
        self.expected = expected
        self.actual = actual

    @property
    def ok(self) -> bool:
        return self.expected == self.actual


def _load_yaml(path: str) -> dict:
    with open(path, encoding='utf-8') as f:
        return yaml.safe_load(f) or {}


def _load_expected_resource(path: str) -> dict:
    """Load an expected patched/generated resource with the same namespace
    defaulting the CLI applies to inputs (reference: fetch.go:310)."""
    doc = _load_yaml(path)
    meta = doc.setdefault('metadata', {})
    if not meta.get('namespace'):
        meta['namespace'] = 'default'
    return doc


def find_test_files(path: str) -> List[str]:
    """Recursively find kyverno-test.yaml files under ``path``."""
    out: List[str] = []
    if os.path.isfile(path):
        return [path]
    for root, _dirs, files in os.walk(path):
        for name in files:
            if name in TEST_FILE_NAMES:
                out.append(os.path.join(root, name))
    return sorted(out)


def run_test_file(test_file: str,
                  registry_access: bool = False) -> Tuple[str, List[TestRow]]:
    """Run one kyverno-test.yaml; returns (test name, result rows)."""
    base = os.path.dirname(os.path.abspath(test_file))
    doc = _load_yaml(test_file)
    name = doc.get('name', os.path.basename(base))
    store = reset_store()
    store.mock = True
    store.registry_access = registry_access

    values = Values()
    if doc.get('variables'):
        values = load_values(os.path.join(base, doc['variables']))
    store.set_policies(values.policies)
    store.subresources = values.subresources

    user_info = None
    if doc.get('userinfo'):
        user_info = load_user_info(os.path.join(base, doc['userinfo']))

    policies = load_policies_from_paths(
        [os.path.join(base, p) for p in doc.get('policies') or []])
    resources = load_resources_from_paths(
        [os.path.join(base, r) for r in doc.get('resources') or []])

    cases = [TestCase(r) for r in doc.get('results') or []]

    # CloneSourceResource per generate rule (test_command.go:720ish)
    rule_to_clone_source: Dict[str, dict] = {}
    for case in cases:
        if case.clone_source_resource:
            src = _load_yaml(os.path.join(base, case.clone_source_resource))
            if case.rule:
                rule_to_clone_source[case.rule] = src

    engine = Engine(context_loader=MockContextLoader(store))
    ns_map = values.namespace_selector_map()

    # (policy, kind, namespace, resource_name) -> ApplyResult
    applied: Dict[Tuple[str, str, str, str], ApplyResult] = {}
    for policy in policies:
        for resource in resources:
            meta = resource.get('metadata') or {}
            rname = meta.get('name', '')
            rkind = resource.get('kind', '')
            rns = meta.get('namespace', '')
            variables = dict(values.global_values)
            variables.update(values.resource_values(policy.name, rname))
            result = apply_policy_on_resource(
                policy, resource, engine=engine, variables=variables,
                user_info=user_info, namespace_selector_map=ns_map,
                rule_to_clone_source=rule_to_clone_source,
                subresources=values.subresources)
            applied[(policy.name, rkind, rns, rname)] = result

    unscored = {p.name for p in policies
                if (p.annotations or {}).get(
                    'policies.kyverno.io/scored') == 'false'}
    rows: List[TestRow] = []
    for case in cases:
        for target in case.target_resources():
            actual = _actual_status(case, target, applied, base)
            # reference: common.go:739 — scored=false downgrades fail→warn
            if actual == RuleStatus.FAIL and case.policy in unscored:
                actual = RuleStatus.WARN
            rows.append(TestRow(case.policy, case.rule, target,
                                case.status, actual))
    return name, rows


def _match_resource(case: TestCase, target: str,
                    applied: Dict[Tuple[str, str, str, str], ApplyResult]
                    ) -> Optional[ApplyResult]:
    candidates = []
    for (pname, kind, ns, rname), result in applied.items():
        if pname != case.policy or rname != target:
            continue
        if case.kind and kind != case.kind:
            continue
        if case.namespace and ns not in (case.namespace, ''):
            continue
        candidates.append(result)
    return candidates[0] if candidates else None


def _actual_status(case: TestCase, target: str,
                   applied: Dict[Tuple[str, str, str, str], ApplyResult],
                   base: str) -> str:
    result = _match_resource(case, target, applied)
    if result is None:
        return RuleStatus.SKIP
    rule_names = [r.name
                  for resp in result.engine_responses
                  for r in resp.policy_response.rules]
    rule_name = case.rule
    if rule_name not in rule_names:
        # reference: test_command.go:482 autogen rule name fallback
        if 'autogen-' + rule_name in rule_names:
            rule_name = 'autogen-' + rule_name
        elif 'autogen-cronjob-' + rule_name in rule_names:
            rule_name = 'autogen-cronjob-' + rule_name
        else:
            return RuleStatus.SKIP
    for resp in result.engine_responses:
        for rule in resp.policy_response.rules:
            if rule.name != rule_name:
                continue
            if rule.rule_type == RuleType.MUTATION:
                return _mutation_status(case, rule, result, base)
            if rule.rule_type == RuleType.GENERATION:
                return _generation_status(case, rule, base)
            return rule.status
    return RuleStatus.SKIP


def _mutation_status(case: TestCase, rule, result: ApplyResult,
                     base: str) -> str:
    # reference: test_command.go:578 mutation result comparison
    if rule.status in (RuleStatus.SKIP, RuleStatus.ERROR):
        return rule.status
    if not case.patched_resource:
        return rule.status
    try:
        expected = _load_expected_resource(
            os.path.join(base, case.patched_resource))
    except yaml.YAMLError:
        # unreadable expected resource compares as a failure
        # (reference: test_command.go getAndCompareResource load error)
        return RuleStatus.FAIL
    actual = result.patched_resource or {}
    return RuleStatus.PASS if _normalize(actual) == _normalize(expected) \
        else RuleStatus.FAIL


def _generation_status(case: TestCase, rule, base: str) -> str:
    # reference: test_command.go:545 generation result comparison
    if rule.status in (RuleStatus.SKIP, RuleStatus.ERROR):
        return rule.status
    if not case.generated_resource:
        return rule.status
    try:
        expected = _load_expected_resource(os.path.join(
            base, case.generated_resource))
    except yaml.YAMLError:
        return RuleStatus.FAIL
    actual = rule.generated_resource or {}
    return RuleStatus.PASS if _normalize(actual) == _normalize(expected) \
        else RuleStatus.FAIL


def _normalize(resource: Any) -> Any:
    """Drop fields the CLI strips before comparing
    (reference: test_command.go getAndCompareResource →
    common.GetResourceFromPath + unstructured cleanup)."""
    if isinstance(resource, dict):
        out = {}
        for k, v in resource.items():
            if k in ('status',):
                continue
            out[k] = _normalize(v)
        meta = out.get('metadata')
        if isinstance(meta, dict):
            for drop in ('creationTimestamp', 'resourceVersion', 'uid',
                         'generation', 'managedFields'):
                meta.pop(drop, None)
            if 'labels' in meta and isinstance(meta['labels'], dict):
                for label in list(meta['labels']):
                    if label.startswith(('policy.kyverno.io/',
                                         'generate.kyverno.io/',
                                         'app.kubernetes.io/managed-by',
                                         'kyverno.io/')):
                        meta['labels'].pop(label)
                if not meta['labels']:
                    meta.pop('labels')
        return out
    if isinstance(resource, list):
        return [_normalize(v) for v in resource]
    return resource


def format_rows(name: str, rows: List[TestRow],
                detailed_results: bool = False) -> str:
    lines = [f'Executing {name}...']
    width_p = max([len('POLICY')] + [len(r.policy) for r in rows])
    width_r = max([len('RULE')] + [len(r.rule) for r in rows])
    width_s = max([len('RESOURCE')] + [len(r.resource) for r in rows])
    lines.append(f'{"#":<4}{"POLICY":<{width_p + 2}}{"RULE":<{width_r + 2}}'
                 f'{"RESOURCE":<{width_s + 2}}RESULT')
    for i, row in enumerate(rows, 1):
        verdict = 'Pass' if row.ok else \
            f'Fail (expected {row.expected}, got {row.actual})'
        lines.append(f'{i:<4}{row.policy:<{width_p + 2}}'
                     f'{row.rule:<{width_r + 2}}'
                     f'{row.resource:<{width_s + 2}}{verdict}')
    return '\n'.join(lines)


def command(args) -> int:
    paths = args.paths or ['.']
    test_files: List[str] = []
    for p in paths:
        test_files.extend(find_test_files(p))
    if args.file_name and args.file_name not in TEST_FILE_NAMES:
        test_files = [f for f in test_files
                      if os.path.basename(f) == args.file_name] or [
            os.path.join(p, args.file_name) for p in paths]
    if not test_files:
        print('no test yamls available')
        return 1
    total = passed = 0
    failed_rows: List[TestRow] = []
    for tf in test_files:
        try:
            name, rows = run_test_file(
                tf, registry_access=getattr(args, 'registry', False))
        except Exception as exc:  # noqa: BLE001
            print(f'Error: failed to execute {tf}: {exc}')
            if getattr(args, 'debug', False):
                raise
            total += 1
            continue
        if args.test_case_selector:
            sel = dict(kv.split('=', 1)
                       for kv in args.test_case_selector.split(','))
            rows = [r for r in rows
                    if fnmatch.fnmatch(r.policy, sel.get('policy', '*')) and
                    fnmatch.fnmatch(r.rule, sel.get('rule', '*')) and
                    fnmatch.fnmatch(r.resource, sel.get('resource', '*'))]
        print(format_rows(name, rows))
        print()
        total += len(rows)
        passed += sum(r.ok for r in rows)
        failed_rows.extend(r for r in rows if not r.ok)
    print(f'Test Summary: {total} tests ({passed} passed, '
          f'{total - passed} failed)')
    if failed_rows:
        print('Aggregated Failed Test Cases:')
        for r in failed_rows:
            print(f'  {r.policy}/{r.rule}/{r.resource}: expected '
                  f'{r.expected}, got {r.actual}')
        return 1
    return 0
