"""``kyverno jp`` — JMESPath query/parse/function subcommands.

Reference: cmd/cli/kubectl-kyverno/jp/{query,parse,function} — a REPL-ish
debugger for the engine's JMESPath dialect (41 custom functions).
"""

from __future__ import annotations

import json
import sys

import yaml

from ..engine import jmespath as jp


def command_query(args) -> int:
    exprs = list(args.query or [])
    for qf in args.query_file or []:
        with open(qf, encoding='utf-8') as f:
            exprs.append(f.read().strip())
    if not exprs:
        print('no query given')
        return 1
    if args.input:
        with open(args.input, encoding='utf-8') as f:
            data = yaml.safe_load(f)
    else:
        data = yaml.safe_load(sys.stdin.read())
    for expr in exprs:
        try:
            compiled = jp.compile(expr)
        except jp.JMESPathError as exc:
            print(f'failed to compile query: {exc}')
            return 1
        try:
            result = compiled.search(data)
        except jp.JMESPathError as exc:
            print(f'failed to execute query: {exc}')
            return 1
        if len(exprs) > 1:
            print(f'# {expr}')
        if args.unquoted and isinstance(result, str):
            print(result)
        else:
            print(json.dumps(result, indent=2))
    return 0


def command_parse(args) -> int:
    from ..engine.jmespath.parser import parse
    exprs = list(args.expression or [])
    if not exprs:
        exprs = [sys.stdin.read().strip()]
    for expr in exprs:
        try:
            ast = parse(expr)
        except jp.JMESPathError as exc:
            print(f'failed to parse: {exc}')
            return 1
        print(_format_ast(ast))
    return 0


def _format_ast(node, indent: int = 0) -> str:
    pad = '  ' * indent
    ntype = node.get('type', '')
    value = node.get('value', '')
    children = node.get('children') or []
    line = f'{pad}{ntype}({value!r})'
    if children:
        inner = '\n'.join(_format_ast(c, indent + 1)
                          for c in children if isinstance(c, dict))
        return f'{line}\n{inner}' if inner else line
    return line


def command_function(args) -> int:
    from ..engine.jmespath.custom import register_custom_functions
    from ..engine.jmespath.interpreter import make_builtin_registry
    registry = register_custom_functions(make_builtin_registry())
    names = set(args.name or [])
    for fname in registry.names():
        if names and fname not in names:
            continue
        entry = registry._functions[fname]
        sig = ', '.join('|'.join(arg.get('types') or ['any'])
                        for arg in entry['signature'])
        if entry.get('variadic'):
            sig += ', ...'
        print(f'{fname}({sig})')
    return 0
