"""``kyverno oci`` — push/pull policies as OCI artifacts.

Reference: cmd/cli/kubectl-kyverno/oci/{oci.go,push,pull} — policies are
bundled as an OCI image whose layers carry the policy documents with the
kyverno media types.  The hermetic environment has no live registry, so
refs address an OCI image-layout directory store (the standard on-disk
registry format: ``oci-layout`` + ``index.json`` + ``blobs/sha256/...``)
— the same bytes a registry would serve, addressable by tag.

Media types match the reference's artifact shape:
  config: application/vnd.cncf.kyverno.config.v1+json
  layer:  application/vnd.cncf.kyverno.policy.layer.v1+yaml
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import List, Optional, Tuple

import yaml

CONFIG_MEDIA_TYPE = 'application/vnd.cncf.kyverno.config.v1+json'
POLICY_LAYER_MEDIA_TYPE = 'application/vnd.cncf.kyverno.policy.layer.v1+yaml'
MANIFEST_MEDIA_TYPE = 'application/vnd.oci.image.manifest.v1+json'


class OCILayout:
    """Minimal OCI image-layout store (spec v1.0.2 directory layout)."""

    def __init__(self, root: str):
        self.root = root

    # -- blob store ----------------------------------------------------------

    def _blob_path(self, digest: str) -> str:
        algo, hexd = digest.split(':', 1)
        return os.path.join(self.root, 'blobs', algo, hexd)

    def put_blob(self, data: bytes) -> Tuple[str, int]:
        digest = 'sha256:' + hashlib.sha256(data).hexdigest()
        path = self._blob_path(digest)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        if not os.path.exists(path):
            with open(path, 'wb') as f:
                f.write(data)
        return digest, len(data)

    def get_blob(self, digest: str) -> bytes:
        with open(self._blob_path(digest), 'rb') as f:
            data = f.read()
        check = 'sha256:' + hashlib.sha256(data).hexdigest()
        if check != digest:
            raise ValueError(f'blob {digest} corrupted (got {check})')
        return data

    # -- index ---------------------------------------------------------------

    def _index_path(self) -> str:
        return os.path.join(self.root, 'index.json')

    def read_index(self) -> dict:
        try:
            with open(self._index_path()) as f:
                return json.load(f)
        except FileNotFoundError:
            return {'schemaVersion': 2, 'manifests': []}

    def write_index(self, index: dict) -> None:
        os.makedirs(self.root, exist_ok=True)
        with open(os.path.join(self.root, 'oci-layout'), 'w') as f:
            json.dump({'imageLayoutVersion': '1.0.0'}, f)
        with open(self._index_path(), 'w') as f:
            json.dump(index, f, indent=1)

    def tag(self, tag: str, manifest_digest: str, size: int) -> None:
        index = self.read_index()
        index['manifests'] = [
            m for m in index['manifests']
            if (m.get('annotations') or {}).get(
                'org.opencontainers.image.ref.name') != tag]
        index['manifests'].append({
            'mediaType': MANIFEST_MEDIA_TYPE,
            'digest': manifest_digest, 'size': size,
            'annotations': {'org.opencontainers.image.ref.name': tag},
        })
        self.write_index(index)

    def resolve(self, tag: str) -> str:
        for m in self.read_index()['manifests']:
            if (m.get('annotations') or {}).get(
                    'org.opencontainers.image.ref.name') == tag:
                return m['digest']
        raise KeyError(f'tag {tag!r} not found in {self.root}')


def parse_ref(ref: str) -> Tuple[str, str]:
    """'dir:TAG' or a bare layout dir (tag 'latest')."""
    head, sep, tag = ref.rpartition(':')
    if sep and '/' not in tag and head:
        return head, tag
    return ref, 'latest'


def push(policy_paths: List[str], ref: str) -> str:
    """Bundle policy documents into the layout store; returns the
    manifest digest (reference: oci/push command)."""
    docs = []
    for path in policy_paths:
        files = []
        if os.path.isdir(path):
            for name in sorted(os.listdir(path)):
                if name.endswith(('.yaml', '.yml')):
                    files.append(os.path.join(path, name))
        else:
            files.append(path)
        for fp in files:
            with open(fp) as f:
                for doc in yaml.safe_load_all(f):
                    if doc and doc.get('kind') in (
                            'ClusterPolicy', 'Policy'):
                        docs.append(doc)
    if not docs:
        raise ValueError('no policies found to push')
    root, tag = parse_ref(ref)
    layout = OCILayout(root)
    layers = []
    for doc in docs:
        data = yaml.safe_dump(doc, sort_keys=False).encode()
        digest, size = layout.put_blob(data)
        layers.append({
            'mediaType': POLICY_LAYER_MEDIA_TYPE,
            'digest': digest, 'size': size,
            'annotations': {
                'io.kyverno.image.name':
                    (doc.get('metadata') or {}).get('name', ''),
                'io.kyverno.image.kind': doc.get('kind', ''),
            },
        })
    config = json.dumps({'policies': len(docs)}).encode()
    cfg_digest, cfg_size = layout.put_blob(config)
    manifest = json.dumps({
        'schemaVersion': 2,
        'mediaType': MANIFEST_MEDIA_TYPE,
        'config': {'mediaType': CONFIG_MEDIA_TYPE,
                   'digest': cfg_digest, 'size': cfg_size},
        'layers': layers,
    }, indent=1).encode()
    man_digest, man_size = layout.put_blob(manifest)
    layout.tag(tag, man_digest, man_size)
    return man_digest


def pull(ref: str, output_dir: str) -> List[str]:
    """Extract the bundle's policies into ``output_dir`` as YAML files;
    returns the written paths (reference: oci/pull command)."""
    root, tag = parse_ref(ref)
    layout = OCILayout(root)
    manifest = json.loads(layout.get_blob(layout.resolve(tag)))
    os.makedirs(output_dir, exist_ok=True)
    written = []
    used = set()
    for i, layer in enumerate(manifest.get('layers', [])):
        if layer.get('mediaType') != POLICY_LAYER_MEDIA_TYPE:
            continue
        data = layout.get_blob(layer['digest'])
        name = (layer.get('annotations') or {}).get(
            'io.kyverno.image.name') or f'policy-{i}'
        # the annotation is attacker-controlled content from the pulled
        # artifact: strip any path components so writes cannot escape
        # output_dir
        name = os.path.basename(name.replace('\\', '/')) or f'policy-{i}'
        # same-named policies (e.g. cluster + namespaced 'restrict') must
        # not overwrite each other
        if name in used:
            name = f'{name}-{i}'
        used.add(name)
        path = os.path.join(output_dir, f'{name}.yaml')
        with open(path, 'wb') as f:
            f.write(data)
        written.append(path)
    return written


def command_push(args) -> int:
    digest = push(args.paths, args.ref)
    print(f'pushed {args.ref} ({digest})')
    return 0


def command_pull(args) -> int:
    written = pull(args.ref, args.output or '.')
    for path in written:
        print(path)
    print(f'pulled {len(written)} policies from {args.ref}')
    return 0
