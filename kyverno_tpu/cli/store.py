"""CLI mock store: per-policy/rule variable values and mock toggles.

Reference: cmd/cli/kubectl-kyverno/utils/store/store.go — the CLI runs the
engine with a mock context loader whose variables come from the test's
values file rather than live cluster/API/registry calls.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional


class Store:
    def __init__(self):
        self.mock = False
        self.registry_access = False
        self.allow_api_calls = False
        # matches the reference's Go zero-value: store.ForeachElement is
        # never set by the CLI, so the mock loader always injects element 0
        self.foreach_element = 0
        # policy name -> rule name -> {key: value}
        self.rule_values: Dict[str, Dict[str, Dict[str, Any]]] = {}
        # policy name -> rule name -> {key: [values per foreach element]}
        self.foreach_values: Dict[str, Dict[str, Dict[str, List[Any]]]] = {}
        self.subresources: List[dict] = []

    def set_policies(self, policies: List[dict]) -> None:
        """Load the ``policies:`` section of a values file
        (reference: store.SetContext)."""
        for p in policies or []:
            name = p.get('name', '')
            for rule in p.get('rules') or []:
                self.rule_values.setdefault(name, {})[rule.get('name', '')] = \
                    rule.get('values') or {}
                if rule.get('foreachValues'):
                    self.foreach_values.setdefault(name, {})[
                        rule.get('name', '')] = rule['foreachValues']

    def get_policy_rule(self, policy: str, rule: str) -> Optional[Dict[str, Any]]:
        return (self.rule_values.get(policy) or {}).get(rule)

    def get_foreach_values(self, policy: str, rule: str
                           ) -> Optional[Dict[str, List[Any]]]:
        return (self.foreach_values.get(policy) or {}).get(rule)


_store = Store()


def get_store() -> Store:
    return _store


def reset_store() -> Store:
    global _store
    _store = Store()
    return _store
