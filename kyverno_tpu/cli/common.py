"""CLI engine harness: values files, mock context loader, apply loop.

Reference: cmd/cli/kubectl-kyverno/utils/common/common.go — notably
``ApplyPolicyOnResource`` (common.go:371): build a JSON context from the
resource + values-file variables, then run mutate → validate →
verifyImages → generate against a single (policy, resource) pair.
"""

from __future__ import annotations

import copy
import os
from typing import Any, Dict, List, Optional, Tuple

import yaml

from ..api.policy import (Policy, load_policies_from_yaml,
                          load_resources_from_yaml)
from ..autogen.autogen import compute_rules
from ..engine.api import EngineResponse, PolicyContext, RuleStatus
from ..engine.context import Context, ContextError, InvalidVariableError
from ..engine.engine import ContextLoader, Engine
from ..utils.image_extract import extract_images_from_resource
from .store import Store, get_store


class MockContextLoader(ContextLoader):
    """Loads per-rule variables from the CLI store instead of the cluster
    (reference: pkg/engine/jsonContext.go:88 mockContextLoader.Load)."""

    def __init__(self, store: Optional[Store] = None,
                 configmap_resolver=None, api_call=None, image_data=None):
        super().__init__(configmap_resolver=configmap_resolver,
                         api_call=api_call, image_data=image_data)
        self.store = store or get_store()

    def load(self, entries: List[dict], ctx: Context,
             policy_name: str = '', rule_name: str = '') -> None:
        rule_values = self.store.get_policy_rule(policy_name, rule_name)
        if rule_values:
            for key, value in rule_values.items():
                ctx.add_variable(key, value)
        for entry in entries:
            name = entry.get('name', '')
            if entry.get('imageRegistry') is not None:
                if self.store.registry_access and self.image_data is not None:
                    data = self.image_data(entry, ctx)
                    ctx.add_context_entry(name, data)
            elif entry.get('variable') is not None:
                self._load_variable(entry, ctx)
            elif entry.get('apiCall') is not None:
                if self.store.allow_api_calls:
                    if self.api_call is None:
                        raise ContextError(
                            f'failed to load context entry {name}: '
                            'no API client')
                    ctx.add_context_entry(name, self.api_call(entry, ctx))
            elif entry.get('configMap') is not None:
                if self.configmap_resolver is not None:
                    self._load_configmap(entry, ctx)
        foreach = self.store.get_foreach_values(policy_name, rule_name)
        if foreach:
            for key, values in foreach.items():
                ctx.add_variable(key, values[self.store.foreach_element])


class Values:
    """Parsed values file (reference: common.go:59 Values struct)."""

    def __init__(self, raw: Optional[dict] = None):
        raw = raw or {}
        self.policies: List[dict] = raw.get('policies') or []
        self.global_values: Dict[str, Any] = raw.get('globalValues') or {}
        self.namespace_selectors: List[dict] = \
            raw.get('namespaceSelector') or []
        self.subresources: List[dict] = raw.get('subresources') or []

    def namespace_selector_map(self) -> Dict[str, Dict[str, str]]:
        return {s.get('name', ''): s.get('labels') or {}
                for s in self.namespace_selectors}

    def resource_values(self, policy: str, resource: str) -> Dict[str, Any]:
        """Per-(policy, resource) variables (reference: common.go:300
        variables resolution in GetVariable)."""
        for p in self.policies:
            if p.get('name') != policy:
                continue
            for r in p.get('resources') or []:
                if r.get('name') == resource:
                    return dict(r.get('values') or {})
        return {}


def load_values(path: str) -> Values:
    with open(path, encoding='utf-8') as f:
        return Values(yaml.safe_load(f) or {})


def load_user_info(path: str) -> dict:
    """Load a RequestInfo YAML (reference:
    cmd/cli/kubectl-kyverno/utils/common/fetch.go GetUserInfoFromPath)."""
    with open(path, encoding='utf-8') as f:
        doc = yaml.safe_load(f) or {}
    user_info = doc.get('userInfo') or {}
    subject = doc.get('subject') or {}
    if subject and not user_info.get('username'):
        # reference: store.SetSubject + engine/utils.go:164 matchSubjects
        # mock — translate the subject into the equivalent username
        if subject.get('kind') == 'ServiceAccount':
            user_info['username'] = (
                f"system:serviceaccount:{subject.get('namespace', '')}:"
                f"{subject.get('name', '')}")
        elif subject.get('kind') in ('User', 'Group'):
            user_info['username'] = subject.get('name', '')
    return {
        'roles': doc.get('roles') or [],
        'clusterRoles': doc.get('clusterRoles') or [],
        'userInfo': user_info,
    }


def load_policies_from_paths(paths: List[str]) -> List[Policy]:
    out: List[Policy] = []
    for path in paths:
        if os.path.isdir(path):
            for entry in sorted(os.listdir(path)):
                if entry.endswith(('.yaml', '.yml', '.json')):
                    out.extend(load_policies_from_paths(
                        [os.path.join(path, entry)]))
            continue
        with open(path, encoding='utf-8') as f:
            loaded = load_policies_from_yaml(f.read())
        # reference: pkg/utils/yaml/loadpolicy.go:66 — namespaced Policy
        # defaults to "default"; ClusterPolicy namespace is cleared
        for policy in loaded:
            meta = policy.raw.setdefault('metadata', {})
            if policy.kind == 'Policy':
                if not meta.get('namespace'):
                    meta['namespace'] = 'default'
            else:
                meta.pop('namespace', None)
        out.extend(loaded)
    return out


def load_resources_from_paths(paths: List[str]) -> List[dict]:
    out: List[dict] = []
    for path in paths:
        if os.path.isdir(path):
            for entry in sorted(os.listdir(path)):
                if entry.endswith(('.yaml', '.yml', '.json')):
                    out.extend(load_resources_from_paths(
                        [os.path.join(path, entry)]))
            continue
        with open(path, encoding='utf-8') as f:
            docs = load_resources_from_yaml(f.read())
        from ..api.policy import is_kyverno_policy
        for doc in docs:
            if is_kyverno_policy(doc):
                continue
            # reference: fetch.go:310 — CLI resources default to "default"
            meta = doc.setdefault('metadata', {})
            if not meta.get('namespace'):
                meta['namespace'] = 'default'
            out.append(doc)
    return out


def _policy_uses_namespace_selector(policy: Policy) -> bool:
    # reference: common.go:381-412
    for rule in compute_rules(policy):
        match = rule.get('match') or {}
        exclude = rule.get('exclude') or {}
        for block in (match, exclude):
            if (block.get('resources') or {}).get('namespaceSelector'):
                return True
            for clause in (block.get('any') or []) + (block.get('all') or []):
                if (clause.get('resources') or {}).get('namespaceSelector'):
                    return True
    return False


class ApplyResult:
    def __init__(self):
        self.engine_responses: List[EngineResponse] = []
        self.patched_resource: Optional[dict] = None
        self.generated_resources: List[dict] = []


def apply_policy_on_resource(
        policy: Policy,
        resource: dict,
        engine: Optional[Engine] = None,
        variables: Optional[Dict[str, Any]] = None,
        user_info: Optional[dict] = None,
        namespace_selector_map: Optional[Dict[str, Dict[str, str]]] = None,
        subresource: str = '',
        rule_to_clone_source: Optional[Dict[str, dict]] = None,
        exceptions: Optional[List[dict]] = None,
        subresources: Optional[List[dict]] = None,
) -> ApplyResult:
    """reference: common.go:371 ApplyPolicyOnResource."""
    engine = engine or Engine(context_loader=MockContextLoader())
    variables = dict(variables or {})
    # reference: common.go:287 — request.operation defaults to CREATE
    if not variables.get('request.operation'):
        variables['request.operation'] = 'CREATE'
    out = ApplyResult()

    namespace_labels: Dict[str, str] = {}
    if _policy_uses_namespace_selector(policy):
        ns = (resource.get('metadata') or {}).get('namespace') or ''
        namespace_labels = (namespace_selector_map or {}).get(ns, {})

    operation_is_delete = variables.get('request.operation') == 'DELETE'

    ctx = Context()
    if operation_is_delete:
        ctx.add_old_resource(resource)
    else:
        ctx.add_resource(resource)
    for key, value in variables.items():
        ctx.add_variable(key, value)
    try:
        infos = extract_images_from_resource(resource)
        if infos:
            ctx.add_image_infos(
                {name: {k: i.to_dict() for k, i in group.items()}
                 for name, group in infos.items()})
    except Exception:  # noqa: BLE001 — kinds without extractors
        pass

    admission_info = user_info or {}
    pctx = PolicyContext(
        policy,
        new_resource=resource if not operation_is_delete else {},
        old_resource=resource if operation_is_delete else {},
        admission_info=admission_info,
        namespace_labels=namespace_labels,
        json_context=ctx,
        subresource=subresource,
        exceptions=exceptions or [],
        admission_operation=variables.get('request.operation', ''),
        subresources_in_policy=subresources or [],
    )
    if admission_info.get('userInfo'):
        ctx.add_user_info({'userInfo': admission_info['userInfo']})
        username = (admission_info['userInfo'] or {}).get('username', '')
        if username:
            ctx.add_service_account(username)

    has_mutate = any(r.get('mutate') for r in compute_rules(policy))
    patched = resource
    mutate_resp = None
    if has_mutate:
        mutate_resp = engine.mutate(pctx)
        out.engine_responses.append(mutate_resp)
        if mutate_resp.patched_resource is not None:
            patched = mutate_resp.patched_resource
    out.patched_resource = patched

    has_validate = any(r.get('validate') for r in compute_rules(policy))
    pctx = pctx.copy()
    pctx.new_resource = patched if not operation_is_delete else {}
    if not operation_is_delete:
        ctx.add_resource(patched)
    if has_validate:
        out.engine_responses.append(engine.validate(pctx))

    has_verify_images = any(r.get('verifyImages')
                            for r in compute_rules(policy))
    if has_verify_images:
        vresp, _ = engine.verify_and_patch_images(pctx)
        if not vresp.is_empty():
            out.engine_responses.append(vresp)

    has_generate = any(r.get('generate') for r in compute_rules(policy))
    if has_generate:
        gen_resp = engine.filter_background_rules(pctx)
        _simulate_generation(gen_resp, pctx, rule_to_clone_source or {})
        if not gen_resp.is_empty():
            out.engine_responses.append(gen_resp)
            for r in gen_resp.policy_response.rules:
                if r.generated_resource:
                    out.generated_resources.append(r.generated_resource)
    return out


def _simulate_generation(resp: EngineResponse, pctx: PolicyContext,
                         rule_to_clone_source: Dict[str, dict]) -> None:
    """Materialize generate-rule targets offline
    (reference: cmd/cli/kubectl-kyverno/utils/common/generate.go
    handleGeneratePolicy — runs the generate controller with a fake client
    seeded from CloneSourceResource)."""
    from ..background.generate import materialize_rule_offline
    for rule_resp in resp.policy_response.rules:
        if rule_resp.status != RuleStatus.PASS:
            continue
        raw_rule = None
        for r in compute_rules(pctx.policy):
            if r.get('name') == rule_resp.name and r.get('generate'):
                raw_rule = r
                break
        if raw_rule is None:
            continue
        try:
            generated = materialize_rule_offline(
                raw_rule, pctx,
                rule_to_clone_source.get(rule_resp.name))
            if generated is not None:
                rule_resp.generated_resource = generated
        except Exception as exc:  # noqa: BLE001
            rule_resp.status = RuleStatus.ERROR
            rule_resp.message = f'failed to generate resource: {exc}'
