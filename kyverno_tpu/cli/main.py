"""kubectl-kyverno CLI entry point.

Reference: cmd/cli/kubectl-kyverno/main.go:22 — subcommands ``apply``,
``test``, ``jp``, ``version``. Run as ``python -m kyverno_tpu.cli ...``.
"""

from __future__ import annotations

import argparse
import sys
from typing import List, Optional

from .. import __version__


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog='kyverno',
        description='Kyverno-TPU: batched policy evaluation for Kubernetes')
    sub = parser.add_subparsers(dest='command')

    p_apply = sub.add_parser(
        'apply', help='Apply policies to resources')
    p_apply.add_argument('paths', nargs='+', help='policy file(s) or dir(s)')
    p_apply.add_argument('--resource', '-r', action='append',
                         help='resource file path')
    p_apply.add_argument('--set', '-s', action='append',
                         help='variables key=value[,key=value]')
    p_apply.add_argument('--values-file', '-f', dest='values_file',
                         help='values file for variable substitution')
    p_apply.add_argument('--userinfo', '-u', help='admission info YAML')
    p_apply.add_argument('--policy-report', '-p', action='store_true',
                         dest='policy_report',
                         help='output a policy report')
    p_apply.add_argument('--audit-warn', action='store_true',
                         dest='audit_warn',
                         help='audit failures are warnings, not failures')
    p_apply.add_argument('--output', '-o', help='mutated resource output file')
    p_apply.add_argument('--registry', action='store_true',
                         help='allow image registry access')

    p_test = sub.add_parser(
        'test', help='Run kyverno-test.yaml fixtures')
    p_test.add_argument('paths', nargs='*', help='dirs with kyverno-test.yaml')
    p_test.add_argument('--file-name', '-f', dest='file_name',
                        default='kyverno-test.yaml',
                        help='test file name (default kyverno-test.yaml)')
    p_test.add_argument('--test-case-selector', '-t',
                        dest='test_case_selector',
                        help='filter, e.g. policy=name,rule=name,resource=x')
    p_test.add_argument('--registry', action='store_true',
                        help='allow image registry access')
    p_test.add_argument('--fail-only', action='store_true', dest='fail_only',
                        help='print only failed test cases')
    p_test.add_argument('--debug', action='store_true')

    p_jp = sub.add_parser('jp', help='JMESPath utilities')
    jp_sub = p_jp.add_subparsers(dest='jp_command')
    p_q = jp_sub.add_parser('query', help='evaluate a JMESPath query')
    p_q.add_argument('query', nargs='*', help='query expression(s)')
    p_q.add_argument('--input', '-i', help='input JSON/YAML file')
    p_q.add_argument('--query-file', '-q', action='append',
                     dest='query_file', help='read query from file')
    p_q.add_argument('--unquoted', '-u', action='store_true',
                     help='unquoted string output')
    p_p = jp_sub.add_parser('parse', help='print the parsed AST')
    p_p.add_argument('expression', nargs='*')
    p_fn = jp_sub.add_parser('function', help='list custom functions')
    p_fn.add_argument('name', nargs='*')

    p_oci = sub.add_parser(
        'oci', help='push/pull policies as OCI artifacts')
    oci_sub = p_oci.add_subparsers(dest='oci_command')
    p_push = oci_sub.add_parser('push', help='bundle policies to a ref')
    p_push.add_argument('paths', nargs='+',
                        help='policy file(s) or dir(s)')
    p_push.add_argument('--image', '-i', dest='ref', required=True,
                        help='layout-dir:tag destination ref')
    p_pull = oci_sub.add_parser('pull', help='extract policies from a ref')
    p_pull.add_argument('--image', '-i', dest='ref', required=True,
                        help='layout-dir:tag source ref')
    p_pull.add_argument('--output', '-o', help='output directory')

    sub.add_parser('version', help='print version')
    return parser


def main(argv: Optional[List[str]] = None) -> int:
    parser = build_parser()
    args = parser.parse_args(argv)
    if args.command == 'apply':
        from .apply_command import command
        return command(args)
    if args.command == 'test':
        from .test_command import command
        return command(args)
    if args.command == 'jp':
        from . import jp_command
        if args.jp_command == 'query':
            return jp_command.command_query(args)
        if args.jp_command == 'parse':
            return jp_command.command_parse(args)
        if args.jp_command == 'function':
            return jp_command.command_function(args)
        print('usage: kyverno jp {query,parse,function}')
        return 1
    if args.command == 'oci':
        from . import oci_command
        if args.oci_command == 'push':
            return oci_command.command_push(args)
        if args.oci_command == 'pull':
            return oci_command.command_pull(args)
        print('usage: kyverno oci {push,pull}')
        return 1
    if args.command == 'version':
        print(f'Version: {__version__}')
        return 0
    parser.print_help()
    return 0


if __name__ == '__main__':
    sys.exit(main())
