"""Partition-scoped compile/AOT key derivation.

The whole-set fingerprint (``aotcache/keys.py:policy_set_fingerprint``)
is the right identity for *provenance* — "which policy set served this
decision" — but the wrong identity for *executable cache keys*: one
edited policy in a 1k-policy enforce set changes the whole-set
fingerprint and invalidates every compiled executable (the 49–93s
``cache_warm_s`` wall on every churn event).

This module is the single sanctioned construction site for the
fingerprint an executable cache key may consume.  An evaluator built
over a partition's member policies gets a fingerprint derived from
*those members only* — editing any other policy leaves it (and every
AOT entry keyed under it) untouched.  ktpu-lint **KTPU508** enforces
the boundary: ``executable_cache_key`` callers outside
``kyverno_tpu/partition/`` must not feed it a whole-set
``policy_set_fingerprint(...)`` result.
"""

from __future__ import annotations

from typing import Iterable

from ..aotcache.keys import policy_set_fingerprint


def compile_fingerprint(cps) -> str:
    """The fingerprint executable cache keys are derived from.

    For a :class:`CompiledPolicySet` over a partition's member policies
    this is the *partition* fingerprint — stable under edits to any
    policy outside the partition.  For a whole-set compile (the
    ``KTPU_PARTITIONS=0`` monolithic oracle) it degenerates to the
    whole-set fingerprint, preserving every existing AOT key."""
    return policy_set_fingerprint(cps.policies)


def partition_fingerprint(policies: Iterable) -> str:
    """Fingerprint of one partition's member policies, in membership
    order.  Identical inputs across processes yield identical AOT keys,
    so a second process warm-loads untouched partitions from disk."""
    return policy_set_fingerprint(list(policies))
