"""Merge per-partition compact verdict buffers into the whole-set
verdict contract.

Per-rule compilation is independent — a rule lowers to the same
``RuleProgram`` whether its policy is compiled alone or inside the full
set — so a partition's program list is value-identical to the whole-set
program list restricted to its members.  Composition is therefore pure
index bookkeeping: scatter each partition's program columns into the
global column order, and remap each partition's anyPattern auxiliary
fdet blocks (local base offsets) onto the whole-set evaluator's aux
layout.  No verdict value is ever recomputed or approximated, which is
what makes ``KTPU_PARTITIONS=N`` bit-identical to the
``KTPU_PARTITIONS=0`` oracle.

Both mappings are validated eagerly at construction; any mismatch
raises :class:`PartitionError` and the scanner falls back to the
monolithic path rather than risk a wrong verdict.
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import numpy as np

from .plan import PartitionError


class Composer:
    """Precomputed scatter maps from per-partition output buffers into
    whole-set ``(statuses, details, fdet)`` buffers."""

    def __init__(self, whole_evaluator, runtimes: Sequence) -> None:
        self.n_programs = int(whole_evaluator.n_programs)
        self.n_cols = int(whole_evaluator.n_cols)
        whole_meta = dict(whole_evaluator.any_meta or {})
        self._runtimes = tuple(runtimes)
        self._prog_cols: List[np.ndarray] = []
        self._aux_src: List[np.ndarray] = []
        self._aux_dst: List[np.ndarray] = []

        covered = np.zeros(self.n_programs, bool)
        aux_covered = set()
        for rt in self._runtimes:
            cols = np.asarray(rt.prog_cols, np.int64)
            if cols.size and (cols.min() < 0 or
                              cols.max() >= self.n_programs):
                raise PartitionError(
                    f'partition {rt.part.pid}: program column out of '
                    f'range [0, {self.n_programs})')
            if covered[cols].any():
                raise PartitionError(
                    f'partition {rt.part.pid}: program column claimed '
                    f'by two partitions')
            covered[cols] = True
            self._prog_cols.append(cols)

            p_k = int(rt.evaluator.n_programs)
            local_meta = dict(rt.evaluator.any_meta or {})
            src, dst = [], []
            for lj, (lbase, cnt) in sorted(local_meta.items()):
                gj = int(cols[lj])
                gmeta = whole_meta.get(gj)
                if gmeta is None or gmeta[1] != cnt:
                    raise PartitionError(
                        f'partition {rt.part.pid}: aux block for local '
                        f'program {lj} (global {gj}) does not match the '
                        f'whole-set layout')
                src.extend(range(p_k + lbase, p_k + lbase + cnt))
                dst.extend(range(self.n_programs + gmeta[0],
                                 self.n_programs + gmeta[0] + cnt))
                aux_covered.add(gj)
            self._aux_src.append(np.asarray(src, np.int64))
            self._aux_dst.append(np.asarray(dst, np.int64))

        if not covered.all():
            missing = int((~covered).sum())
            raise PartitionError(
                f'{missing} whole-set program column(s) owned by no '
                f'partition')
        stray = set(whole_meta) - aux_covered
        if stray:
            raise PartitionError(
                f'whole-set aux blocks for programs {sorted(stray)} '
                f'owned by no partition')

    def compose(self, parts_out: Sequence[Tuple[np.ndarray, np.ndarray,
                                                np.ndarray]],
                rows: int):
        """Scatter per-partition ``(s_k, d_k, fd_k)`` buffers (aligned
        with the runtimes this composer was built over) into whole-set
        buffers.  fdet cells default to -1, the 'materialize on host'
        sentinel — coverage validation guarantees every live cell is
        overwritten, so the default is only visible to code that never
        reads it."""
        s = np.zeros((rows, self.n_programs), np.int8)
        d = np.zeros((rows, self.n_programs), np.int8)
        fd = np.full((rows, self.n_cols), -1, np.int32)
        for i, (s_k, d_k, fd_k) in enumerate(parts_out):
            cols = self._prog_cols[i]
            p_k = cols.size
            s[:, cols] = s_k
            d[:, cols] = d_k
            fd[:, cols] = fd_k[:, :p_k]
            if self._aux_src[i].size:
                fd[:, self._aux_dst[i]] = fd_k[:, self._aux_src[i]]
        return s, d, fd
