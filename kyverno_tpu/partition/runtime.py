"""Per-partition compile + evaluator lifecycle.

``build_runtime`` compiles each partition's member policies into its
own :class:`CompiledPolicySet` and evaluator.  The evaluator's compile
and AOT keys derive from the *partition* fingerprint
(``partition/keys.py``), so:

* editing a policy recompiles only its own partition — every other
  partition's evaluator is reused verbatim from the in-process cache
  below (zero retrace, zero recompile), and across processes its
  executables warm-load from the AOT store under unchanged keys;
* the executable ledger tags each record with the partition
  fingerprint, which is what lets ``partition/census.py`` attribute
  executables to partitions.

Every structural assumption (per-rule compile independence: the
partition's program list must be value-identical to the whole-set list
restricted to its members) is validated here; a mismatch raises
:class:`PartitionError` and the caller falls back to the monolithic
path.
"""

from __future__ import annotations

import os
import threading
from collections import OrderedDict
from dataclasses import dataclass
from typing import Dict, List, Sequence, Tuple

import numpy as np

from .plan import (PartitionError, PartitionPlan, Partition, build_plan)

PARTITION_COUNT = 'kyverno_tpu_partition_count'
PARTITION_RECOMPILES = 'kyverno_tpu_partition_recompiles_total'
PARTITION_REUSES = 'kyverno_tpu_partition_evaluator_reuses_total'
PARTITION_FALLBACKS = 'kyverno_tpu_partition_fallbacks_total'


def _reg():
    from ..observability.metrics import global_registry
    return global_registry()


def _eval_cache_max() -> int:
    try:
        return max(0, int(os.environ.get(
            'KTPU_PARTITION_EVAL_CACHE', '128') or 0))
    except ValueError:
        return 128


# in-process evaluator cache keyed by partition fingerprint: untouched
# partitions across scanner rebuilds (policy churn, handler hot-swap)
# reuse the same evaluator object — its internal executable cache, AOT
# entries and ledger records all carry over
_cache_lock = threading.Lock()
_EVAL_CACHE: 'OrderedDict[str, Tuple[object, object]]' = OrderedDict()


def clear_eval_cache() -> None:
    with _cache_lock:
        _EVAL_CACHE.clear()


def eval_cache_size() -> int:
    with _cache_lock:
        return len(_EVAL_CACHE)


@dataclass
class PartitionRuntime:
    """One live partition: its compiled subset, evaluator, and the
    local→global program-column map the composer scatters through."""
    part: Partition
    sub_cps: object
    evaluator: object
    prog_cols: np.ndarray
    reused: bool = False

    @property
    def adm(self):
        return getattr(self.evaluator, 'adm_table', None)


@dataclass
class PartitionedSet:
    """The full partitioned compile of one policy set."""
    plan: PartitionPlan
    runtimes: Tuple[PartitionRuntime, ...]
    set_fingerprint: str = ''

    def recompiled(self) -> List[int]:
        return [rt.part.pid for rt in self.runtimes if not rt.reused]


def _programs_by_policy(cps) -> Dict[int, List[int]]:
    by_pol: Dict[int, List[int]] = {}
    for j, prog in enumerate(cps.programs):
        by_pol.setdefault(prog.policy_index, []).append(j)
    return by_pol


def _map_prog_cols(part: Partition, sub_cps, whole_cps) -> np.ndarray:
    """local program index -> whole-set program column, validated
    pairwise on (rule_name, rule_index) — per-rule compile independence
    made checkable."""
    local_by_pol = _programs_by_policy(sub_cps)
    whole_by_pol = _programs_by_policy(whole_cps)
    cols = np.empty(len(sub_cps.programs), np.int64)
    for m, g in enumerate(part.policy_indices):
        ljs = local_by_pol.get(m, [])
        gjs = whole_by_pol.get(g, [])
        if len(ljs) != len(gjs):
            raise PartitionError(
                f'partition {part.pid}: policy {g} lowered to '
                f'{len(ljs)} programs alone vs {len(gjs)} in the set')
        for lj, gj in zip(ljs, gjs):
            lp, gp = sub_cps.programs[lj], whole_cps.programs[gj]
            if (lp.rule_name, lp.rule_index) != \
                    (gp.rule_name, gp.rule_index):
                raise PartitionError(
                    f'partition {part.pid}: program order diverged for '
                    f'policy {g} rule {gp.rule_name!r}')
            cols[lj] = gj
    return cols


def _acquire(part: Partition, members: Sequence) -> Tuple[object, object,
                                                          bool]:
    """(sub_cps, evaluator, reused) for one partition, via the
    fingerprint-keyed evaluator cache."""
    with _cache_lock:
        hit = _EVAL_CACHE.get(part.fingerprint)
        if hit is not None:
            _EVAL_CACHE.move_to_end(part.fingerprint)
            return hit[0], hit[1], True
    from ..compiler.compile import compile_policies
    from ..ops.eval import build_evaluator
    sub_cps = compile_policies(list(members))
    evaluator = build_evaluator(sub_cps)
    with _cache_lock:
        _EVAL_CACHE[part.fingerprint] = (sub_cps, evaluator)
        limit = _eval_cache_max()
        while limit and len(_EVAL_CACHE) > limit:
            _EVAL_CACHE.popitem(last=False)
    return sub_cps, evaluator, False


def build_runtime(policies: Sequence, whole_cps, n_parts: int,
                  set_fingerprint: str = '') -> PartitionedSet:
    """Partition ``policies`` and compile (or reuse) each partition's
    evaluator.  ``whole_cps`` is the monolithic compile the scanner
    already built — the source of truth the per-partition program maps
    are validated against."""
    plan = build_plan(policies, n_parts)
    runtimes = []
    reused = 0
    for part in plan.partitions:
        members = [policies[i] for i in part.policy_indices]
        sub_cps, evaluator, hit = _acquire(part, members)
        if not sub_cps.programs:
            # host-only partition: no device programs to own; the
            # whole-set host matcher handles its policies
            continue
        cols = _map_prog_cols(part, sub_cps, whole_cps)
        runtimes.append(PartitionRuntime(
            part=part, sub_cps=sub_cps, evaluator=evaluator,
            prog_cols=cols, reused=hit))
        reused += 1 if hit else 0
    reg = _reg()
    if reg is not None:
        fresh = len(runtimes) - reused
        if fresh:
            reg.inc(PARTITION_RECOMPILES, float(fresh))
        if reused:
            reg.inc(PARTITION_REUSES, float(reused))
        # live partition-runtime occupancy: must read 0 once drained
        reg.mark_reset_on_close(PARTITION_COUNT)
        reg.set_gauge(PARTITION_COUNT, float(len(runtimes)))
    return PartitionedSet(plan=plan, runtimes=tuple(runtimes),
                          set_fingerprint=set_fingerprint)
