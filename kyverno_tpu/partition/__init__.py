"""Partitioned policy-set compilation (``KTPU_PARTITIONS``).

Splits a policy set into stable per-group partitions, each with its own
fingerprint and AOT keys derived from only its member policies, so
policy churn recompiles one partition instead of the world.  See
``plan.py`` (grouping + differ), ``runtime.py`` (per-partition
compile/evaluator lifecycle), ``compose.py`` (bit-identical merge back
into the whole-set verdict contract), ``census.py``
(``/debug/partitions``), and ``keys.py`` (the only sanctioned
fingerprint source for executable cache keys — enforced by ktpu-lint
KTPU508).
"""

from .keys import compile_fingerprint, partition_fingerprint
from .plan import (ChurnDiff, Partition, PartitionError, PartitionPlan,
                   build_plan, coupling_signature, diff_plans,
                   env_partitions)
from . import census

__all__ = [
    'ChurnDiff', 'Partition', 'PartitionError', 'PartitionPlan',
    'build_plan', 'coupling_signature', 'diff_plans', 'env_partitions',
    'compile_fingerprint', 'partition_fingerprint', 'census',
    'Composer', 'PartitionRuntime', 'PartitionedSet', 'build_runtime',
    'clear_eval_cache',
]

_LAZY = {
    'Composer': 'compose',
    'PartitionRuntime': 'runtime',
    'PartitionedSet': 'runtime',
    'build_runtime': 'runtime',
    'clear_eval_cache': 'runtime',
    'eval_cache_size': 'runtime',
}


def __getattr__(name):
    # runtime/compose pull in the compiler + ops stack; loaded on first
    # use so `from ..partition.keys import compile_fingerprint` inside
    # ops/eval.py never cycles
    mod = _LAZY.get(name)
    if mod is None:
        raise AttributeError(name)
    import importlib
    return getattr(importlib.import_module(f'.{mod}', __name__), name)
