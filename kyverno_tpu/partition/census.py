"""Partition census: which partitions are live, which executables they
own, and the hot-swap history — the data behind ``/debug/partitions``.

The executable ledger (``observability/executables.py``) records every
executable with the fingerprint of the evaluator that built it.  In
partitioned mode that is the *partition* fingerprint, so joining the
ledger against the registered plans attributes each executable — and
its dispatch/device-time/build-time totals — to the partition that owns
it.  Records that match no registered partition (monolithic evaluators,
stale generations) are reported under ``unattributed``.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Deque, Dict, Optional

_lock = threading.Lock()
#: set fingerprint -> {'plan': PartitionPlan, 'serial': int, 'ts': float}
_plans: Dict[str, dict] = {}
_swaps: Deque[dict] = deque(maxlen=64)


def record_plan(set_fingerprint: str, plan, serial: Optional[int] = None,
                diff=None) -> None:
    """Register the partition plan a scanner was built from."""
    with _lock:
        _plans[set_fingerprint] = {
            'plan': plan,
            'serial': serial,
            'ts': time.time(),
            'diff': diff.to_dict() if diff is not None else None,
        }
        while len(_plans) > 16:
            oldest = min(_plans, key=lambda k: _plans[k]['ts'])
            del _plans[oldest]


def record_swap(kind: str, old_serial, new_serial,
                breaker_state: Optional[str] = None,
                touched=None) -> None:
    """Log one live scanner hot-swap (for ``/debug/partitions``)."""
    with _lock:
        _swaps.append({
            'ts': time.time(),
            'kind': kind,
            'old_serial': old_serial,
            'new_serial': new_serial,
            'breaker_state': breaker_state,
            'touched_partitions': list(touched) if touched else None,
        })


def reset() -> None:
    with _lock:
        _plans.clear()
        _swaps.clear()


def report() -> dict:
    """Join registered plans against the executable ledger."""
    from ..observability import executables as exe
    with _lock:
        plans = dict(_plans)
        swaps = list(_swaps)

    by_fp: Dict[str, dict] = {}
    records = exe.ledger().records() if exe.enabled() else []
    for rec in records:
        row = by_fp.setdefault(rec.fingerprint, {
            'executables': 0, 'dispatches': 0,
            'device_s': 0.0, 'build_s': 0.0, 'by_source': {}})
        row['executables'] += 1
        row['dispatches'] += rec.dispatches
        row['device_s'] += rec.device_s
        row['build_s'] += rec.build_s
        row['by_source'][rec.source] = \
            row['by_source'].get(rec.source, 0) + 1

    sets = []
    claimed = set()
    for set_fp, info in sorted(plans.items(),
                               key=lambda kv: kv[1]['ts'], reverse=True):
        plan = info['plan']
        parts = []
        for part in plan.partitions:
            exe_row = by_fp.get(part.fingerprint)
            if exe_row is not None:
                claimed.add(part.fingerprint)
            parts.append({**part.to_dict(),
                          'executables': exe_row or {
                              'executables': 0, 'dispatches': 0,
                              'device_s': 0.0, 'build_s': 0.0,
                              'by_source': {}}})
        sets.append({'set_fingerprint': set_fp,
                     'serial': info['serial'],
                     'n_parts': plan.n_parts,
                     'n_partitions': len(plan.partitions),
                     'last_diff': info['diff'],
                     'partitions': parts})

    unattributed = {fp: row for fp, row in by_fp.items()
                    if fp not in claimed}
    return {'sets': sets,
            'swaps': swaps,
            'unattributed': {
                'fingerprints': len(unattributed),
                'executables': sum(r['executables']
                                   for r in unattributed.values()),
                'dispatches': sum(r['dispatches']
                                  for r in unattributed.values())}}
