"""Stable policy-set partitioning + the churn differ.

A **partition plan** splits a policy set into at most ``KTPU_PARTITIONS``
buckets.  The grouping key is the policy's *coupling signature* — the
resource-kind vocabulary its match/exclude blocks reference plus the
validation lowering families its rules use — sharded by the policy's
identity (``namespace/name``) through sha256.  Two properties follow:

* **Stability** — a policy keeps its bucket as long as its vocabulary
  and identity are unchanged; editing one rule's pattern or message
  touches exactly one partition's fingerprint.  sha256 (not Python
  ``hash()``, which is salted per process) keeps the assignment
  identical across processes, so a second process derives the same
  partition fingerprints and warm-loads untouched partitions from the
  AOT store.
* **Affinity** — policies sharing a vocabulary signature hash from a
  common prefix, so coupled policies (same kinds, same lowering shape)
  tend to co-locate, keeping per-partition encode vocabularies small.

Correctness never depends on the grouping: the composition layer
(``partition/compose.py``) merges per-partition verdict buffers into
the whole-set contract bit-identically for *any* assignment.

The **differ** maps a policy add/update/delete to the partitions it
touches: partitions present in both plans with equal fingerprints are
untouched (their executables, ledger records and verdict generations
carry over); everything else recompiles.
"""

from __future__ import annotations

import hashlib
import json
import os
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

#: validation keys that select a lowering family — part of the coupling
#: signature (policies lowered the same way share compiled structure)
_VALIDATE_FAMILIES = ('pattern', 'anyPattern', 'deny', 'foreach',
                      'podSecurity', 'cel')


class PartitionError(Exception):
    """The partition plan or runtime could not be validated against the
    whole-set compile; callers fall back to the monolithic path."""


def env_partitions() -> int:
    """``KTPU_PARTITIONS``: number of partition buckets (0 = off, the
    monolithic oracle)."""
    try:
        return max(0, int(os.environ.get('KTPU_PARTITIONS', '0') or 0))
    except ValueError:
        return 0


def _iter_clause_kinds(block: dict):
    for clause in [block] + list(block.get('any') or []) + \
            list(block.get('all') or []):
        res = (clause or {}).get('resources') or {}
        for k in res.get('kinds') or []:
            yield str(k)


def coupling_signature(policy) -> str:
    """The vocabulary half of the bucket key: sorted match/exclude
    resource kinds + the validation lowering families the rules use.
    A JSON string so it is hashable, diffable and process-stable."""
    spec = (getattr(policy, 'raw', None) or {}).get('spec') or {}
    kinds = set()
    families = set()
    for rule in spec.get('rules') or []:
        if not isinstance(rule, dict):
            continue
        for part in ('match', 'exclude'):
            kinds.update(_iter_clause_kinds(rule.get(part) or {}))
        validate = rule.get('validate') or {}
        families.update(f for f in _VALIDATE_FAMILIES if f in validate)
    return json.dumps([sorted(kinds), sorted(families)],
                      separators=(',', ':'))


def _bucket(policy, n_parts: int) -> int:
    ident = f'{policy.namespace}/{policy.name}'
    key = coupling_signature(policy) + '\x00' + ident
    return int(hashlib.sha256(key.encode()).hexdigest()[:12], 16) % n_parts


@dataclass(frozen=True)
class Partition:
    """One bucket of the plan: member policies (global indices in set
    order) and the fingerprint their compile keys derive from."""
    pid: int
    policy_indices: Tuple[int, ...]
    fingerprint: str

    def to_dict(self) -> dict:
        return {'pid': self.pid,
                'fingerprint': self.fingerprint,
                'n_policies': len(self.policy_indices)}


@dataclass(frozen=True)
class PartitionPlan:
    """The full assignment: ``partitions`` holds the non-empty buckets
    in pid order; ``assignment[i]`` is policy *i*'s pid."""
    n_parts: int
    partitions: Tuple[Partition, ...]
    assignment: Tuple[int, ...]

    def by_pid(self) -> Dict[int, Partition]:
        return {p.pid: p for p in self.partitions}

    def members(self, policies: Sequence, pid: int) -> List:
        part = self.by_pid().get(pid)
        if part is None:
            return []
        return [policies[i] for i in part.policy_indices]


def build_plan(policies: Sequence, n_parts: int) -> PartitionPlan:
    """Deterministic plan over ``policies``.  Membership order within a
    bucket follows global set order, so an untouched bucket's member
    list — and therefore its fingerprint and every local index stored
    against it — is reproducible across processes and across churn."""
    from .keys import partition_fingerprint
    if n_parts <= 0:
        raise PartitionError('n_parts must be positive')
    assignment = [_bucket(p, n_parts) for p in policies]
    buckets: Dict[int, List[int]] = {}
    for i, pid in enumerate(assignment):
        buckets.setdefault(pid, []).append(i)
    partitions = tuple(
        Partition(pid=pid, policy_indices=tuple(idxs),
                  fingerprint=partition_fingerprint(
                      [policies[i] for i in idxs]))
        for pid, idxs in sorted(buckets.items()))
    return PartitionPlan(n_parts=n_parts, partitions=partitions,
                         assignment=tuple(assignment))


@dataclass(frozen=True)
class ChurnDiff:
    """Which partitions a policy-set change touches.  ``touched`` pids
    must recompile (fingerprint changed, bucket appeared, or bucket
    emptied); ``unchanged`` pids keep their executables, ledger records
    and verdict-cache generations."""
    touched: Tuple[int, ...]
    unchanged: Tuple[int, ...]

    def to_dict(self) -> dict:
        return {'touched': list(self.touched),
                'unchanged': list(self.unchanged)}


def diff_plans(old: Optional[PartitionPlan],
               new: PartitionPlan) -> ChurnDiff:
    """Map a policy-set change to touched partitions by fingerprint.
    ``old=None`` (first build) touches everything."""
    new_by = new.by_pid()
    if old is None:
        return ChurnDiff(touched=tuple(sorted(new_by)), unchanged=())
    old_by = old.by_pid()
    touched = []
    unchanged = []
    for pid in sorted(set(old_by) | set(new_by)):
        a, b = old_by.get(pid), new_by.get(pid)
        if a is not None and b is not None and \
                a.fingerprint == b.fingerprint:
            unchanged.append(pid)
        else:
            touched.append(pid)
    return ChurnDiff(touched=tuple(touched), unchanged=tuple(unchanged))
