"""Mutate-existing processing (reference: pkg/background/mutate/mutate.go).

Applies mutate rules carrying ``targets:`` to already-admitted cluster
resources when a trigger event fires.
"""

from __future__ import annotations

import json
from typing import List, Optional

from ..api.policy import Policy, Rule
from ..engine.api import PolicyContext, RuleStatus
from ..engine.background import is_mutate_existing
from ..engine.context import Context
from ..dclient.client import NotFoundError
from ..engine.variables import substitute_all
from .common import get_policy, get_trigger_resource, new_background_context
from .updaterequest import STATE_COMPLETED, STATE_FAILED, UpdateRequest

MUTATE_LAST_APPLIED_ANNOTATION = 'policies.kyverno.io/last-applied-patches'


class MutateExistingController:
    """reference: pkg/background/mutate/mutate.go:46"""

    def __init__(self, client, engine, policy_getter=None):
        self.client = client
        self.engine = engine
        self.policy_getter = policy_getter or (
            lambda key: get_policy(client, key))

    def process_ur(self, ur: UpdateRequest) -> Optional[Exception]:
        """reference: mutate.go:73 ProcessUR"""
        errs: List[str] = []
        try:
            policy = self.policy_getter(ur.policy_key)
        except Exception as exc:  # noqa: BLE001
            ur.set_status(STATE_FAILED, str(exc))
            return exc
        if policy is None:
            err = NotFoundError(f'policy {ur.policy_key!r} not found')
            ur.set_status(STATE_FAILED, str(err))
            return err
        rules = [r for r in (policy.spec.get('rules') or [])
                 if is_mutate_existing(Rule(r))]
        pctx = None
        if rules:
            try:
                trigger = get_trigger_resource(self.client, ur)
            except Exception as exc:  # noqa: BLE001
                ur.set_status(STATE_FAILED, str(exc))
                return exc
            if trigger is None:
                # DELETE triggers resolve from the admission request's
                # old object (reference: pkg/background/common/
                # context.go:50 — trigger = &old when nil)
                old = (ur.admission_request or {}).get('oldObject')
                if isinstance(old, dict) and old:
                    trigger = old
            if trigger is not None:
                pctx = new_background_context(self.client, ur, policy, trigger)
        if pctx is not None:
            from ..api.unstructured import Resource
            from ..engine.match import matches_resource_description
            from ..engine.mutate.mutate import _check_preconditions
            for raw_rule in rules:
                rule = Rule(raw_rule)
                # the trigger must actually select this rule before any
                # target is touched (reference: mutate.go:80 ProcessUR →
                # engine.Mutate, whose rule loop match/precondition-gates)
                if matches_resource_description(
                        Resource(pctx.new_resource), rule,
                        pctx.admission_info, pctx.exclude_group_roles,
                        pctx.namespace_labels,
                        policy.namespace) is not None:
                    continue
                try:
                    # rule context loads BEFORE preconditions, exactly
                    # like the engine mutate loop (mutate.py:185)
                    self.engine.context_loader.load(
                        rule.context, pctx.json_context,
                        policy_name=policy.name, rule_name=rule.name)
                    if not _check_preconditions(pctx, rule.preconditions):
                        continue
                except Exception as exc:  # noqa: BLE001
                    errs.append(f'{rule.name}: failed to evaluate '
                                f'preconditions: {exc}')
                    continue
                errs.extend(
                    self._mutate_targets(pctx, rule, raw_rule, policy, ur))
        if errs:
            msg = '; '.join(errs)
            ur.set_status(STATE_FAILED, msg)
            return RuntimeError(msg)
        ur.set_status(STATE_COMPLETED)
        return None

    def _mutate_targets(self, pctx: PolicyContext, rule: Rule,
                        raw_rule: dict, policy: Policy,
                        ur: UpdateRequest) -> List[str]:
        """Resolve each target spec, run the mutation against the target
        with ``target`` bound in the JSON context, and persist the patched
        object (reference: mutate.go:102-170 + engine mutate target
        loading)."""
        errs: List[str] = []
        ctx = pctx.json_context
        for target in rule.mutation.get('targets') or []:
            ctx.checkpoint()
            try:
                resolved = substitute_all(ctx, dict(target))
                api_version = resolved.get('apiVersion', '')
                kind = resolved.get('kind', '')
                name = resolved.get('name', '')
                namespace = resolved.get('namespace', '')
                candidates = self._resolve_targets(
                    api_version, kind, namespace, name)
                for obj in candidates:
                    err = self._mutate_one(pctx, rule, raw_rule, policy, obj)
                    if err:
                        errs.append(err)
            except Exception as exc:  # noqa: BLE001
                errs.append(f'{rule.name}: {exc}')
            finally:
                ctx.restore()
        return errs

    def _resolve_targets(self, api_version: str, kind: str, namespace: str,
                         name: str) -> List[dict]:
        if name and '*' not in name:
            try:
                return [self.client.get_resource(
                    api_version, kind, namespace, name)]
            except Exception:  # noqa: BLE001 — missing target is not fatal
                return []
        from ..utils.wildcard import match as wildcard_match
        out = []
        for obj in self.client.list_resource(api_version, kind, namespace):
            obj_name = (obj.get('metadata') or {}).get('name', '')
            if not name or wildcard_match(name, obj_name):
                out.append(obj)
        return out

    def _mutate_one(self, pctx: PolicyContext, rule: Rule, raw_rule: dict,
                    policy: Policy, target_obj: dict) -> Optional[str]:
        from ..engine.mutate.mutate import mutate_rule
        ctx = pctx.json_context
        ctx.checkpoint()
        try:
            ctx.add_target_resource(target_obj)
            resp = mutate_rule(raw_rule, ctx, target_obj)
            if resp.status == RuleStatus.FAIL or resp.status == RuleStatus.ERROR:
                return (f'failed to mutate existing resource, rule response '
                        f'{resp.status}: {resp.message}')
            if resp.status != RuleStatus.PASS or resp.patched_resource is None:
                return None
            patched = resp.patched_resource
            if resp.patches:
                annotations = patched.setdefault('metadata', {}) \
                    .setdefault('annotations', {})
                annotations[MUTATE_LAST_APPLIED_ANNOTATION] = json.dumps(
                    resp.patches, separators=(',', ':'), sort_keys=True)
            patched.setdefault('metadata', {})['resourceVersion'] = \
                (target_obj.get('metadata') or {}).get('resourceVersion', '')
            self.client.update_resource(
                patched.get('apiVersion', ''), patched.get('kind', ''),
                (patched.get('metadata') or {}).get('namespace', ''), patched)
            return None
        except Exception as exc:  # noqa: BLE001
            return f'{rule.name}: {exc}'
        finally:
            ctx.restore()
