"""Shared background-context construction (reference:
pkg/background/common/context.go NewBackgroundContext,
pkg/background/common/resource.go GetResource).
"""

from __future__ import annotations

from typing import Optional

from ..api.policy import Policy
from ..dclient.client import NotFoundError
from ..engine.api import PolicyContext
from ..engine.context import Context
from .updaterequest import UpdateRequest


def get_policy(client, policy_key: str) -> Policy:
    """Resolve a UR's policy key (``ns/name`` for namespaced Policy, bare
    name for ClusterPolicy) from the store (reference:
    pkg/background/generate/generate.go:267 getPolicySpec)."""
    from ..dclient.client import NotFoundError
    if '/' in policy_key:
        ns, name = policy_key.split('/', 1)
        kind = 'Policy'
    else:
        ns, name, kind = '', policy_key, 'ClusterPolicy'
    # policy CRDs serve multiple versions; the store holds whichever the
    # manifest used
    for api_version in ('kyverno.io/v1', 'kyverno.io/v2beta1', ''):
        try:
            return Policy(client.get_resource(api_version, kind, ns, name))
        except NotFoundError:
            continue
    raise NotFoundError(f'{kind} "{policy_key}" not found')


def get_trigger_resource(client, ur: UpdateRequest) -> Optional[dict]:
    """reference: pkg/background/common/resource.go:16 GetResource —
    resolves the trigger from the cluster; a trigger deleted (or already
    terminating) yields None, signalling the caller to skip processing
    (generate then cleans up downstream targets)."""
    res = ur.resource
    namespace = res.get('namespace', '')
    if res.get('kind') == 'Namespace':
        namespace = ''
    try:
        trigger = client.get_resource(res.get('apiVersion', ''),
                                      res.get('kind', ''),
                                      namespace, res.get('name', ''))
    except NotFoundError:
        req = ur.admission_request or {}
        if ur.operation == 'DELETE' or req.get('operation') == 'DELETE':
            return None
        raise
    meta = trigger.get('metadata') or {}
    if meta.get('deletionTimestamp'):
        return None  # trigger is terminating
    return trigger


def new_background_context(client, ur: UpdateRequest, policy: Policy,
                           trigger: Optional[dict]) -> PolicyContext:
    """reference: pkg/background/common/context.go NewBackgroundContext"""
    ctx = Context()
    if trigger:
        ctx.add_resource(trigger)
    user_info = ur.user_info
    if user_info:
        ctx.add_user_info(user_info)
        username = ((user_info.get('userInfo') or {}).get('username')
                    or user_info.get('username') or '')
        if username:
            ctx.add_service_account(username)
    req = ur.admission_request
    if req:
        ctx.add_request(req)
        old = req.get('oldObject')
        if isinstance(old, dict) and old:
            ctx.add_old_resource(old)
    ns = (trigger.get('metadata') or {}).get('namespace', '') if trigger else ''
    ctx.add_namespace(ns)
    ns_labels = client.get_namespace_labels(ns) if ns else {}
    pctx = PolicyContext(
        policy=policy,
        new_resource=trigger or {},
        old_resource=(req or {}).get('oldObject')
        if isinstance((req or {}).get('oldObject'), dict) else None,
        admission_info=user_info or None,
        namespace_labels=ns_labels,
        json_context=ctx,
    )
    return pctx
