"""Generated-resource label management (reference:
pkg/background/common/labels.go ManageLabels).
"""

from __future__ import annotations

LABEL_APP_MANAGED_BY = 'app.kubernetes.io/managed-by'
VALUE_KYVERNO_APP = 'kyverno'
GENERATED_BY_KIND = 'kyverno.io/generated-by-kind'
GENERATED_BY_NAMESPACE = 'kyverno.io/generated-by-namespace'
GENERATED_BY_NAME = 'kyverno.io/generated-by-name'
POLICY_NAME_LABEL = 'policy.kyverno.io/policy-name'
GR_NAME_LABEL = 'policy.kyverno.io/gr-name'
SYNCHRONIZE_LABEL = 'policy.kyverno.io/synchronize'
BACKGROUND_GEN_RULE_LABEL = 'kyverno.io/background-gen-rule'


def manage_labels(resource: dict, trigger: dict) -> None:
    """Stamp managed-by + generated-by-* labels onto a generated resource
    (reference: labels.go:23 ManageLabels). An existing foreign managed-by
    value is left untouched."""
    meta = resource.setdefault('metadata', {})
    labels = meta.setdefault('labels', {})
    if labels.get(LABEL_APP_MANAGED_BY, VALUE_KYVERNO_APP) == VALUE_KYVERNO_APP:
        labels[LABEL_APP_MANAGED_BY] = VALUE_KYVERNO_APP
    tmeta = trigger.get('metadata') or {}
    checks = [
        (GENERATED_BY_KIND, trigger.get('kind', '')),
        (GENERATED_BY_NAMESPACE, tmeta.get('namespace', '')),
        (GENERATED_BY_NAME, tmeta.get('name', '')),
    ]
    for key, value in checks:
        # keep at most 63 chars per label-value k8s constraint
        labels[key] = str(value)[:63]
