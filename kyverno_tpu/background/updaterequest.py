"""UpdateRequest CR model + generator (reference:
api/kyverno/v1beta1/updaterequest_types.go,
pkg/webhooks/updaterequest/generator.go).
"""

from __future__ import annotations

import copy
import itertools
from typing import Dict, List, Optional

UR_MUTATE = 'mutate'
UR_GENERATE = 'generate'

STATE_PENDING = 'Pending'
STATE_FAILED = 'Failed'
STATE_COMPLETED = 'Completed'
STATE_SKIP = 'Skip'

# reference: api/kyverno/v1beta1/constants.go
UR_GENERATE_POLICY_LABEL = 'generate.kyverno.io/policy-name'
UR_GENERATE_RESOURCE_NAME_LABEL = 'generate.kyverno.io/resource-name'
UR_GENERATE_RESOURCE_NS_LABEL = 'generate.kyverno.io/resource-namespace'
UR_GENERATE_RESOURCE_KIND_LABEL = 'generate.kyverno.io/resource-kind'
UR_MUTATE_POLICY_LABEL = 'mutate.updaterequest.kyverno.io/policy-name'
UR_MUTATE_TRIGGER_NAME_LABEL = 'mutate.updaterequest.kyverno.io/trigger-name'
UR_MUTATE_TRIGGER_NS_LABEL = 'mutate.updaterequest.kyverno.io/trigger-namespace'
UR_MUTATE_TRIGGER_KIND_LABEL = 'mutate.updaterequest.kyverno.io/trigger-kind'
UR_MUTATE_TRIGGER_APIVERSION_LABEL = 'mutate.updaterequest.kyverno.io/trigger-apiversion'

KYVERNO_NAMESPACE = 'kyverno'

_counter = itertools.count(1)


class UpdateRequest:
    """Accessor wrapper over an unstructured UpdateRequest."""

    __slots__ = ('raw',)

    def __init__(self, raw: dict):
        self.raw = raw or {}

    @property
    def name(self) -> str:
        return (self.raw.get('metadata') or {}).get('name', '')

    @property
    def spec(self) -> dict:
        return self.raw.get('spec') or {}

    @property
    def type(self) -> str:
        return self.spec.get('requestType', '')

    @property
    def policy_key(self) -> str:
        return self.spec.get('policy', '')

    @property
    def resource(self) -> dict:
        """Trigger resource spec {apiVersion, kind, namespace, name}."""
        return self.spec.get('resource') or {}

    @property
    def user_info(self) -> dict:
        return ((self.spec.get('context') or {}).get('userInfo') or {})

    @property
    def admission_request(self) -> Optional[dict]:
        info = (self.spec.get('context') or {}).get('admissionRequestInfo') or {}
        return info.get('admissionRequest')

    @property
    def operation(self) -> str:
        info = (self.spec.get('context') or {}).get('admissionRequestInfo') or {}
        return info.get('operation', '')

    @property
    def status(self) -> dict:
        return self.raw.get('status') or {}

    @property
    def state(self) -> str:
        return self.status.get('state', '')

    @property
    def generated_resources(self) -> List[dict]:
        return self.status.get('generatedResources') or []

    def set_status(self, state: str, message: str = '',
                   generated: Optional[List[dict]] = None) -> None:
        status = self.raw.setdefault('status', {})
        status['state'] = state
        if message:
            status['message'] = message
        elif 'message' in status:
            del status['message']
        if generated is not None:
            status['generatedResources'] = generated


def generate_labels_set(policy_key: str, trigger: Optional[dict]) -> Dict[str, str]:
    """reference: pkg/background/common/labels.go GenerateLabelsSet"""
    policy_name = policy_key.split('/')[-1]
    labels = {UR_GENERATE_POLICY_LABEL: policy_name}
    if trigger:
        meta = trigger.get('metadata') or {}
        labels[UR_GENERATE_RESOURCE_NAME_LABEL] = meta.get('name', '')
        labels[UR_GENERATE_RESOURCE_NS_LABEL] = meta.get('namespace', '')
        labels[UR_GENERATE_RESOURCE_KIND_LABEL] = trigger.get('kind', '')
    return labels


def mutate_labels_set(policy_key: str, trigger: Optional[dict]) -> Dict[str, str]:
    """reference: pkg/background/common/labels.go MutateLabelsSet"""
    policy_name = policy_key.split('/')[-1]
    labels = {UR_MUTATE_POLICY_LABEL: policy_name}
    if trigger:
        meta = trigger.get('metadata') or {}
        labels[UR_MUTATE_TRIGGER_NAME_LABEL] = meta.get('name', '')
        labels[UR_MUTATE_TRIGGER_NS_LABEL] = meta.get('namespace', '')
        labels[UR_MUTATE_TRIGGER_KIND_LABEL] = trigger.get('kind', '')
        if trigger.get('apiVersion'):
            labels[UR_MUTATE_TRIGGER_APIVERSION_LABEL] = \
                trigger['apiVersion'].replace('/', '-')
    return labels


class UpdateRequestGenerator:
    """Creates UpdateRequest CRs in the kyverno namespace, deduplicating
    by label set (reference: pkg/webhooks/updaterequest/generator.go:42
    Apply — a pending UR with the same labels is reused)."""

    def __init__(self, client):
        self.client = client

    def apply(self, ur_spec: dict) -> dict:
        labels = (generate_labels_set if ur_spec.get('requestType') == UR_GENERATE
                  else mutate_labels_set)(
            ur_spec.get('policy', ''),
            _trigger_from_spec(ur_spec))
        existing = self.client.list_resource(
            'kyverno.io/v1beta1', 'UpdateRequest', KYVERNO_NAMESPACE,
            {'matchLabels': labels})
        for old in existing:
            state = ((old.get('status') or {}).get('state'))
            if state in (None, '', STATE_PENDING):
                old['spec'] = copy.deepcopy(ur_spec)
                old.setdefault('status', {})['state'] = STATE_PENDING
                return self.client.update_resource(
                    'kyverno.io/v1beta1', 'UpdateRequest',
                    KYVERNO_NAMESPACE, old)
        ur = {
            'apiVersion': 'kyverno.io/v1beta1',
            'kind': 'UpdateRequest',
            'metadata': {
                'generateName': 'ur-',
                'name': f'ur-{next(_counter)}',
                'namespace': KYVERNO_NAMESPACE,
                'labels': labels,
            },
            'spec': copy.deepcopy(ur_spec),
            'status': {'state': STATE_PENDING},
        }
        return self.client.create_resource(
            'kyverno.io/v1beta1', 'UpdateRequest', KYVERNO_NAMESPACE, ur)


def _trigger_from_spec(ur_spec: dict) -> Optional[dict]:
    res = ur_spec.get('resource') or {}
    if not res:
        return None
    return {
        'apiVersion': res.get('apiVersion', ''),
        'kind': res.get('kind', ''),
        'metadata': {'name': res.get('name', ''),
                     'namespace': res.get('namespace', '')},
    }


def new_ur_spec(request_type: str, policy_key: str, trigger: dict,
                user_info: Optional[dict] = None,
                admission_request: Optional[dict] = None,
                operation: str = '') -> dict:
    """Build an UpdateRequestSpec from a trigger resource."""
    meta = (trigger.get('metadata') or {})
    spec = {
        'requestType': request_type,
        'policy': policy_key,
        'resource': {
            'apiVersion': trigger.get('apiVersion', ''),
            'kind': trigger.get('kind', ''),
            'namespace': meta.get('namespace', ''),
            'name': meta.get('name', ''),
        },
        'context': {},
    }
    if user_info:
        spec['context']['userInfo'] = user_info
    if admission_request or operation:
        spec['context']['admissionRequestInfo'] = {}
        if admission_request:
            spec['context']['admissionRequestInfo']['admissionRequest'] = \
                admission_request
        if operation:
            spec['context']['admissionRequestInfo']['operation'] = operation
    return spec
