"""Background processing (reference: pkg/background).

UpdateRequests are the durable hand-off from the admission path to the
async controllers: generate-rule materialization and mutate-existing.
"""

from .updaterequest import (  # noqa: F401
    UR_GENERATE, UR_MUTATE, STATE_COMPLETED, STATE_FAILED, STATE_PENDING,
    STATE_SKIP, UpdateRequest, UpdateRequestGenerator,
)
from .generate import GenerateController  # noqa: F401
from .mutate_existing import MutateExistingController  # noqa: F401
from .update_request_controller import UpdateRequestController  # noqa: F401
