"""Generate-rule materialization (reference: pkg/background/generate/
generate.go).

Given a Pending UpdateRequest of type ``generate``, re-validates the
trigger against the policy, then materializes each applicable generate
rule's target: inline ``data``, ``clone`` (copy one source resource) or
``cloneList`` (copy all selector-matched resources of the listed kinds),
honoring ``synchronize`` create/update semantics and ownership labels.
"""

from __future__ import annotations

import copy
from typing import Any, Dict, List, Optional, Tuple

from ..api.policy import Policy, Rule
from ..api.unstructured import get_kind_from_gvk
from ..dclient.client import AlreadyExistsError, NotFoundError
from ..engine.api import RuleStatus
from ..engine.background import generate_response
from ..engine.variables import substitute_all
from .common import get_policy, get_trigger_resource, new_background_context
from .labels import (
    BACKGROUND_GEN_RULE_LABEL, GR_NAME_LABEL, POLICY_NAME_LABEL,
    SYNCHRONIZE_LABEL, manage_labels,
)
from .updaterequest import (
    STATE_COMPLETED, STATE_FAILED, UpdateRequest,
)

# ResourceMode (reference: generate.go ResourceMode)
SKIP = 'SKIP'
CREATE = 'CREATE'
UPDATE = 'UPDATE'

#: default apiVersions for generate rules that name only a kind — the
#: reference resolves these through discovery (dclient.GetResource with
#: empty apiVersion); the fake path needs the common built-ins
_DEFAULT_API_VERSIONS = {
    'ConfigMap': 'v1', 'Secret': 'v1', 'Namespace': 'v1',
    'ServiceAccount': 'v1', 'Service': 'v1', 'LimitRange': 'v1',
    'ResourceQuota': 'v1', 'Pod': 'v1',
    'Role': 'rbac.authorization.k8s.io/v1',
    'RoleBinding': 'rbac.authorization.k8s.io/v1',
    'ClusterRole': 'rbac.authorization.k8s.io/v1',
    'ClusterRoleBinding': 'rbac.authorization.k8s.io/v1',
    'NetworkPolicy': 'networking.k8s.io/v1',
    'Deployment': 'apps/v1',
    'PodDisruptionBudget': 'policy/v1',
}


class GenerateResponseItem:
    __slots__ = ('data', 'action', 'name', 'kind', 'namespace',
                 'api_version', 'error')

    def __init__(self, data=None, action=SKIP, name='', kind='',
                 namespace='', api_version='', error=None):
        self.data = data
        self.action = action
        self.name = name
        self.kind = kind
        self.namespace = namespace
        self.api_version = api_version
        self.error = error


class GenerateController:
    """reference: pkg/background/generate/generate.go:61"""

    def __init__(self, client, engine, policy_getter=None):
        self.client = client
        self.engine = engine
        # policy_getter(policy_key) -> Policy; defaults to the client store
        self.policy_getter = policy_getter or (
            lambda key: get_policy(client, key))
        # permission pre-flight results, keyed (kind, namespace) with a
        # short TTL: RBAC changes live (the shipped ClusterRoles are
        # aggregated), so both a cached denial after the admin grants
        # the permission and a cached allow after revocation must age out
        self._auth_cache: Dict[Tuple[str, str],
                               Tuple[float, Optional[str]]] = {}
        self._auth_ttl = float(
            __import__('os').environ.get('KTPU_AUTH_TTL', '60'))

    def _check_generate_auth(self, kind: str, namespace: str
                             ) -> Optional[str]:
        """SSAR pre-flight before applying a generate target: create/
        update/get/delete on the target kind (reference:
        pkg/policy/generate/auth.go Operations + validate.go:130
        canIGenerate — enforced here so a permission lost after policy
        admission still fails the UR instead of erroring mid-apply)."""
        import time as _time
        from ..auth import Auth
        from ..auth.auth import can_i_generate_error
        if not kind:
            return None
        key = (kind, namespace)
        hit = self._auth_cache.get(key)
        now = _time.monotonic()
        if hit is not None and now - hit[0] < self._auth_ttl:
            return hit[1]
        try:
            err = can_i_generate_error(Auth(self.client), kind, namespace)
        except AttributeError:
            # client without an access-review surface (bare test doubles):
            # behave like the reference with full RBAC
            err = None
        self._auth_cache[key] = (now, err)
        return err

    # -- UR processing -------------------------------------------------------

    def process_ur(self, ur: UpdateRequest) -> Optional[Exception]:
        """reference: generate.go:92 ProcessUR"""
        try:
            trigger = get_trigger_resource(self.client, ur)
        except NotFoundError as err:
            ur.set_status(STATE_FAILED, str(err))
            return err
        if trigger is None:
            # DELETE with no recoverable trigger: clean up downstream
            self._delete_downstream(ur)
            ur.set_status(STATE_COMPLETED)
            return None
        try:
            generated, err = self._apply_generate(trigger, ur)
        except Exception as exc:  # noqa: BLE001 — status captures the failure
            ur.set_status(STATE_FAILED, str(exc))
            return exc
        existing = {self._spec_key(g) for g in ur.generated_resources}
        merged = ur.generated_resources + [
            g for g in generated if self._spec_key(g) not in existing]
        if err is not None:
            # record partial creations so they remain cleanable
            # (reference: generate.go updateStatus → statusControl.Failed
            # with genResources)
            ur.set_status(STATE_FAILED, str(err), generated=merged)
            return err
        ur.set_status(STATE_COMPLETED, generated=merged)
        return None

    @staticmethod
    def _spec_key(g: dict) -> Tuple[str, str, str, str]:
        return (g.get('apiVersion', ''), g.get('kind', ''),
                g.get('namespace', ''), g.get('name', ''))

    def _apply_generate(self, trigger: dict, ur: UpdateRequest
                        ) -> Tuple[List[dict], Optional[Exception]]:
        """reference: generate.go:178 applyGenerate"""
        policy = self.policy_getter(ur.policy_key)
        pctx = new_background_context(self.client, ur, policy, trigger)
        resp = generate_response(self.engine, pctx, ur.raw)
        applicable = []
        failed_match = False
        for rr in resp.policy_response.rules:
            if rr.status == RuleStatus.PASS:
                applicable.append(rr.name)
            elif rr.status == RuleStatus.FAIL:
                failed_match = True
        if not applicable:
            if failed_match:
                # the old resource matched but the new one doesn't: the
                # trigger moved out of scope — delete downstream targets
                # (reference: generate.go:206-217)
                self._delete_downstream(ur)
            return [], None
        return self.apply_generate_policy(pctx, ur, applicable)

    def apply_generate_policy(self, pctx, ur: UpdateRequest,
                              applicable_rules: List[str]
                              ) -> Tuple[List[dict], Optional[Exception]]:
        """reference: generate.go:311 ApplyGeneratePolicy"""
        policy = pctx.policy
        gen_resources: List[dict] = []
        apply_rules = policy.apply_rules
        apply_count = 0
        for raw_rule in self.engine._compute_rules(policy):
            rule = Rule(raw_rule)
            if not rule.has_generate():
                continue
            if rule.name not in applicable_rules:
                continue
            if apply_rules == 'One' and apply_count > 0:
                break
            ctx = pctx.json_context
            ctx.checkpoint()
            try:
                self.engine.context_loader.load(rule.context, ctx,
                                                policy_name=policy.name,
                                                rule_name=rule.name)
                substituted = Rule(substitute_all(ctx, raw_rule))
                created = self._apply_rule(substituted, pctx.new_resource,
                                           policy, ur)
            except Exception as exc:  # noqa: BLE001
                return gen_resources, exc
            finally:
                ctx.restore()
            gen_resources.extend(created)
            apply_count += 1
        return gen_resources, None

    # -- single rule ---------------------------------------------------------

    def _apply_rule(self, rule: Rule, trigger: dict, policy: Policy,
                    ur: UpdateRequest) -> List[dict]:
        """reference: generate.go:414 applyRule"""
        gen = rule.generation
        clone = gen.get('clone') or {}
        clone_list = gen.get('cloneList') or {}
        items: List[GenerateResponseItem] = []

        kind = gen.get('kind', '')
        name = gen.get('name', '')
        namespace = gen.get('namespace', '')
        api_version = gen.get('apiVersion', '') or \
            _DEFAULT_API_VERSIONS.get(kind, '')
        if not clone_list.get('kinds'):
            if not kind:
                raise ValueError('generate kind can not be empty')
            if not name:
                raise ValueError('generate name can not be empty')
            auth_err = self._check_generate_auth(kind, namespace)
        else:
            auth_err = None
            for gvk in clone_list['kinds']:
                # the full group/version/Kind string rides into the SSAR
                # so group-qualified kinds probe the right GVR
                auth_err = self._check_generate_auth(str(gvk), namespace)
                if auth_err:
                    break
        if auth_err:
            raise PermissionError(auth_err)

        if clone.get('name'):
            data, mode, err = self._manage_clone(
                api_version, kind, namespace, name, clone,
                bool(gen.get('synchronize')), ur)
            items.append(GenerateResponseItem(
                data, mode, name, kind, namespace, api_version, err))
        elif clone_list.get('kinds'):
            items = self._manage_clone_list(namespace, clone_list,
                                            bool(gen.get('synchronize')), ur)
        else:
            data, mode, err = self._manage_data(
                api_version, kind, namespace, name, gen.get('data'),
                bool(gen.get('synchronize')), ur)
            items.append(GenerateResponseItem(
                data, mode, name, kind, namespace, api_version, err))

        created: List[dict] = []
        for item in items:
            if item.error is not None:
                raise item.error
            if item.action == SKIP:
                continue
            if item.data is None and item.action == UPDATE:
                continue
            new_resource = copy.deepcopy(item.data) or {}
            meta = new_resource.setdefault('metadata', {})
            meta['name'] = item.name
            if item.namespace:
                meta['namespace'] = item.namespace
            elif 'namespace' in meta:
                del meta['namespace']
            if not new_resource.get('kind'):
                new_resource['kind'] = item.kind
            if item.api_version:
                new_resource['apiVersion'] = item.api_version
            manage_labels(new_resource, trigger)
            labels = meta.setdefault('labels', {})
            if _is_generate_existing(policy):
                labels[BACKGROUND_GEN_RULE_LABEL] = rule.name
            labels[POLICY_NAME_LABEL] = policy.name
            labels[GR_NAME_LABEL] = ur.name
            if clone.get('name') or clone_list.get('kinds'):
                # cloned targets carry the cloning policy's name
                # (reference: pkg/background/common/labels.go
                # GenerateLabelsSet clone path)
                labels['generate.kyverno.io/clone-policy-name'] = \
                    policy.name
            synchronize = bool(rule.generation.get('synchronize'))
            if item.action == CREATE:
                labels[SYNCHRONIZE_LABEL] = 'enable' if synchronize else 'disable'
                meta.pop('resourceVersion', None)
                try:
                    self.client.create_resource(
                        new_resource.get('apiVersion', item.api_version),
                        new_resource.get('kind', item.kind),
                        item.namespace, new_resource)
                except AlreadyExistsError:
                    pass
                created.append(_resource_spec(item))
            elif item.action == UPDATE:
                created.extend(self._update_target(
                    item, new_resource, labels, synchronize))
        return created

    def _update_target(self, item: GenerateResponseItem, new_resource: dict,
                       labels: dict, synchronize: bool) -> List[dict]:
        try:
            generated = self.client.get_resource(
                item.api_version, item.kind, item.namespace, item.name)
        except NotFoundError:
            self.client.create_resource(
                new_resource.get('apiVersion', item.api_version),
                new_resource.get('kind', item.kind),
                item.namespace, new_resource)
            return [_resource_spec(item)]
        if synchronize:
            labels[SYNCHRONIZE_LABEL] = 'enable'
            meta = new_resource.setdefault('metadata', {})
            meta['resourceVersion'] = (generated.get('metadata') or {}) \
                .get('resourceVersion', '')
            if not _subset_matches(generated, new_resource):
                self.client.update_resource(
                    new_resource.get('apiVersion', item.api_version),
                    new_resource.get('kind', item.kind),
                    item.namespace, new_resource)
        else:
            # synchronize is off here; downgrade a stale 'enable' marker
            cur_labels = ((generated.get('metadata') or {})
                          .setdefault('labels', {}))
            if cur_labels.get(SYNCHRONIZE_LABEL) == 'enable':
                cur_labels[SYNCHRONIZE_LABEL] = 'disable'
                self.client.update_resource(
                    generated.get('apiVersion', item.api_version),
                    generated.get('kind', item.kind),
                    item.namespace, generated)
        return []

    # -- data / clone / cloneList --------------------------------------------

    def _manage_data(self, api_version, kind, namespace, name, data,
                     synchronize, ur):
        """reference: generate.go:594 manageData"""
        if data is None:
            resource = None
        elif not isinstance(data, dict):
            return None, SKIP, TypeError('generate.data must be an object')
        else:
            resource = copy.deepcopy(data)
        try:
            existing = self.client.get_resource(api_version, kind, namespace, name)
        except NotFoundError:
            if ur.generated_resources and not synchronize:
                return None, SKIP, None
            if resource is None:
                return None, SKIP, None
            return resource, CREATE, None
        if data is None:
            return None, SKIP, None
        resource.setdefault('metadata', {})['resourceVersion'] = \
            (existing.get('metadata') or {}).get('resourceVersion', '')
        return resource, UPDATE, None

    def _manage_clone(self, api_version, kind, namespace, name, clone,
                      synchronize, ur):
        """reference: generate.go:626 manageClone"""
        src_ns = clone.get('namespace', '')
        src_name = clone.get('name', '')
        if not src_name:
            return None, SKIP, ValueError('failed to find source name')
        if src_ns == namespace and src_name == name:
            return None, SKIP, None  # self-clone
        try:
            src = self.client.get_resource(api_version, kind, src_ns, src_name)
        except NotFoundError as err:
            return None, SKIP, NotFoundError(
                f'source resource {api_version} {kind}/{src_ns}/{src_name} '
                f'not found. {err}')
        try:
            target = self.client.get_resource(api_version, kind, namespace, name)
        except NotFoundError:
            target = None
            if ur.generated_resources and not synchronize:
                return None, SKIP, None
        if src_ns != namespace:
            (src.get('metadata') or {}).pop('ownerReferences', None)
        if target is not None:
            src_meta = src.setdefault('metadata', {})
            tgt_meta = target.get('metadata') or {}
            for field in ('uid', 'selfLink', 'creationTimestamp',
                          'managedFields', 'resourceVersion'):
                if field in tgt_meta:
                    src_meta[field] = tgt_meta[field]
                else:
                    src_meta.pop(field, None)
            src_cmp = copy.deepcopy(src)
            (src_cmp.get('metadata') or {})['name'] = tgt_meta.get('name', '')
            (src_cmp.get('metadata') or {})['namespace'] = \
                tgt_meta.get('namespace', '')
            if src_cmp == target:
                return None, SKIP, None
            return src, UPDATE, None
        return src, CREATE, None

    def _manage_clone_list(self, namespace, clone_list, synchronize, ur
                           ) -> List[GenerateResponseItem]:
        """reference: generate.go:681 manageCloneList"""
        out: List[GenerateResponseItem] = []
        src_ns = clone_list.get('namespace', '')
        kinds = clone_list.get('kinds') or []
        selector = clone_list.get('selector')
        if not kinds:
            return [GenerateResponseItem(
                error=ValueError('failed to find kinds list'))]
        for gvk in kinds:
            api_version, kind = get_kind_from_gvk(gvk)
            sources = self.client.list_resource(
                api_version, kind, src_ns, selector)
            for src in sources:
                src_name = (src.get('metadata') or {}).get('name', '')
                data, mode, err = self._manage_clone(
                    api_version or src.get('apiVersion', ''), kind,
                    namespace, src_name,
                    {'namespace': src_ns, 'name': src_name},
                    synchronize, ur)
                out.append(GenerateResponseItem(
                    data, mode, src_name, kind, namespace,
                    api_version or src.get('apiVersion', ''), err))
        return out

    # -- cleanup -------------------------------------------------------------

    def _delete_downstream(self, ur: UpdateRequest) -> None:
        """reference: generate.go:848 deleteGeneratedResources — deletes the
        targets recorded in UR status, and additionally locates downstream
        resources by the ownership labels stamped at creation time (a fresh
        UR for a retired trigger has an empty status list)."""
        for g in ur.generated_resources:
            try:
                self.client.delete_resource(
                    g.get('apiVersion', ''), g.get('kind', ''),
                    g.get('namespace', ''), g.get('name', ''))
            except NotFoundError:
                pass
        from .labels import (
            GENERATED_BY_KIND, GENERATED_BY_NAME, GENERATED_BY_NAMESPACE,
        )
        trigger = ur.resource
        policy_name = ur.policy_key.split('/')[-1]
        selector = {'matchLabels': {
            POLICY_NAME_LABEL: policy_name,
            GENERATED_BY_KIND: trigger.get('kind', '')[:63],
            GENERATED_BY_NAMESPACE: trigger.get('namespace', '')[:63],
            GENERATED_BY_NAME: trigger.get('name', '')[:63],
        }}
        for obj in self.client.list_resource('', '', '', selector):
            meta = obj.get('metadata') or {}
            try:
                self.client.delete_resource(
                    obj.get('apiVersion', ''), obj.get('kind', ''),
                    meta.get('namespace', ''), meta.get('name', ''))
            except NotFoundError:
                pass

    def cleanup_cloned_resource(self, target_spec: dict) -> None:
        """Delete a generated resource on trigger delete unless it carries
        data the user owns (reference: generate.go:242
        cleanupClonedResource — only deletes when generated by clone and
        synchronize is enabled via the label)."""
        try:
            target = self.client.get_resource(
                target_spec.get('apiVersion', ''), target_spec.get('kind', ''),
                target_spec.get('namespace', ''), target_spec.get('name', ''))
        except NotFoundError:
            return
        labels = ((target.get('metadata') or {}).get('labels') or {})
        if labels.get(SYNCHRONIZE_LABEL) == 'enable':
            self.client.delete_resource(
                target_spec.get('apiVersion', ''), target_spec.get('kind', ''),
                target_spec.get('namespace', ''), target_spec.get('name', ''))


def _is_generate_existing(policy: Policy) -> bool:
    """reference: spec_types.go IsGenerateExistingOnPolicyUpdate"""
    v = policy.spec.get('generateExistingOnPolicyUpdate')
    return bool(v)


def _resource_spec(item: GenerateResponseItem) -> dict:
    return {'apiVersion': item.api_version, 'kind': item.kind,
            'namespace': item.namespace, 'name': item.name}


def _subset_matches(existing: dict, desired: dict) -> bool:
    """True when every field of ``desired`` already equals ``existing``
    (reference: generate.go ValidateResourceWithPattern gate before
    update)."""
    if isinstance(desired, dict):
        if not isinstance(existing, dict):
            return False
        return all(k in existing and _subset_matches(existing[k], v)
                   for k, v in desired.items())
    if isinstance(desired, list):
        if not isinstance(existing, list) or len(existing) != len(desired):
            return False
        return all(_subset_matches(e, d) for e, d in zip(existing, desired))
    return existing == desired


def materialize_rule_offline(raw_rule: dict, pctx,
                             clone_source: Optional[dict] = None
                             ) -> Optional[dict]:
    """Materialize one generate rule's target without a cluster — the CLI
    `test`/`apply` path (reference: cmd/cli/kubectl-kyverno/utils/common/
    generate.go handleGeneratePolicy, which runs the generate controller
    against a fake client seeded with CloneSourceResource)."""
    ctx = pctx.json_context
    ctx.checkpoint()
    try:
        rule = Rule(substitute_all(ctx, raw_rule))
    finally:
        ctx.restore()
    gen = rule.generation
    kind = gen.get('kind', '')
    name = gen.get('name', '')
    namespace = gen.get('namespace', '')
    api_version = gen.get('apiVersion', '')
    clone = gen.get('clone') or {}
    if clone.get('name'):
        if clone_source is None:
            raise ValueError(
                f'no clone source for generate rule {rule.name}')
        data = copy.deepcopy(clone_source)
        (data.get('metadata') or {}).pop('creationTimestamp', None)
        (data.get('metadata') or {}).pop('resourceVersion', None)
        (data.get('metadata') or {}).pop('uid', None)
    elif gen.get('data') is not None:
        data = copy.deepcopy(gen.get('data')) or {}
    elif (gen.get('cloneList') or {}).get('kinds'):
        raise ValueError(
            f'generate rule {rule.name} uses cloneList, which needs cluster '
            'access; provide cloneSourceResource per target instead')
    else:
        return None
    meta = data.setdefault('metadata', {})
    meta['name'] = name
    if namespace:
        meta['namespace'] = namespace
    else:
        meta.pop('namespace', None)
    if not data.get('kind'):
        data['kind'] = kind
    if api_version and not data.get('apiVersion'):
        data['apiVersion'] = api_version
    manage_labels(data, pctx.new_resource)
    return data
