"""UpdateRequest controller (reference:
pkg/background/update_request_controller.go).

Dispatches pending UpdateRequests to the generate or mutate-existing
processor, with bounded retries and cleanup of completed URs — the same
worker/workqueue discipline as the reference, driven here by an explicit
``process_pending`` step so it composes with any scheduler (thread pool,
asyncio, or a test loop).
"""

from __future__ import annotations

from typing import List, Optional

from ..dclient.client import NotFoundError
from .generate import GenerateController
from .mutate_existing import MutateExistingController
from .updaterequest import (
    KYVERNO_NAMESPACE, STATE_COMPLETED, STATE_FAILED, STATE_PENDING,
    UR_GENERATE, UR_MUTATE, UpdateRequest,
)

MAX_RETRIES = 10  # reference: update_request_controller.go:39 maxRetries


class UpdateRequestController:
    """reference: pkg/background/update_request_controller.go:74"""

    def __init__(self, client, engine, policy_getter=None):
        self.client = client
        self.generate = GenerateController(client, engine, policy_getter)
        self.mutate = MutateExistingController(client, engine, policy_getter)
        self._retries = {}

    def list_urs(self, state: Optional[str] = None) -> List[UpdateRequest]:
        urs = [UpdateRequest(raw) for raw in self.client.list_resource(
            'kyverno.io/v1beta1', 'UpdateRequest', KYVERNO_NAMESPACE)]
        if state is not None:
            urs = [ur for ur in urs if (ur.state or STATE_PENDING) == state]
        return urs

    def process_pending(self) -> int:
        """One reconcile pass over all pending URs. Returns the number
        processed (reference: syncUpdateRequest worker loop)."""
        n = 0
        for ur in self.list_urs(STATE_PENDING):
            self.sync_update_request(ur)
            n += 1
        # synchronize=true generate URs re-reconcile continuously: the
        # reference watches downstream/source changes and re-enqueues
        # the UR (pkg/background/update_request_controller.go informer
        # hooks); the tick model re-processes them each pass, which
        # no-ops when everything already converged
        for ur in self.list_urs(STATE_COMPLETED):
            if ur.type != UR_GENERATE or not self._wants_sync(ur):
                continue
            # converged sync URs re-reconcile as no-ops; not counted as
            # processed work
            self.sync_update_request(ur)
        return n

    def _wants_sync(self, ur: UpdateRequest) -> bool:
        policy = None
        try:
            policy = self.generate.policy_getter(ur.policy_key)
        except Exception:  # noqa: BLE001 - deleted policy: nothing to sync
            return False
        if policy is None:
            return False
        return any(bool((r.raw.get('generate') or {}).get('synchronize'))
                   for r in policy.rules)

    def sync_update_request(self, ur: UpdateRequest) -> None:
        """reference: update_request_controller.go syncUpdateRequest"""
        # background entry point of the trace: any device scans the
        # processors trigger nest their stage spans under this one
        from ..observability import tracing
        with tracing.start_span(
                'kyverno/background/ur',
                {'ur': ur.name, 'type': ur.type or '',
                 'policy': ur.policy_key or ''}) as span:
            self._sync_update_request(ur, span)

    def _sync_update_request(self, ur: UpdateRequest, span) -> None:
        if ur.type == UR_GENERATE:
            err = self.generate.process_ur(ur)
        elif ur.type == UR_MUTATE:
            err = self.mutate.process_ur(ur)
        else:
            # a malformed type is permanent: fail without consuming retries
            ur.set_status(STATE_FAILED, f'unknown request type {ur.type!r}')
            self._store_status(ur)
            return
        span.set_attribute('result', 'error' if err is not None else 'ok')
        if err is not None:
            key = ur.name
            self._retries[key] = self._retries.get(key, 0) + 1
            if self._retries[key] < MAX_RETRIES:
                # leave Pending for the next pass (rate-limited requeue)
                ur.raw.setdefault('status', {})['state'] = STATE_PENDING
                ur.raw['status']['message'] = str(err)
            else:
                ur.set_status(STATE_FAILED, str(err))
                self._retries.pop(key, None)
        else:
            self._retries.pop(ur.name, None)
        self._store_status(ur)

    def _store_status(self, ur: UpdateRequest) -> None:
        try:
            self.client.update_resource(
                'kyverno.io/v1beta1', 'UpdateRequest', KYVERNO_NAMESPACE,
                ur.raw)
        except NotFoundError:
            pass

    def cleanup_completed(self) -> int:
        """Delete completed URs (reference: cleanupUR). Returns count."""
        n = 0
        for ur in self.list_urs(STATE_COMPLETED):
            try:
                self.client.delete_resource(
                    'kyverno.io/v1beta1', 'UpdateRequest', KYVERNO_NAMESPACE,
                    ur.name)
                n += 1
            except NotFoundError:
                pass
        return n
