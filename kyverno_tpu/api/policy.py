"""Policy API types.

Policies are kept as unstructured dicts (the same representation the engine
substitutes variables into) wrapped in light accessor classes mirroring the
reference CRD fields (reference: api/kyverno/v1/policy_types.go:136,
spec_types.go:49, rule_types.go:40).
"""

from __future__ import annotations

import copy
from typing import Any, Dict, Iterator, List, Optional

import yaml

POD_CONTROLLERS_ANNOTATION = 'pod-policies.kyverno.io/autogen-controllers'


class Rule:
    __slots__ = ('raw',)

    def __init__(self, raw: dict):
        self.raw = raw or {}

    @property
    def name(self) -> str:
        return self.raw.get('name', '') or ''

    @property
    def match(self) -> dict:
        return self.raw.get('match') or {}

    @property
    def exclude(self) -> dict:
        return self.raw.get('exclude') or {}

    @property
    def context(self) -> List[dict]:
        return self.raw.get('context') or []

    @property
    def preconditions(self) -> Any:
        return self.raw.get('preconditions')

    @property
    def validation(self) -> dict:
        return self.raw.get('validate') or {}

    @property
    def mutation(self) -> dict:
        return self.raw.get('mutate') or {}

    @property
    def generation(self) -> dict:
        return self.raw.get('generate') or {}

    @property
    def verify_images(self) -> List[dict]:
        return self.raw.get('verifyImages') or []

    def has_validate(self) -> bool:
        return bool(self.raw.get('validate'))

    def has_mutate(self) -> bool:
        return bool(self.raw.get('mutate'))

    def has_generate(self) -> bool:
        return bool(self.raw.get('generate'))

    def has_verify_images(self) -> bool:
        return bool(self.raw.get('verifyImages'))

    def has_validate_pod_security(self) -> bool:
        return bool(self.validation.get('podSecurity'))

    def copy(self) -> 'Rule':
        return Rule(copy.deepcopy(self.raw))

    def get_any_all_conditions(self) -> Any:
        return self.preconditions


class Policy:
    """ClusterPolicy or (namespaced) Policy."""

    __slots__ = ('raw',)

    def __init__(self, raw: dict):
        self.raw = raw or {}

    @property
    def api_version(self) -> str:
        return self.raw.get('apiVersion', '') or ''

    @property
    def kind(self) -> str:
        return self.raw.get('kind', '') or ''

    @property
    def metadata(self) -> dict:
        return self.raw.get('metadata') or {}

    @property
    def name(self) -> str:
        return self.metadata.get('name', '') or ''

    @property
    def namespace(self) -> str:
        return self.metadata.get('namespace', '') or ''

    @property
    def annotations(self) -> Dict[str, str]:
        return {str(k): str(v) for k, v in (self.metadata.get('annotations') or {}).items()}

    @property
    def is_namespaced(self) -> bool:
        return self.kind == 'Policy'

    @property
    def spec(self) -> dict:
        return self.raw.get('spec') or {}

    @property
    def rules(self) -> List[Rule]:
        return [Rule(r) for r in self.spec.get('rules') or []]

    @property
    def validation_failure_action(self) -> str:
        # reference: api/kyverno/v1/spec_types.go ValidationFailureAction
        return self.spec.get('validationFailureAction', 'Audit') or 'Audit'

    @property
    def validation_failure_action_overrides(self) -> List[dict]:
        return self.spec.get('validationFailureActionOverrides') or []

    @property
    def background(self) -> bool:
        v = self.spec.get('background')
        return True if v is None else bool(v)

    @property
    def failure_policy(self) -> str:
        return self.spec.get('failurePolicy', 'Fail') or 'Fail'

    @property
    def webhook_timeout_seconds(self) -> Optional[int]:
        return self.spec.get('webhookTimeoutSeconds')

    @property
    def apply_rules(self) -> str:
        return self.spec.get('applyRules', 'All') or 'All'

    @property
    def schema_validation(self) -> bool:
        v = self.spec.get('schemaValidation')
        return True if v is None else bool(v)

    def get_kind_and_name(self) -> str:
        if self.namespace:
            return f'{self.namespace}/{self.name}'
        return self.name

    def copy(self) -> 'Policy':
        return Policy(copy.deepcopy(self.raw))


def load_policies_from_yaml(text: str) -> List[Policy]:
    """Load every ClusterPolicy/Policy document from a YAML string."""
    out = []
    for doc in yaml.safe_load_all(text):
        if not isinstance(doc, dict):
            continue
        if is_kyverno_policy(doc):
            out.append(Policy(doc))
        elif doc.get('kind') == 'List':
            for item in doc.get('items') or []:
                if isinstance(item, dict) and is_kyverno_policy(item):
                    out.append(Policy(item))
    return out


def is_kyverno_policy(doc: dict) -> bool:
    """True only for kyverno.io Policy/ClusterPolicy — other API groups
    also use the kind name ``Policy`` (e.g. config.kio.kasten.io)."""
    if doc.get('kind') not in ('ClusterPolicy', 'Policy'):
        return False
    api_version = doc.get('apiVersion') or 'kyverno.io/v1'
    return api_version.startswith('kyverno.io/')


def load_resources_from_yaml(text: str) -> List[dict]:
    """Load every non-policy Kubernetes document from a YAML string."""
    out = []
    for doc in yaml.safe_load_all(text):
        if not isinstance(doc, dict) or not doc.get('kind'):
            continue
        if doc.get('kind') == 'List':
            out.extend(i for i in doc.get('items') or [] if isinstance(i, dict))
        else:
            out.append(doc)
    return out
