"""Unstructured Kubernetes resource helpers.

Mirrors the tiny slice of k8s.io/apimachinery's unstructured.Unstructured the
engine needs (kind/name/namespace/labels/annotations/GVK accessors) plus the
GVK-string parsing used in policy match blocks
(reference: pkg/utils/kube/kind.go).
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, Optional, Tuple

_VERSION_RE = re.compile(r'v\d((alpha|beta)\d)?')


class Resource:
    """Thin wrapper over an unstructured resource dict."""

    __slots__ = ('obj',)

    def __init__(self, obj: dict):
        self.obj = obj or {}

    @property
    def api_version(self) -> str:
        return self.obj.get('apiVersion', '') or ''

    @property
    def kind(self) -> str:
        return self.obj.get('kind', '') or ''

    @property
    def metadata(self) -> dict:
        return self.obj.get('metadata') or {}

    @property
    def name(self) -> str:
        return self.metadata.get('name', '') or ''

    @property
    def generate_name(self) -> str:
        return self.metadata.get('generateName', '') or ''

    @property
    def namespace(self) -> str:
        return self.metadata.get('namespace', '') or ''

    @property
    def uid(self) -> str:
        return self.metadata.get('uid', '') or ''

    @property
    def labels(self) -> Dict[str, str]:
        return {str(k): str(v) for k, v in (self.metadata.get('labels') or {}).items()}

    @property
    def annotations(self) -> Dict[str, str]:
        return {str(k): str(v) for k, v in (self.metadata.get('annotations') or {}).items()}

    @property
    def owner_references(self) -> List[dict]:
        return self.metadata.get('ownerReferences') or []

    @property
    def group_version(self) -> str:
        return self.api_version

    @property
    def group(self) -> str:
        av = self.api_version
        return av.rsplit('/', 1)[0] if '/' in av else ''

    @property
    def version(self) -> str:
        av = self.api_version
        return av.rsplit('/', 1)[1] if '/' in av else av

    def __bool__(self):
        return bool(self.obj)


def get_kind_from_gvk(s: str) -> Tuple[str, str]:
    """Parse a policy 'kinds' entry into (groupVersion, kind[/subresource])
    (reference: pkg/utils/kube/kind.go:11 GetKindFromGVK)."""
    parts = s.split('/')
    count = len(parts)
    if count == 2:
        if _VERSION_RE.search(parts[0]) or parts[0] == '*':
            return parts[0], _format_subresource(parts[1])
        return '', parts[0] + '/' + parts[1]
    if count == 3:
        if _VERSION_RE.search(parts[0]) or parts[0] == '*':
            return parts[0], parts[1] + '/' + parts[2]
        return parts[0] + '/' + parts[1], _format_subresource(parts[2])
    if count == 4:
        return parts[0] + '/' + parts[1], parts[2] + '/' + parts[3]
    return '', _format_subresource(s)


def _format_subresource(s: str) -> str:
    return s.replace('.', '/', 1)


def split_subresource(s: str) -> Tuple[str, str]:
    parts = s.split('/')
    if len(parts) == 2:
        return parts[0], parts[1]
    return s, ''


def contains_kind(kinds: List[str], kind: str) -> bool:
    for e in kinds:
        _, k = get_kind_from_gvk(e)
        k, _ = split_subresource(k)
        if k == kind:
            return True
    return False


def group_version_matches(group_version: str, server_gv: str) -> bool:
    # reference: pkg/utils/kube/kind.go:63
    if '*' in group_version:
        return server_gv.startswith(group_version.rstrip('*'))
    g1, _, v1 = group_version.rpartition('/')
    g2, _, v2 = server_gv.rpartition('/')
    return g1 == g2 and v1 == v2
