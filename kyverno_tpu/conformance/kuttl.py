"""kuttl step-replay harness (reference corpus:
/root/reference/test/conformance/kuttl — SURVEY.md §4).

Replays a kuttl test directory against the in-memory cluster + the real
daemons: numbered step files apply manifests through the admission
webhook chain (mutate → validate, enforce denials fail the apply, the
way the API server would), ``NN-assert.yaml`` subset-matches live CRs
after controller ticks, ``NN-errors.yaml`` asserts absence.  TestStep
``apply:`` entries honor ``shouldFail``; the common
``if kubectl apply -f X`` deny-check script pattern is recognized.
Unsupported commands surface as :class:`Unsupported` so callers can
list divergences instead of mis-reporting them as passes.
"""

from __future__ import annotations

import json
import os
import re
from typing import Any, List, Optional, Tuple

import yaml

from ..cmd.admission_controller import AdmissionController
from ..cmd.background_controller import BackgroundController
from ..cmd.internal import Setup, base_parser
from ..cmd.reports_controller import ReportsController
from ..dclient.client import ApiError, FakeClient, NotFoundError


class KuttlFailure(AssertionError):
    """A replayed step diverged from the recorded expectation."""


class Unsupported(Exception):
    """The step uses a kuttl feature the replay harness cannot model."""


class AdmissionDenied(Exception):
    def __init__(self, message: str):
        super().__init__(message)


_STEP_RE = re.compile(r'^(\d+)-(.+)\.yaml$')
# the corpus' standard denial-check script shape
_DENY_SCRIPT_RE = re.compile(
    r'if\s+kubectl\s+apply\s+-f\s+(\S+)', re.MULTILINE)
_APPLY_CMD_RE = re.compile(r'^kubectl\s+apply\s+-f\s+(\S+)\s*$')


class KuttlCluster:
    """One in-memory cluster wired with the three daemons."""

    def __init__(self):
        self.client = FakeClient()
        setup = Setup('kuttl', [], base_parser('kuttl'), client=self.client)
        self.admission = AdmissionController(setup, tls=False)
        self.background = BackgroundController(setup)
        self.reports = ReportsController(setup)
        from ..controllers.cleanup import CleanupController
        self.cleanup = CleanupController(self.client)
        self._uid = 0
        self.client.create_resource('v1', 'Namespace', '', {
            'apiVersion': 'v1', 'kind': 'Namespace',
            'metadata': {'name': 'default'}})
        # the chart's install-time objects (aggregated ClusterRoles)
        # exist in any real cluster the corpus runs against
        from ..config.install import seed_install_manifests
        seed_install_manifests(self.client)

    # -- plumbing ----------------------------------------------------------

    def tick(self) -> None:
        self.admission.flush_audits()
        self.admission.tick()
        self.background.tick()
        self.reports.tick()
        # cleanup policies run on their cron; a tick stands in for the
        # corpus' sleep-past-the-minute steps.  Deleted policies must
        # also leave the controller or they keep firing.
        live = set()
        for kind in ('ClusterCleanupPolicy', 'CleanupPolicy'):
            for doc in self.client.list_resource(
                    'kyverno.io/v2alpha1', kind):
                self.cleanup.set_policy(doc)
                live.add(self.cleanup._key(doc))
        self.cleanup.retain_policies(live)
        self.cleanup.tick()
        self.admission.event_generator.drain(timeout=3)

    def _review(self, doc: dict, operation: str,
                old: Optional[dict], sub_resource: str = '') -> bytes:
        self._uid += 1
        meta = doc.get('metadata') or {}
        return json.dumps({
            'apiVersion': 'admission.k8s.io/v1', 'kind': 'AdmissionReview',
            'request': {
                'uid': f'kuttl-{self._uid}', 'operation': operation,
                'kind': {'group': '', 'version': 'v1',
                         'kind': doc.get('kind', '')},
                'subResource': sub_resource,
                'namespace': meta.get('namespace', ''),
                'name': meta.get('name', ''),
                'object': doc, 'oldObject': old,
                'userInfo': {'username': 'kuttl-admin',
                             'groups': ['system:masters']},
            }}).encode()

    def _ensure_namespace(self, doc: dict) -> None:
        ns = (doc.get('metadata') or {}).get('namespace', '')
        if not ns:
            return
        try:
            self.client.get_resource('v1', 'Namespace', '', ns)
        except NotFoundError:
            self.client.create_resource('v1', 'Namespace', '', {
                'apiVersion': 'v1', 'kind': 'Namespace',
                'metadata': {'name': ns}})

    # -- apply -------------------------------------------------------------

    #: kinds stored without a namespace (everything else defaults to
    #: 'default' when the manifest names none, the way kubectl does)
    _CLUSTER_SCOPED = {
        'Namespace', 'Node', 'ClusterPolicy', 'ClusterCleanupPolicy',
        'ClusterRole', 'ClusterRoleBinding', 'CustomResourceDefinition',
        'ValidatingWebhookConfiguration', 'MutatingWebhookConfiguration',
        'ClusterPolicyReport', 'ClusterAdmissionReport',
        'ClusterBackgroundScanReport', 'PriorityClass', 'StorageClass',
    }

    def apply_doc(self, doc: dict) -> None:
        """Apply one manifest the way ``kubectl apply`` + the admission
        chain would; raises AdmissionDenied on an enforce block."""
        kind = doc.get('kind', '')
        api_version = doc.get('apiVersion', '')
        meta = doc.setdefault('metadata', {})
        if kind not in self._CLUSTER_SCOPED and not meta.get('namespace'):
            meta['namespace'] = 'default'
        if kind in ('ClusterCleanupPolicy', 'CleanupPolicy'):
            # the cleanup controller's own admission webhook validates
            # these (cmd/cleanup-controller/handlers/admission/policy.go)
            from ..controllers.cleanup import validate_cleanup_admission
            resp = validate_cleanup_admission(
                {'uid': 'kuttl', 'object': doc}, self.client)
            if not resp.get('allowed', True):
                raise AdmissionDenied(
                    (resp.get('status') or {}).get('message', 'denied'))
            self._store(api_version, kind, meta.get('namespace', ''), doc)
            self.admission.tick()
            return
        if kind in ('ClusterPolicy', 'Policy'):
            # policy CR admission (reference: pkg/webhooks/policy/
            # handlers.go served at /policyvalidate)
            from ..policy.validate import validate_policy_admission
            resp = validate_policy_admission(
                {'uid': 'kuttl', 'object': doc}, self.client)
            if not resp.get('allowed', True):
                raise AdmissionDenied(
                    (resp.get('status') or {}).get('message', 'denied'))
            self._store(api_version, kind, meta.get('namespace', ''), doc)
            self.admission.tick()
            return
        if kind == 'PolicyException':
            self._store(api_version, kind, meta.get('namespace', ''), doc)
            self.admission.tick()
            return
        if kind == 'Deployment':
            # stand in for the deployment controller: kuttl asserts read
            # back rollout status a real cluster would converge to
            replicas = int((doc.get('spec') or {}).get('replicas', 1))
            doc.setdefault('status', {
                'replicas': replicas, 'readyReplicas': replicas,
                'availableReplicas': replicas,
                'updatedReplicas': replicas,
                'conditions': [{'type': 'Available', 'status': 'True',
                                'reason': 'MinimumReplicasAvailable'}],
            })
        if kind == 'CustomResourceDefinition':
            # the API server populates acceptedNames/conditions on CRD
            # create; asserts in the corpus read them back
            doc.setdefault('status', {
                'acceptedNames': dict(
                    (doc.get('spec') or {}).get('names') or {},
                    categories=((doc.get('spec') or {}).get('names') or
                                {}).get('categories', ['all'])),
                'conditions': [
                    {'type': 'NamesAccepted', 'status': 'True',
                     'reason': 'NoConflicts',
                     'message': 'no conflicts found'},
                    {'type': 'Established', 'status': 'True',
                     'reason': 'InitialNamesAccepted',
                     'message': 'the initial names have been accepted'},
                ],
                'storedVersions': [
                    v.get('name') for v in
                    ((doc.get('spec') or {}).get('versions') or [])
                    if v.get('storage')],
            })
        self._ensure_namespace(doc)
        exists, old = self._existing(api_version, kind, doc)
        operation = 'UPDATE' if exists else 'CREATE'
        # the API server assigns the uid before admission webhooks run
        if exists:
            doc.setdefault('metadata', {}).setdefault(
                'uid', (old.get('metadata') or {}).get('uid', ''))
        else:
            self._uid += 1
            doc.setdefault('metadata', {}).setdefault(
                'uid', f'kuttl-uid-{self._uid}')
        # API-server order: mutating webhooks run before validating ones
        body = self.admission.server.handle(
            '/mutate', self._review(doc, operation, old))
        resp = json.loads(body)['response']
        if not resp.get('allowed', True):
            raise AdmissionDenied(
                (resp.get('status') or {}).get('message', 'denied'))
        patched = doc
        patch_b64 = resp.get('patch')
        if patch_b64:
            import base64
            from ..engine.mutate.jsonpatch import apply_patch
            patched = apply_patch(
                json.loads(json.dumps(doc)),
                json.loads(base64.b64decode(patch_b64)))
        body = self.admission.server.handle(
            '/validate', self._review(patched, operation, old))
        resp = json.loads(body)['response']
        if not resp.get('allowed', True):
            raise AdmissionDenied(
                (resp.get('status') or {}).get('message', 'denied'))
        self._store(api_version, kind, (patched.get('metadata') or
                                        {}).get('namespace', ''), patched)

    def close(self) -> None:
        """Reap worker threads (a conformance run spins up many
        clusters; leaked event workers busy-poll the queue forever)."""
        self.admission.close()

    def delete_doc(self, api_version: str, kind: str, namespace: str,
                   name: str) -> None:
        """Delete through the admission chain (DELETE reviews carry the
        old object and can spawn mutate-existing URs / be denied)."""
        try:
            old = self.client.get_resource(api_version, kind, namespace,
                                           name)
        except ApiError:
            raise NotFoundError(f'{kind} "{name}" not found')
        self._uid += 1
        review = json.dumps({
            'apiVersion': 'admission.k8s.io/v1', 'kind': 'AdmissionReview',
            'request': {
                'uid': f'kuttl-{self._uid}', 'operation': 'DELETE',
                'kind': {'group': '', 'version': 'v1', 'kind': kind},
                'namespace': namespace, 'name': name,
                'object': None, 'oldObject': old,
                'userInfo': {'username': 'kuttl-admin',
                             'groups': ['system:masters']},
            }}).encode()
        body = self.admission.server.handle('/validate', review)
        resp = json.loads(body)['response']
        if not resp.get('allowed', True):
            raise AdmissionDenied(
                (resp.get('status') or {}).get('message', 'denied'))
        self.client.delete_resource(api_version, kind, namespace, name)

    def _existing(self, api_version: str, kind: str,
                  doc: dict) -> Tuple[bool, Optional[dict]]:
        meta = doc.get('metadata') or {}
        try:
            old = self.client.get_resource(
                api_version, kind, meta.get('namespace', ''),
                meta.get('name', ''))
            return True, old
        except ApiError:
            return False, None

    def _store(self, api_version: str, kind: str, namespace: str,
               doc: dict) -> None:
        try:
            self.client.create_resource(api_version, kind, namespace, doc)
        except ApiError:
            current = self.client.get_resource(
                api_version, kind, namespace,
                (doc.get('metadata') or {}).get('name', ''))
            merged = dict(doc)
            merged.setdefault('metadata', {})['resourceVersion'] = \
                (current.get('metadata') or {}).get('resourceVersion')
            self.client.update_resource(api_version, kind, namespace,
                                        merged)

    # -- asserts -----------------------------------------------------------

    def assert_doc(self, expected: dict, rounds: int = 5) -> None:
        """kuttl assert: some live resource must subset-match; controller
        ticks stand in for kuttl's polling."""
        last = None
        for _ in range(rounds):
            ok, last = self._match_once(expected)
            if ok:
                return
            self.tick()
        raise KuttlFailure(
            f'no live {expected.get("kind")} matches assert '
            f'{json.dumps(expected)[:300]}; closest: '
            f'{json.dumps(last)[:300] if last else "none"}')

    def assert_absent(self, expected: dict, rounds: int = 2) -> None:
        for _ in range(rounds):
            self.tick()
        ok, matched = self._match_once(expected)
        if ok:
            raise KuttlFailure(
                f'{expected.get("kind")} unexpectedly present: '
                f'{json.dumps(matched)[:300]}')

    def _match_once(self, expected: dict
                    ) -> Tuple[bool, Optional[dict]]:
        kind = expected.get('kind', '')
        api_version = expected.get('apiVersion', '')
        if api_version.startswith('kyverno.io/'):
            # policy CRDs are multi-version served; the fake stores one
            # version, asserts may name another — conversion-equivalent
            expected = dict(expected)
            expected.pop('apiVersion')
            api_version = ''
        meta = expected.get('metadata') or {}
        name = meta.get('name', '')
        ns = meta.get('namespace', '')
        candidates = []
        if name:
            try:
                candidates = [self.client.get_resource(
                    api_version, kind, ns, name)]
            except ApiError:
                # report CR names are nondeterministic; fall back to a
                # kind-wide sweep
                candidates = self.client.list_resource('', kind, ns)
        else:
            candidates = self.client.list_resource('', kind, ns)
        best = candidates[0] if candidates else None
        for cand in candidates:
            if _subset(expected, cand, skip_keys={'resourceVersion'}):
                return True, cand
        return False, best


def _subset(expected: Any, actual: Any, skip_keys=frozenset()) -> bool:
    """kuttl subset matching: every expected field must be present and
    equal; lists match index-wise as subsets."""
    if isinstance(expected, dict):
        if not isinstance(actual, dict):
            return False
        for k, v in expected.items():
            if k in skip_keys:
                continue
            if k not in actual:
                return False
            if not _subset(v, actual[k], skip_keys):
                return False
        return True
    if isinstance(expected, list):
        if not isinstance(actual, list) or len(actual) < len(expected):
            return False
        return all(_subset(e, a, skip_keys)
                   for e, a in zip(expected, actual))
    if isinstance(expected, (int, float)) and \
            isinstance(actual, (int, float)):
        return float(expected) == float(actual)
    return expected == actual


def _load_docs(path: str) -> List[dict]:
    with open(path) as f:
        return [d for d in yaml.safe_load_all(f) if d]


def run_suite(suite_dir: str) -> None:
    """Replay one kuttl test directory; raises KuttlFailure on
    divergence, Unsupported on unreplayable steps."""
    cluster = KuttlCluster()
    steps = []
    for name in os.listdir(suite_dir):
        m = _STEP_RE.match(name)
        if m:
            label = m.group(2)
            # kuttl runs the index's step first, then checks its assert
            # and error files
            if label == 'assert' or label.endswith('-assert'):
                rank = 1
            elif label in ('errors', 'error') or label.endswith('-errors'):
                rank = 2
            else:
                rank = 0
            steps.append((int(m.group(1)), rank, label, name))
    steps.sort()
    steps = [(num, label, name) for num, _rank, label, name in steps]
    try:
        _run_steps(cluster, suite_dir, steps)
    finally:
        cluster.close()


def _run_steps(cluster: KuttlCluster, suite_dir: str, steps) -> None:
    for _num, label, name in steps:
        path = os.path.join(suite_dir, name)
        docs = _load_docs(path)
        if label == 'assert' or label.endswith('-assert'):
            for doc in docs:
                if doc.get('kind') == 'TestAssert':
                    # timeout/collector tuning — ticks stand in for the
                    # poll budget; replay its command list if any
                    for c in doc.get('commands') or []:
                        _run_command(cluster, suite_dir, c)
                    continue
                cluster.assert_doc(doc)
            continue
        if label in ('errors', 'error') or label.endswith('-errors'):
            for doc in docs:
                if doc.get('kind') == 'TestAssert':
                    continue
                cluster.assert_absent(doc)
            continue
        for doc in docs:
            if doc.get('kind') == 'TestStep':
                _run_test_step(cluster, suite_dir, doc)
            else:
                cluster.apply_doc(doc)
        cluster.tick()


def _run_test_step(cluster: KuttlCluster, suite_dir: str,
                   step: dict) -> None:
    for entry in step.get('delete') or []:
        ref = entry.get('ref') or entry
        try:
            cluster.delete_doc(
                ref.get('apiVersion', ''), ref.get('kind', ''),
                ref.get('namespace', ''), ref.get('name', ''))
        except ApiError:
            pass
    for entry in step.get('apply') or []:
        if isinstance(entry, str):
            fname, should_fail = entry, False
        else:
            fname = entry.get('file', '')
            should_fail = bool(entry.get('shouldFail'))
        _apply_file(cluster, os.path.join(suite_dir, fname), should_fail)
    for cmd in step.get('commands') or []:
        _run_command(cluster, suite_dir, cmd)
    for fname in step.get('assert') or []:
        for doc in _load_docs(os.path.join(suite_dir, fname)):
            cluster.assert_doc(doc)
    for fname in step.get('error') or []:
        for doc in _load_docs(os.path.join(suite_dir, fname)):
            cluster.assert_absent(doc)


def _apply_file(cluster: KuttlCluster, path: str, should_fail: bool,
                deny_phrase: Optional[str] = None) -> None:
    denied: Optional[AdmissionDenied] = None
    for doc in _load_docs(path):
        try:
            cluster.apply_doc(doc)
        except AdmissionDenied as e:
            denied = e
    if should_fail and denied is None:
        raise KuttlFailure(
            f'{os.path.basename(path)} applied cleanly but the corpus '
            f'expects a denial')
    if not should_fail and denied is not None:
        raise KuttlFailure(
            f'{os.path.basename(path)} denied unexpectedly: {denied}')
    if should_fail and deny_phrase and deny_phrase not in str(denied):
        raise KuttlFailure(
            f'{os.path.basename(path)} denied, but the message lacks the '
            f'expected phrase {deny_phrase!r}: {denied}')
    cluster.tick()


#: kubectl short-name / plural aliases the corpus uses
_KIND_ALIASES = {
    'cpol': ('kyverno.io/v1', 'ClusterPolicy'),
    'clusterpolicy': ('kyverno.io/v1', 'ClusterPolicy'),
    'clusterpolicies': ('kyverno.io/v1', 'ClusterPolicy'),
    'pol': ('kyverno.io/v1', 'Policy'),
    'policy': ('kyverno.io/v1', 'Policy'),
    'policies': ('kyverno.io/v1', 'Policy'),
    'polex': ('kyverno.io/v2beta1', 'PolicyException'),
    'ur': ('kyverno.io/v1beta1', 'UpdateRequest'),
    'updaterequest': ('kyverno.io/v1beta1', 'UpdateRequest'),
    'updaterequests': ('kyverno.io/v1beta1', 'UpdateRequest'),
    'pod': ('v1', 'Pod'), 'pods': ('v1', 'Pod'), 'po': ('v1', 'Pod'),
    'ns': ('v1', 'Namespace'), 'namespace': ('v1', 'Namespace'),
    'namespaces': ('v1', 'Namespace'),
    'cm': ('v1', 'ConfigMap'), 'configmap': ('v1', 'ConfigMap'),
    'configmaps': ('v1', 'ConfigMap'),
    'secret': ('v1', 'Secret'), 'secrets': ('v1', 'Secret'),
    'svc': ('v1', 'Service'), 'service': ('v1', 'Service'),
    'deploy': ('apps/v1', 'Deployment'),
    'deployment': ('apps/v1', 'Deployment'),
    'deployments': ('apps/v1', 'Deployment'),
    'node': ('v1', 'Node'), 'nodes': ('v1', 'Node'),
    'netpol': ('networking.k8s.io/v1', 'NetworkPolicy'),
    'cleanuppolicy': ('kyverno.io/v2alpha1', 'CleanupPolicy'),
    'clustercleanuppolicy': ('kyverno.io/v2alpha1',
                             'ClusterCleanupPolicy'),
    'crd': ('apiextensions.k8s.io/v1', 'CustomResourceDefinition'),
    'crds': ('apiextensions.k8s.io/v1', 'CustomResourceDefinition'),
}


def _do_scale(cluster: KuttlCluster, kind_tok: str, name: str, ns: str,
              replicas: int, expect_deny: bool,
              phrase: Optional[str]) -> None:
    """Replay ``kubectl scale`` as the scale-subresource UPDATE it is:
    policies match ``<Kind>/scale`` (reference: the webhook registers
    the deployments/scale resource and the engine matches subresources,
    pkg/utils/match CheckKind)."""
    import copy as _copy
    resolved = _resolve_kind(cluster, kind_tok)
    if resolved is None:
        raise Unsupported(f'scale kind {kind_tok!r} unknown')
    api_version, kind = resolved
    try:
        current = cluster.client.get_resource(api_version or 'apps/v1',
                                              kind, ns, name)
    except ApiError:
        raise Unsupported(f'scale target {kind}/{name} not found')
    patched = _copy.deepcopy(current)
    patched.setdefault('spec', {})['replicas'] = replicas
    body = cluster.admission.server.handle(
        '/validate', cluster._review(patched, 'UPDATE', current,
                                     sub_resource='scale'))
    resp = json.loads(body)['response']
    allowed = resp.get('allowed', True)
    message = (resp.get('status') or {}).get('message', '')
    if expect_deny and allowed:
        raise KuttlFailure(f'scale of {kind}/{name} was not denied')
    if not expect_deny and not allowed:
        raise KuttlFailure(f'scale of {kind}/{name} denied: {message}')
    if expect_deny and phrase and phrase not in message:
        raise KuttlFailure(
            f'scale denial message lacks {phrase!r}: {message}')
    if allowed:
        patched['status'] = dict(patched.get('status') or {},
                                 replicas=replicas)
        cluster.client.update_resource(
            patched.get('apiVersion', api_version), kind, ns, patched)
    cluster.tick()


def _do_patch(cluster: KuttlCluster, argstr: str, expect_deny: bool,
              phrase: Optional[str]) -> None:
    """Replay ``kubectl patch <Kind> <name> [-n ns] --type=t -p=<doc>``
    through the admission chain as the UPDATE it performs."""
    toks = argstr.split()
    if len(toks) < 2:
        raise Unsupported(f'patch args not replayable: {argstr[:80]!r}')
    kind_tok, name = toks[0], toks[1]
    api_version, kind = _KIND_ALIASES.get(
        kind_tok.lower(), ('', kind_tok))
    ns = _flag_value(toks, '-n') or _flag_value(toks, '--namespace') or ''
    ptype = (_flag_value(toks, '--type') or 'strategic').strip("'\"")
    mp = re.search(r'(?:^|\s)-p=?\s*(.+)$', argstr, re.S)
    if not mp:
        raise Unsupported(f'patch without -p: {argstr[:80]!r}')
    payload = mp.group(1).strip()
    # tiered un-quoting: try the payload as-is first (empty-string
    # values are legitimate), then undo the corpus scripts' shell
    # quoting (\" escapes, "" concatenation seams)
    def _valid(d):
        if isinstance(d, dict):
            return True
        return isinstance(d, list) and d and all(
            isinstance(o, dict) and 'op' in o for o in d)

    doc = None
    for candidate in (payload.strip('"\''),
                      payload.strip('"').replace('\\"', '"'),
                      payload.strip('"').replace('\\"', '"')
                      .replace('""', '')):
        try:
            parsed = yaml.safe_load(candidate)
        except Exception:  # noqa: BLE001 - try the next unquoting tier
            continue
        if _valid(parsed):
            doc = parsed
            break
    if doc is None:
        raise Unsupported(f'unparseable patch payload: {payload[:80]!r}')
    try:
        current = cluster.client.get_resource(api_version, kind, ns, name)
    except ApiError:
        raise Unsupported(f'patch target {kind}/{name} not found')
    if ptype == 'json':
        from ..engine.mutate.jsonpatch import apply_patch
        patched = apply_patch(current, doc)
    else:
        from ..engine.mutate.strategic import strategic_merge
        patched = strategic_merge(current, doc)
    denied: Optional[AdmissionDenied] = None
    try:
        cluster.apply_doc(patched)
    except AdmissionDenied as e:
        denied = e
    if expect_deny and denied is None:
        raise KuttlFailure(
            f'patch of {kind}/{name} applied cleanly but the corpus '
            f'expects a denial')
    if not expect_deny and denied is not None:
        raise KuttlFailure(f'patch of {kind}/{name} denied: {denied}')
    if expect_deny and phrase and phrase not in str(denied):
        raise KuttlFailure(
            f'patch denial message lacks the expected phrase '
            f'{phrase!r}: {denied}')
    cluster.tick()


def _resolve_kind(cluster: KuttlCluster, token: str
                  ) -> Optional[Tuple[str, str]]:
    """(apiVersion, Kind) for a kubectl kind token: the static alias
    table first, then the live store (covers custom resources whose CRDs
    the suite itself created)."""
    hit = _KIND_ALIASES.get(token.lower())
    if hit is not None:
        return hit
    t = token.lower()
    for obj in cluster.client.list_resource('', '', ''):
        kind = obj.get('kind', '')
        low = kind.lower()
        if t in (low, low + 's', low + 'es',
                 (low[:-1] + 'ies') if low.endswith('y') else low):
            return obj.get('apiVersion', ''), kind
    return None


def _flag_value(tokens: List[str], flag: str) -> Optional[str]:
    for i, tok in enumerate(tokens):
        if tok == flag and i + 1 < len(tokens):
            return tokens[i + 1]
        if tok.startswith(flag + '='):
            return tok.split('=', 1)[1]
    return None


def _run_command(cluster: KuttlCluster, suite_dir: str,
                 cmd: dict) -> None:
    script = cmd.get('script', '') or cmd.get('command', '')
    if isinstance(script, list):
        script = ' '.join(str(s) for s in script)
    sm = re.search(
        r'if\s+kubectl\s+scale\s+(\S+)\s+(\S+)\s+--replicas[= ](\d+)'
        r'(?:\s+-n\s+(\S+))?.*?grep\s+-q\s+(["\'])(.*?)\5', script, re.S)
    if sm is None:
        sm2 = re.match(
            r'^kubectl\s+scale\s+(\S+)\s+(\S+)\s+--replicas[= ](\d+)'
            r'(?:\s+-n\s+(\S+))?', script.strip())
        if sm2 is not None:
            _do_scale(cluster, sm2.group(1), sm2.group(2),
                      sm2.group(4) or 'default', int(sm2.group(3)),
                      expect_deny=False, phrase=None)
            return
    else:
        _do_scale(cluster, sm.group(1), sm.group(2),
                  sm.group(4) or 'default', int(sm.group(3)),
                  expect_deny=True, phrase=sm.group(6))
        return
    pm = re.search(
        r'if\s+kubectl\s+patch\s+(.+?)\s+2>&1\s*\|\s*grep\s+-q\s+'
        r'(["\'])(.*?)\2', script, re.S)
    if pm:
        _do_patch(cluster, pm.group(1), expect_deny=True,
                  phrase=pm.group(3))
        return
    m = re.match(r'^kubectl\s+patch\s+(.+)$', script.strip(), re.S)
    if m:
        _do_patch(cluster, m.group(1), expect_deny=False, phrase=None)
        return
    m = _DENY_SCRIPT_RE.search(script)
    if m:
        # the corpus writes both polarities of this script: the branch
        # that exits 0 tells us whether the apply is expected to be
        # denied (grep-on-error / plain-if with exit 1 in then) or to
        # succeed (plain-if with exit 0 in then)
        phrase = None
        pm = re.search(r"grep\s+-q\s+'([^']+)'", script) or \
            re.search(r'grep\s+-q\s+"([^"]+)"', script)
        if pm:
            phrase = pm.group(1)
        bm = re.search(r'\bthen\b(.*?)(?:\belse\b(.*?))?\bfi\b', script,
                       re.S)
        then_block = bm.group(1) if bm else ''
        if pm is not None:
            should_fail = 'exit 1' not in then_block.split('echo')[0] \
                and 'exit 0' in then_block
        else:
            should_fail = 'exit 1' in then_block
        _apply_file(cluster, os.path.join(suite_dir, m.group(1)),
                    should_fail=should_fail, deny_phrase=phrase)
        return
    m = _APPLY_CMD_RE.match(script.strip())
    if m:
        _apply_file(cluster, os.path.join(suite_dir, m.group(1)),
                    should_fail=False)
        return
    m = re.match(r'^kubectl\s+delete\s+-f\s+(\S+)', script.strip())
    if m:
        for fname in m.group(1).split(','):
            path = os.path.join(suite_dir, fname)
            if not os.path.exists(path):
                continue
            for doc in _load_docs(path):
                meta = doc.get('metadata') or {}
                try:
                    cluster.delete_doc(
                        doc.get('apiVersion', ''), doc.get('kind', ''),
                        meta.get('namespace', ''), meta.get('name', ''))
                except ApiError:
                    pass
        cluster.tick()
        return
    tokens = script.strip().split()
    # kubectl delete <kind> [<name>] [-n ns] [-A --all --force ...]
    delete_kind = _resolve_kind(cluster, tokens[2]) \
        if len(tokens) >= 3 and tokens[0] == 'kubectl' and \
        tokens[1] == 'delete' else None
    if delete_kind is not None:
        api_version, kind = delete_kind
        ns = _flag_value(tokens, '-n') or \
            _flag_value(tokens, '--namespace') or ''
        names = [t for t in tokens[3:] if not t.startswith('-')
                 and t != ns]
        delete_all = '--all' in tokens or '-A' in tokens
        if delete_all:
            targets = cluster.client.list_resource('', kind, ns)
            names = [(t.get('metadata') or {}).get('name', '')
                     for t in targets]
        for name in names:
            try:
                cluster.delete_doc(api_version, kind, ns, name)
            except ApiError:
                pass
        cluster.tick()
        return
    # kubectl label <kind> <name> key=value | key-
    if len(tokens) >= 5 and tokens[0] == 'kubectl' and \
            tokens[1] == 'label' and tokens[2].lower() in _KIND_ALIASES:
        api_version, kind = _KIND_ALIASES[tokens[2].lower()]
        name = tokens[3]
        ns = _flag_value(tokens, '-n') or ''
        try:
            obj = cluster.client.get_resource(api_version, kind, ns, name)
        except ApiError:
            raise Unsupported(
                f'label target {kind}/{name} absent from the fake '
                f'cluster (no real nodes here)')
        labels = obj.setdefault('metadata', {}).setdefault('labels', {})
        for spec in tokens[4:]:
            if spec.startswith('-'):
                continue
            if spec.endswith('-') and '=' not in spec:
                labels.pop(spec[:-1], None)
            elif '=' in spec:
                k, v = spec.split('=', 1)
                labels[k] = v
        cluster.client.update_resource(api_version, kind, ns, obj)
        cluster.tick()
        return
    # kubectl [-n ns] create cm <name> --from-literal=k=v ...
    m = re.match(
        r'^kubectl\s+(?:-n\s+(\S+)\s+)?create\s+(?:cm|configmap)\s+(\S+)'
        r'(.*)$', script.strip())
    if m:
        ns, name, rest = m.group(1) or 'default', m.group(2), m.group(3)
        data = {}
        for lit in re.findall(r'--from-literal=([^=\s]+)=(\S+)', rest):
            data[lit[0]] = lit[1]
        cluster.apply_doc({'apiVersion': 'v1', 'kind': 'ConfigMap',
                           'metadata': {'name': name, 'namespace': ns},
                           'data': data})
        cluster.tick()
        return
    if re.fullmatch(r'sleep\s+\d+', script.strip()):
        cluster.tick()
        return
    if len(tokens) >= 3 and tokens[0] == 'kubectl' and \
            tokens[1] == 'delete' and '--ignore-not-found' in script:
        # deleting an unknown kind with --ignore-not-found is a no-op
        # (cleanup steps for resources an earlier denial never created)
        cluster.tick()
        return
    raise Unsupported(f'command not replayable: {script[:120]!r}')
