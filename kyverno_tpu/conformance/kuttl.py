"""kuttl step-replay harness (reference corpus:
/root/reference/test/conformance/kuttl — SURVEY.md §4).

Replays a kuttl test directory against the in-memory cluster + the real
daemons: numbered step files apply manifests through the admission
webhook chain (mutate → validate, enforce denials fail the apply, the
way the API server would), ``NN-assert.yaml`` subset-matches live CRs
after controller ticks, ``NN-errors.yaml`` asserts absence.  TestStep
``apply:`` entries honor ``shouldFail``; the common
``if kubectl apply -f X`` deny-check script pattern is recognized.
Unsupported commands surface as :class:`Unsupported` so callers can
list divergences instead of mis-reporting them as passes.
"""

from __future__ import annotations

import json
import os
import re
from typing import Any, List, Optional, Tuple

import yaml

from ..cmd.admission_controller import AdmissionController
from ..cmd.background_controller import BackgroundController
from ..cmd.internal import Setup, base_parser
from ..cmd.reports_controller import ReportsController
from ..dclient.client import ApiError, FakeClient, NotFoundError


class KuttlFailure(AssertionError):
    """A replayed step diverged from the recorded expectation."""


class Unsupported(Exception):
    """The step uses a kuttl feature the replay harness cannot model."""


class AdmissionDenied(Exception):
    def __init__(self, message: str):
        super().__init__(message)


_STEP_RE = re.compile(r'^(\d+)-(.+)\.yaml$')
# the corpus' standard denial-check script shape
_DENY_SCRIPT_RE = re.compile(
    r'if\s+kubectl\s+apply\s+-f\s+(\S+)', re.MULTILINE)
_APPLY_CMD_RE = re.compile(r'^kubectl\s+apply\s+-f\s+(\S+)\s*$')


class KuttlCluster:
    """One in-memory cluster wired with the three daemons."""

    def __init__(self):
        self.client = FakeClient()
        setup = Setup('kuttl', [], base_parser('kuttl'), client=self.client)
        self.admission = AdmissionController(setup, tls=False)
        self.background = BackgroundController(setup)
        self.reports = ReportsController(setup)
        self._uid = 0
        self.client.create_resource('v1', 'Namespace', '', {
            'apiVersion': 'v1', 'kind': 'Namespace',
            'metadata': {'name': 'default'}})

    # -- plumbing ----------------------------------------------------------

    def tick(self) -> None:
        self.admission.flush_audits()
        self.admission.tick()
        self.background.tick()
        self.reports.tick()

    def _review(self, doc: dict, operation: str,
                old: Optional[dict]) -> bytes:
        self._uid += 1
        meta = doc.get('metadata') or {}
        return json.dumps({
            'apiVersion': 'admission.k8s.io/v1', 'kind': 'AdmissionReview',
            'request': {
                'uid': f'kuttl-{self._uid}', 'operation': operation,
                'kind': {'group': '', 'version': 'v1',
                         'kind': doc.get('kind', '')},
                'namespace': meta.get('namespace', ''),
                'name': meta.get('name', ''),
                'object': doc, 'oldObject': old,
                'userInfo': {'username': 'kuttl-admin',
                             'groups': ['system:masters']},
            }}).encode()

    def _ensure_namespace(self, doc: dict) -> None:
        ns = (doc.get('metadata') or {}).get('namespace', '')
        if not ns:
            return
        try:
            self.client.get_resource('v1', 'Namespace', '', ns)
        except NotFoundError:
            self.client.create_resource('v1', 'Namespace', '', {
                'apiVersion': 'v1', 'kind': 'Namespace',
                'metadata': {'name': ns}})

    # -- apply -------------------------------------------------------------

    def apply_doc(self, doc: dict) -> None:
        """Apply one manifest the way ``kubectl apply`` + the admission
        chain would; raises AdmissionDenied on an enforce block."""
        kind = doc.get('kind', '')
        api_version = doc.get('apiVersion', '')
        meta = doc.get('metadata') or {}
        if kind in ('ClusterPolicy', 'Policy', 'PolicyException',
                    'ClusterCleanupPolicy', 'CleanupPolicy'):
            self._store(api_version, kind, meta.get('namespace', ''), doc)
            self.admission.tick()
            return
        self._ensure_namespace(doc)
        exists, old = self._existing(api_version, kind, doc)
        operation = 'UPDATE' if exists else 'CREATE'
        # the API server assigns the uid before admission webhooks run
        if exists:
            doc.setdefault('metadata', {}).setdefault(
                'uid', (old.get('metadata') or {}).get('uid', ''))
        else:
            self._uid += 1
            doc.setdefault('metadata', {}).setdefault(
                'uid', f'kuttl-uid-{self._uid}')
        # API-server order: mutating webhooks run before validating ones
        body = self.admission.server.handle(
            '/mutate', self._review(doc, operation, old))
        resp = json.loads(body)['response']
        if not resp.get('allowed', True):
            raise AdmissionDenied(
                (resp.get('status') or {}).get('message', 'denied'))
        patched = doc
        patch_b64 = resp.get('patch')
        if patch_b64:
            import base64
            from ..engine.mutate.jsonpatch import apply_patch
            patched = apply_patch(
                json.loads(json.dumps(doc)),
                json.loads(base64.b64decode(patch_b64)))
        body = self.admission.server.handle(
            '/validate', self._review(patched, operation, old))
        resp = json.loads(body)['response']
        if not resp.get('allowed', True):
            raise AdmissionDenied(
                (resp.get('status') or {}).get('message', 'denied'))
        self._store(api_version, kind, (patched.get('metadata') or
                                        {}).get('namespace', ''), patched)

    def _existing(self, api_version: str, kind: str,
                  doc: dict) -> Tuple[bool, Optional[dict]]:
        meta = doc.get('metadata') or {}
        try:
            old = self.client.get_resource(
                api_version, kind, meta.get('namespace', ''),
                meta.get('name', ''))
            return True, old
        except ApiError:
            return False, None

    def _store(self, api_version: str, kind: str, namespace: str,
               doc: dict) -> None:
        try:
            self.client.create_resource(api_version, kind, namespace, doc)
        except ApiError:
            current = self.client.get_resource(
                api_version, kind, namespace,
                (doc.get('metadata') or {}).get('name', ''))
            merged = dict(doc)
            merged.setdefault('metadata', {})['resourceVersion'] = \
                (current.get('metadata') or {}).get('resourceVersion')
            self.client.update_resource(api_version, kind, namespace,
                                        merged)

    # -- asserts -----------------------------------------------------------

    def assert_doc(self, expected: dict, rounds: int = 5) -> None:
        """kuttl assert: some live resource must subset-match; controller
        ticks stand in for kuttl's polling."""
        last = None
        for _ in range(rounds):
            ok, last = self._match_once(expected)
            if ok:
                return
            self.tick()
        raise KuttlFailure(
            f'no live {expected.get("kind")} matches assert '
            f'{json.dumps(expected)[:300]}; closest: '
            f'{json.dumps(last)[:300] if last else "none"}')

    def assert_absent(self, expected: dict, rounds: int = 2) -> None:
        for _ in range(rounds):
            self.tick()
        ok, matched = self._match_once(expected)
        if ok:
            raise KuttlFailure(
                f'{expected.get("kind")} unexpectedly present: '
                f'{json.dumps(matched)[:300]}')

    def _match_once(self, expected: dict
                    ) -> Tuple[bool, Optional[dict]]:
        kind = expected.get('kind', '')
        api_version = expected.get('apiVersion', '')
        if api_version.startswith('kyverno.io/'):
            # policy CRDs are multi-version served; the fake stores one
            # version, asserts may name another — conversion-equivalent
            expected = dict(expected)
            expected.pop('apiVersion')
            api_version = ''
        meta = expected.get('metadata') or {}
        name = meta.get('name', '')
        ns = meta.get('namespace', '')
        candidates = []
        if name:
            try:
                candidates = [self.client.get_resource(
                    api_version, kind, ns, name)]
            except ApiError:
                # report CR names are nondeterministic; fall back to a
                # kind-wide sweep
                candidates = self.client.list_resource('', kind, ns)
        else:
            candidates = self.client.list_resource('', kind, ns)
        best = candidates[0] if candidates else None
        for cand in candidates:
            if _subset(expected, cand, skip_keys={'resourceVersion'}):
                return True, cand
        return False, best


def _subset(expected: Any, actual: Any, skip_keys=frozenset()) -> bool:
    """kuttl subset matching: every expected field must be present and
    equal; lists match index-wise as subsets."""
    if isinstance(expected, dict):
        if not isinstance(actual, dict):
            return False
        for k, v in expected.items():
            if k in skip_keys:
                continue
            if k not in actual:
                return False
            if not _subset(v, actual[k], skip_keys):
                return False
        return True
    if isinstance(expected, list):
        if not isinstance(actual, list) or len(actual) < len(expected):
            return False
        return all(_subset(e, a, skip_keys)
                   for e, a in zip(expected, actual))
    if isinstance(expected, (int, float)) and \
            isinstance(actual, (int, float)):
        return float(expected) == float(actual)
    return expected == actual


def _load_docs(path: str) -> List[dict]:
    with open(path) as f:
        return [d for d in yaml.safe_load_all(f) if d]


def run_suite(suite_dir: str) -> None:
    """Replay one kuttl test directory; raises KuttlFailure on
    divergence, Unsupported on unreplayable steps."""
    cluster = KuttlCluster()
    steps = []
    for name in os.listdir(suite_dir):
        m = _STEP_RE.match(name)
        if m:
            label = m.group(2)
            # kuttl runs the index's step first, then checks its assert
            # and error files
            if label == 'assert' or label.endswith('-assert'):
                rank = 1
            elif label in ('errors', 'error') or label.endswith('-errors'):
                rank = 2
            else:
                rank = 0
            steps.append((int(m.group(1)), rank, label, name))
    steps.sort()
    steps = [(num, label, name) for num, _rank, label, name in steps]
    for _num, label, name in steps:
        path = os.path.join(suite_dir, name)
        docs = _load_docs(path)
        if label == 'assert' or label.endswith('-assert'):
            for doc in docs:
                cluster.assert_doc(doc)
            continue
        if label in ('errors', 'error') or label.endswith('-errors'):
            for doc in docs:
                cluster.assert_absent(doc)
            continue
        for doc in docs:
            if doc.get('kind') == 'TestStep':
                _run_test_step(cluster, suite_dir, doc)
            else:
                cluster.apply_doc(doc)
        cluster.tick()


def _run_test_step(cluster: KuttlCluster, suite_dir: str,
                   step: dict) -> None:
    for entry in step.get('delete') or []:
        ref = entry.get('ref') or entry
        try:
            cluster.client.delete_resource(
                ref.get('apiVersion', ''), ref.get('kind', ''),
                ref.get('namespace', ''), ref.get('name', ''))
        except ApiError:
            pass
    for entry in step.get('apply') or []:
        if isinstance(entry, str):
            fname, should_fail = entry, False
        else:
            fname = entry.get('file', '')
            should_fail = bool(entry.get('shouldFail'))
        _apply_file(cluster, os.path.join(suite_dir, fname), should_fail)
    for cmd in step.get('commands') or []:
        _run_command(cluster, suite_dir, cmd)
    for fname in step.get('assert') or []:
        for doc in _load_docs(os.path.join(suite_dir, fname)):
            cluster.assert_doc(doc)
    for fname in step.get('error') or []:
        for doc in _load_docs(os.path.join(suite_dir, fname)):
            cluster.assert_absent(doc)


def _apply_file(cluster: KuttlCluster, path: str,
                should_fail: bool) -> None:
    denied: Optional[AdmissionDenied] = None
    for doc in _load_docs(path):
        try:
            cluster.apply_doc(doc)
        except AdmissionDenied as e:
            denied = e
    if should_fail and denied is None:
        raise KuttlFailure(
            f'{os.path.basename(path)} applied cleanly but the corpus '
            f'expects a denial')
    if not should_fail and denied is not None:
        raise KuttlFailure(
            f'{os.path.basename(path)} denied unexpectedly: {denied}')
    cluster.tick()


def _run_command(cluster: KuttlCluster, suite_dir: str,
                 cmd: dict) -> None:
    script = cmd.get('script', '') or cmd.get('command', '')
    m = _DENY_SCRIPT_RE.search(script)
    if m:
        _apply_file(cluster, os.path.join(suite_dir, m.group(1)),
                    should_fail=True)
        return
    m = _APPLY_CMD_RE.match(script.strip())
    if m:
        _apply_file(cluster, os.path.join(suite_dir, m.group(1)),
                    should_fail=False)
        return
    m = re.match(r'^kubectl\s+delete\s+-f\s+(\S+)', script.strip())
    if m:
        for fname in m.group(1).split(','):
            path = os.path.join(suite_dir, fname)
            if not os.path.exists(path):
                continue
            for doc in _load_docs(path):
                meta = doc.get('metadata') or {}
                try:
                    cluster.client.delete_resource(
                        doc.get('apiVersion', ''), doc.get('kind', ''),
                        meta.get('namespace', ''), meta.get('name', ''))
                except ApiError:
                    pass
        cluster.tick()
        return
    if re.fullmatch(r'sleep\s+\d+', script.strip()):
        cluster.tick()
        return
    raise Unsupported(f'command not replayable: {script[:120]!r}')
