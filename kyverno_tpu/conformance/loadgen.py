"""Synthetic cluster admission-traffic generator.

Models the traffic shape the heterogeneous-occupancy work targets
(ROADMAP): millions-of-users admission streams are NOT homogeneous —
they mix distinct userInfos (zipfian: a few controllers dominate, a
long tail of humans), many namespaces (zipfian too), CREATE/UPDATE
verbs, a small population of exception-holding tenants whose requests
ride the host engine loop, and bursty/trickling arrival.  The
generator is fully deterministic for a seed, so bench numbers and
tests reproduce.

Consumers:

* ``bench.py`` drives the admission-concurrency bench with
  :meth:`SyntheticCluster.review_bytes` and ratchets mean batch
  occupancy under this traffic (``HET_OCCUPANCY_FLOOR``);
* tests use small instances to pin batched-vs-sync bit-identity under
  mixed admission tuples;
* the chaos drills (``bench.py --admission-chaos``,
  ``tests/test_faults.py``) mark a deterministic slice of rows as
  *poison* — their ``chaos`` label is what a marker-armed
  ``KTPU_FAULTS`` clause keys on — and pair the traffic with a fault
  schedule, so a run under injected failures replays against its own
  fault-free oracle;
* the policy-churn bench (``bench.py --policy-churn``) and churn
  tests share :meth:`SyntheticCluster.churn_schedule` /
  :func:`apply_churn` — deterministic mid-burst policy edit/add/delete
  events at fixed request ticks.

Layered beside the kuttl/scenario harness (this package): scenarios
replay *recorded* cases, the generator synthesizes *load*.
"""

from __future__ import annotations

import bisect
import copy
import json
from dataclasses import dataclass
from typing import Dict, Iterator, List, Optional, Tuple


#: label value a poison row carries under ``metadata.labels.chaos`` —
#: the key the fault injector's ``marker=`` clauses match on
#: (``kyverno_tpu.faults.MARKER_LABEL``); inert in a fault-free run
POISON_MARKER = 'poison'


@dataclass(frozen=True)
class ChurnEvent:
    """One scheduled policy change mid-traffic: at request ``tick``,
    apply ``action`` (edit | add | delete) to ``policy_index`` of the
    live policy set.  ``seed`` disambiguates the edit content so two
    events against the same policy produce distinct fingerprints."""
    tick: int
    action: str
    policy_index: int
    seed: int

    def marker(self) -> str:
        """The string the event's edit stamps into the policy — what a
        bench polls for in responses to observe enforcement."""
        return f'[churn-{self.seed}]'

    def to_dict(self) -> Dict:
        return {'tick': self.tick, 'action': self.action,
                'policy_index': self.policy_index, 'seed': self.seed,
                'marker': self.marker()}


def apply_churn(raw_policies: List[Dict], event: ChurnEvent
                ) -> List[Dict]:
    """Apply one :class:`ChurnEvent` to a list of raw policy documents,
    returning a NEW list with deep-copied changed entries (the inputs
    are never mutated — callers keep the pre-churn set as the oracle).

    * ``edit`` appends the event marker to the target's first validate
      message: a semantic change (new compile fingerprint, new verdict
      text) that leaves the policy's slot vocabulary — and therefore
      its partition assignment — intact.
    * ``add`` clones the target under a ``-churn<seed>`` name.
    * ``delete`` removes the target.
    """
    idx = event.policy_index % max(1, len(raw_policies))
    out = list(raw_policies)
    if event.action == 'delete':
        del out[idx]
        return out
    doc = copy.deepcopy(raw_policies[idx])
    rules = ((doc.get('spec') or {}).get('rules')) or []
    for rule in rules:
        validate = rule.get('validate')
        if isinstance(validate, dict) and 'message' in validate:
            validate['message'] = \
                f"{validate['message']} {event.marker()}"
            break
    if event.action == 'add':
        meta = doc.setdefault('metadata', {})
        meta['name'] = f"{meta.get('name', 'pol')}-churn{event.seed}"
        out.append(doc)
    else:  # edit
        out[idx] = doc
    return out


def _zipf_cum(n: int, s: float) -> List[float]:
    """Cumulative zipf(s) weights over ranks 1..n (rank 1 hottest)."""
    total = 0.0
    out: List[float] = []
    for k in range(1, n + 1):
        total += 1.0 / (k ** s)
        out.append(total)
    return out


class SyntheticCluster:
    """Deterministic admission-traffic source for one synthetic cluster.

    ``request(i)`` is a pure function of ``(seed, i)``: the i-th
    request's user, namespace, verb, and pod shape never depend on how
    many requests were drawn before it, so threads can partition the
    index space freely and still replay identically.
    """

    def __init__(self, seed: int = 0, users: int = 200,
                 namespaces: int = 32, teams: int = 12,
                 zipf_s: float = 1.1, update_ratio: float = 0.25,
                 delete_ratio: float = 0.0,
                 exception_tenant_ratio: float = 0.05,
                 compliant_ratio: float = 0.5,
                 poison_ratio: float = 0.0):
        import random
        self.seed = seed
        self._base = random.Random(seed)
        self.users = [f'user-{i}' for i in range(max(1, users))]
        self.namespaces = [f'ns-{i}' for i in range(max(1, namespaces))]
        self.teams = max(1, teams)
        self.update_ratio = update_ratio
        self.delete_ratio = delete_ratio
        self.compliant_ratio = compliant_ratio
        self._user_cum = _zipf_cum(len(self.users), zipf_s)
        self._ns_cum = _zipf_cum(len(self.namespaces), zipf_s)
        # a deterministic zipf-tail slice of tenants holds policy
        # exceptions; their requests leave the batched device path
        step = max(1, int(round(1.0 / exception_tenant_ratio))) \
            if exception_tenant_ratio > 0 else 0
        self.exception_users = frozenset(
            u for i, u in enumerate(self.users)
            if step and i % step == step - 1)
        # poison rows: every poison_step-th request carries the chaos
        # marker label AND is forced onto a non-exception tenant with a
        # device-served verb, so every poison row is guaranteed to ride
        # the batched device path — the quarantine ratchet can then
        # demand shed(poison_row) == the exact injected poison count
        self._poison_step = max(1, int(round(1.0 / poison_ratio))) \
            if poison_ratio > 0 else 0
        self._device_users = [u for u in self.users
                              if u not in self.exception_users] \
            or list(self.users)

    # -- per-index draws ---------------------------------------------------

    def _rng(self, i: int):
        import random
        return random.Random((self.seed << 20) ^ i)

    @staticmethod
    def _pick(rng, items: List[str], cum: List[float]) -> str:
        r = rng.random() * cum[-1]
        return items[min(bisect.bisect_left(cum, r), len(items) - 1)]

    def user_info(self, user: str) -> Dict:
        idx = int(user.rsplit('-', 1)[1])
        groups = ['system:authenticated', f'team-{idx % self.teams}']
        if idx % 7 == 0:
            groups.append('system:masters')
        return {'username': user, 'groups': groups}

    def is_exception_tenant(self, username: str) -> bool:
        return username in self.exception_users

    # -- poison rows (chaos drills) ----------------------------------------

    def is_poison(self, i: int) -> bool:
        """Whether the i-th request is a marked poison row (pure in
        ``(poison_ratio, i)`` — callers compute exact expectations)."""
        step = self._poison_step
        return bool(step) and i % step == step - 1

    def poison_count(self, count: int, start: int = 0) -> int:
        """Poison rows among requests ``start .. start+count-1``."""
        return sum(1 for k in range(count) if self.is_poison(start + k))

    def fault_spec(self, error: str = 'RuntimeError') -> str:
        """``KTPU_FAULTS`` clause arming the poison marker: any batched
        device dispatch carrying a marked row raises ``error`` — the
        batcher's bisection then has a row-deterministic failure to
        isolate (the clause re-fires on every sub-batch that still
        contains the poison row, and never on one that does not)."""
        return f'site=batcher_dispatch,marker={POISON_MARKER}' \
               f',error={error}'

    def pod(self, ns: str, name: str, user: str,
            compliant: bool) -> Dict:
        idx = int(user.rsplit('-', 1)[1])
        labels = {'app': f'svc-{idx % 17}'}
        if compliant:
            labels['team'] = f'team-{idx % self.teams}'
        containers = [{'name': f'c{k}', 'image': f'registry/app:{idx % 5}'}
                      for k in range(1 + idx % 3)]
        return {'apiVersion': 'v1', 'kind': 'Pod',
                'metadata': {'name': name, 'namespace': ns,
                             'labels': labels},
                'spec': {'containers': containers}}

    def request(self, i: int) -> Dict:
        """The i-th AdmissionRequest dict (uid, operation, object,
        oldObject for UPDATE, userInfo)."""
        rng = self._rng(i)
        user = self._pick(rng, self.users, self._user_cum)
        ns = self._pick(rng, self.namespaces, self._ns_cum)
        compliant = rng.random() < self.compliant_ratio
        poison = self.is_poison(i)
        if poison:
            # device-path guarantee: never an exception tenant (whose
            # requests bypass the batcher entirely)
            user = self._device_users[i % len(self._device_users)]
        name = f'pod-{i}'
        doc = self.pod(ns, name, user, compliant)
        if poison:
            doc['metadata']['labels']['chaos'] = POISON_MARKER
        verb_draw = rng.random()
        if poison or verb_draw >= self.delete_ratio + self.update_ratio:
            operation = 'CREATE'  # poison rows keep a device verb
        elif verb_draw < self.delete_ratio:
            operation = 'DELETE'
        else:
            operation = 'UPDATE'
        req = {
            'uid': f'load-{self.seed}-{i}',
            'operation': operation,
            'kind': {'group': '', 'version': 'v1', 'kind': 'Pod'},
            'namespace': ns, 'name': name,
            'userInfo': self.user_info(user),
        }
        if operation == 'DELETE':
            req['oldObject'] = doc
        else:
            req['object'] = doc
            if operation == 'UPDATE':
                old = json.loads(json.dumps(doc))
                old['metadata']['labels'].pop('team', None)
                old['metadata']['labels']['rev'] = 'old'
                req['oldObject'] = old
        return req

    def review(self, i: int) -> Dict:
        return {'apiVersion': 'admission.k8s.io/v1',
                'kind': 'AdmissionReview', 'request': self.request(i)}

    def review_bytes(self, i: int) -> bytes:
        return json.dumps(self.review(i)).encode('utf-8')

    # -- arrival schedules -------------------------------------------------

    def arrivals(self, count: int, pattern: str = 'burst',
                 burst: int = 16, gap_ms: float = 2.0,
                 rate_per_s: float = 500.0, start: int = 0
                 ) -> Iterator[Tuple[float, bytes]]:
        """Yield ``(delay_before_send_s, review_bytes)`` pairs.

        ``burst`` releases ``burst`` back-to-back requests then pauses
        ``gap_ms``; ``trickle`` spaces requests exponentially around
        ``rate_per_s``; ``steady`` is fixed spacing.  Deterministic."""
        rng = self._rng(-1 - start)
        for k in range(count):
            i = start + k
            if pattern == 'burst':
                delay = 0.0 if (k % max(1, burst)) else (
                    0.0 if k == 0 else gap_ms / 1000.0)
            elif pattern == 'trickle':
                delay = rng.expovariate(rate_per_s)
            else:  # steady
                delay = 1.0 / rate_per_s
            yield delay, self.review_bytes(i)

    # -- mid-burst policy churn --------------------------------------------

    def churn_schedule(self, count: int, n_policies: int,
                       events: int = 1, start_frac: float = 0.25,
                       end_frac: float = 0.75,
                       actions: Tuple[str, ...] = ('edit',)
                       ) -> List['ChurnEvent']:
        """Deterministic mid-burst policy-churn schedule: ``events``
        policy changes at fixed request ticks, evenly spread across
        ``[start_frac, end_frac)`` of a ``count``-request run.  Pure in
        ``(seed, count, n_policies, events, ...)`` so the churn bench
        and the chaos drills fire the exact same edits at the exact
        same ticks — a churn run replays against its own oracle.
        Actions cycle through ``actions``; the targeted policy index is
        a seed-keyed draw so different seeds churn different policies.
        """
        events = max(1, events)
        span = max(0.0, end_frac - start_frac)
        out: List[ChurnEvent] = []
        for k in range(events):
            tick = int(count * (start_frac + span * k / events))
            rng = self._rng(-1000 - k)
            out.append(ChurnEvent(
                tick=min(max(tick, 0), max(count - 1, 0)),
                action=actions[k % len(actions)],
                policy_index=rng.randrange(max(1, n_policies)),
                seed=(self.seed << 8) ^ k))
        return out

    # -- exception-holding tenants ----------------------------------------

    def exception_docs(self, policy_name: str = 'loadgen-exception',
                       rule_names: Optional[List[str]] = None
                       ) -> List[Dict]:
        """PolicyException documents for the exception-tenant
        population.  With the default placeholder ``policy_name`` they
        match no real policy: requests still pay the exception-bearing
        host path (`pctx.exceptions` non-empty disables the device fast
        path) without changing any verdict — the load shape, not the
        outcome."""
        return [{
            'apiVersion': 'kyverno.io/v2beta1',
            'kind': 'PolicyException',
            'metadata': {'name': f'exc-{u}', 'namespace': 'kyverno'},
            'spec': {'exceptions': [{
                'policyName': policy_name,
                'ruleNames': rule_names or ['*'],
            }]},
        } for u in sorted(self.exception_users)]
