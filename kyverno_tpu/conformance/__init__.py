"""Conformance harnesses replaying the reference's test corpora."""
