"""YAML scenario replay (reference: pkg/testrunner/scenario.go:30-50 +
test/scenarios corpus).

Each scenario file holds test cases (``---``-separated) naming a policy
file, a resource file, and the expected mutation / validation /
generation outcomes.  The runner mirrors runTestCase (scenario.go:136):
mutate → compare patched resource + rule responses, validate the
patched resource → compare, and for Namespace resources run the
generate path against a fake cluster and check the generated resources
exist.
"""

from __future__ import annotations

import os
from typing import Any, List, Optional

import yaml

REF_ROOT = '/root/reference'


class ScenarioFailure(AssertionError):
    pass


def _load_docs(rel: str) -> List[dict]:
    path = os.path.join(REF_ROOT, rel.lstrip('/'))
    with open(path) as f:
        return [d for d in yaml.safe_load_all(f) if d]


def _normalize(node: Any) -> Any:
    """Drop Go-marshalling artifacts (``creationTimestamp: null`` etc.)
    that the corpus' expected files carry from struct serialization."""
    if isinstance(node, dict):
        out = {}
        for k, v in node.items():
            if v is None and k == 'creationTimestamp':
                continue
            nv = _normalize(v)
            if nv == {}:
                # Go empty-struct artifacts (strategy: {}, status: {});
                # dropped from BOTH sides, so equality is preserved
                continue
            out[k] = nv
        return out
    if isinstance(node, list):
        return [_normalize(v) for v in node]
    return node


def _strip_empty(node: Any) -> Any:
    """Stand-in for the reference loader's typed-scheme round trip
    (scenario.go loadPolicyResource → runtime scheme): k8s structs drop
    omitempty fields, so empty strings/maps in the input YAML vanish
    before the engine sees the resource."""
    if isinstance(node, dict):
        out = {}
        for k, v in node.items():
            sv = _strip_empty(v)
            if sv == '' or sv is None:
                # omitempty strings vanish; empty maps stay (a pointer
                # struct like emptyDir: {} survives the round trip)
                continue
            out[k] = sv
        return out
    if isinstance(node, list):
        return [_strip_empty(v) for v in node]
    return node


def _compare_rules(actual, expected_rules: List[dict], stage: str) -> None:
    """reference: scenario.go:261 — count equality, then in-order
    name/type/status/message comparison."""
    if len(actual) != len(expected_rules):
        raise ScenarioFailure(
            f'{stage}: rule count mismatch: got '
            f'{[(r.name, r.status) for r in actual]}, expected '
            f'{[(r.get("name"), r.get("status")) for r in expected_rules]}')
    for got, want in zip(actual, expected_rules):
        if want.get('name') and got.name != want['name']:
            raise ScenarioFailure(
                f'{stage}: rule name {got.name!r} != {want["name"]!r}')
        if want.get('type') and got.rule_type != want['type']:
            raise ScenarioFailure(
                f'{stage}: rule type {got.rule_type!r} != {want["type"]!r}')
        if want.get('status') and \
                str(got.status).lower() != str(want['status']).lower():
            raise ScenarioFailure(
                f'{stage}: rule {got.name} status {got.status!r} != '
                f'{want["status"]!r} ({got.message})')
        if want.get('message') and got.message != want['message']:
            raise ScenarioFailure(
                f'{stage}: rule {got.name} message {got.message!r} != '
                f'{want["message"]!r}')


def _compare_header(response, expected: dict, stage: str) -> None:
    pr = response.policy_response
    pol = expected.get('policy') or {}
    if pol.get('name') and pr.policy_name != pol['name']:
        raise ScenarioFailure(
            f'{stage}: policy name {pr.policy_name!r} != {pol["name"]!r}')
    res = expected.get('resource') or {}
    for field, got in (('kind', pr.resource_kind),
                       ('namespace', pr.resource_namespace),
                       ('name', pr.resource_name)):
        want = res.get(field)
        if want is not None and got != want:
            raise ScenarioFailure(
                f'{stage}: resource {field} {got!r} != {want!r}')


def run_scenario(rel_path: str) -> int:
    """Replay one scenario file; returns the number of test cases run."""
    from ..api.policy import Policy
    from ..engine.api import PolicyContext
    from ..engine.engine import Engine

    cases = _load_docs(rel_path)
    n = 0
    for tc in cases:
        inp = tc.get('input') or {}
        expected = tc.get('expected') or {}
        policy_doc = _load_docs(inp['policy'])[0]
        resource = _strip_empty(_load_docs(inp['resource'])[0])
        policy = Policy(policy_doc)
        engine = Engine()

        # --- mutation (scenario.go:155) ---
        pctx = PolicyContext(policy, new_resource=resource)
        er = engine.mutate(pctx)
        expected_mutation = expected.get('mutation') or {}
        patched_file = expected_mutation.get('patchedresource', '')
        if patched_file:
            want = _load_docs(patched_file)[0]
            if _normalize(er.patched_resource) != _normalize(want):
                raise ScenarioFailure(
                    f'patched resource mismatch:\n got: '
                    f'{er.patched_resource}\nwant: {want}')
        if expected_mutation.get('policyresponse'):
            _compare_header(er, expected_mutation['policyresponse'],
                            'mutation')
            _compare_rules(er.policy_response.rules,
                           expected_mutation['policyresponse'].get(
                               'rules') or [], 'mutation')
        if er.policy_response.rules and er.patched_resource is not None:
            resource = er.patched_resource

        # --- validation (scenario.go:167) ---
        pctx = PolicyContext(policy, new_resource=resource)
        er = engine.validate(pctx)
        expected_validation = (expected.get('validation') or {})
        if expected_validation.get('policyresponse'):
            _compare_header(er, expected_validation['policyresponse'],
                            'validation')
            _compare_rules(er.policy_response.rules,
                           expected_validation['policyresponse'].get(
                               'rules') or [], 'validation')

        # --- generation (scenario.go:177, Namespace triggers only) ---
        expected_generation = expected.get('generation') or {}
        if resource.get('kind') == 'Namespace' and expected_generation:
            from ..background.update_request_controller import \
                UpdateRequestController
            from ..background.updaterequest import UpdateRequestGenerator
            from ..dclient.client import FakeClient
            client = FakeClient()
            for extra_rel in inp.get('loadresources') or []:
                for doc in _load_docs(extra_rel):
                    meta = doc.get('metadata') or {}
                    client.create_resource(doc.get('apiVersion', ''),
                                           doc.get('kind', ''),
                                           meta.get('namespace', ''), doc)
            client.create_resource('v1', 'Namespace', '', resource)
            ns_name = (resource.get('metadata') or {}).get('name', '')
            gen = UpdateRequestGenerator(client)
            gen.apply({
                'type': 'generate', 'policy': policy.name,
                'resource': {'apiVersion': 'v1', 'kind': 'Namespace',
                             'name': ns_name, 'namespace': ''},
                'requestType': 'generate',
            })
            ctrl = UpdateRequestController(
                client, engine, policy_getter={policy.name: policy}.get)
            ctrl.process_pending()
            for spec in expected_generation.get('generatedResources') or []:
                try:
                    client.get_resource(spec.get('apiVersion', ''),
                                        spec.get('kind', ''), ns_name,
                                        spec.get('name', ''))
                except Exception:
                    raise ScenarioFailure(
                        f'generated resource {spec.get("kind")}/'
                        f'{ns_name}/{spec.get("name")} not found')
        n += 1
    return n
