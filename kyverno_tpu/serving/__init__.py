"""Admission micro-batching scheduler.

Sits between the webhook handlers and the compiled
:class:`~kyverno_tpu.compiler.scan.BatchScanner`: concurrent CREATE-path
validate requests for the same policy set coalesce into one shared
device dispatch instead of each paying a batch-of-one scan (the
continuous-batching pattern of TPU serving stacks, applied to policy
evaluation).

* :mod:`.queue` — bounded request queue with per-request futures;
* :mod:`.batcher` — the coalescing loop (flush on the
  ``KTPU_BATCH_WINDOW_MS`` deadline or at ``KTPU_BATCH_MAX`` occupancy;
  batches are ragged — padded to a canonical capacity with the tail
  masked in-graph — so a flush at any occupancy reuses a compiled
  executable);
* :mod:`.shed` — the degradation policy: queue-full, deadline-blown, or
  scan-failed requests shed to the host engine loop (identical
  verdicts, never a 500).

Selected per-handler via ``KTPU_SERVING=batch|sync`` (default sync).
Bit-identity with the sync path is the contract, pinned by
``tests/test_serving.py``.
"""

from .batcher import AdmissionBatcher
from .queue import QueueFull, Stopped, Ticket

__all__ = ['AdmissionBatcher', 'QueueFull', 'Stopped', 'Ticket']
