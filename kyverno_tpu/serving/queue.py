"""Bounded admission request queue with per-request futures.

Each webhook thread submits a :class:`Ticket` (its scan inputs plus a
future) and blocks on :meth:`Ticket.wait`; the batcher thread claims
runs of same-key tickets and resolves their futures with the rows of
one shared device dispatch.

Ownership of a ticket is decided by a compare-and-set on its state
under the ticket lock: the batcher moves PENDING → CLAIMED when it
takes a batch, the waiting webhook thread moves PENDING → SHED when its
deadline blows.  Exactly one side wins, so a request is either answered
by the batch it rode or re-run on the host engine loop — never both,
never neither.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, List, Optional

from . import shed as shed_policy

#: ticket states (see module docstring for the ownership protocol)
PENDING = 'pending'
CLAIMED = 'claimed'
SHED = 'shed'
DONE = 'done'


class QueueFull(Exception):
    """The bounded admission queue is at capacity (shed to host)."""


class Stopped(Exception):
    """The batcher is stopped; no new tickets (shed to host)."""


class Ticket:
    """One queued admission scan: inputs + the future its webhook
    thread blocks on.

    ``key`` groups coalescible requests — the compiled scanner's
    monotonic serial alone for scanners that consume per-row admission
    tuples (mixed users/roles/namespaces/verbs share one dispatch,
    bit-identical to each request's own sync scan because the scanner
    threads each row's tuple through the match pipeline), or serial +
    the canonical admission tuple on the residual path for scanners
    without per-row support.  ``on_shed`` is the batcher's shed ledger;
    the deadline shed is recorded here because the waiting thread, not
    the batcher, makes that decision.
    """

    __slots__ = ('key', 'resource', 'context', 'pctx', 'admission',
                 'scanner', 'policies', 'span', 'on_shed', 'enqueued_at',
                 'state', 'responses', 'shed_reason', 'prov',
                 'old_resource', '_lock', '_event')

    def __init__(self, key, resource: dict, context: Optional[dict],
                 pctx, admission: tuple, scanner, policies,
                 span=None, on_shed=None,
                 old_resource: Optional[dict] = None):
        self.key = key
        self.resource = resource
        self.context = context
        #: UPDATE-verb rows ride their oldObject along for the scanner's
        #: old-match retry; None on CREATE / mutate tickets
        self.old_resource = old_resource
        self.pctx = pctx
        self.admission = admission
        self.scanner = scanner
        self.policies = policies
        self.span = span
        self.on_shed = on_shed
        self.enqueued_at = time.monotonic()
        self.state = PENDING
        self.responses: Optional[list] = None
        self.shed_reason: Optional[str] = None
        #: decision-provenance fields the batcher fills at dispatch
        #: (batch id, occupancy, queue wait, amortized device share);
        #: the waiting webhook thread folds them into its
        #: DecisionRecord after resolve
        self.prov: Optional[dict] = None
        self._lock = threading.Lock()
        self._event = threading.Event()

    # -- batcher side -----------------------------------------------------

    def claim(self) -> bool:
        """PENDING → CLAIMED; False when the waiter already shed."""
        with self._lock:
            if self.state == PENDING:
                self.state = CLAIMED
                return True
            return False

    def resolve(self, responses: list) -> None:
        with self._lock:
            self.state = DONE
            self.responses = responses
        self._event.set()

    def shed(self, reason: str) -> None:
        """Terminal shed by the batcher (scan failure / shutdown)."""
        with self._lock:
            self.state = SHED
            self.shed_reason = reason
        self._event.set()

    # -- webhook-thread side ----------------------------------------------

    def _try_shed(self, reason: str) -> bool:
        with self._lock:
            if self.state == PENDING:
                self.state = SHED
                self.shed_reason = reason
                return True
            return False

    def wait(self, shed_after_s: float,
             claimed_timeout_s: float = 60.0) -> Optional[list]:
        """Block for the batched responses.

        Returns the per-policy response list, or None when the request
        shed to the host engine loop (``shed_reason`` says why).  A
        ticket already CLAIMED at the deadline has a dispatch in flight
        — the result is seconds away at worst, so waiting beats
        double-running the scan; ``claimed_timeout_s`` only bounds a
        wedged dispatch.
        """
        if not self._event.wait(shed_after_s):
            if self._try_shed(shed_policy.REASON_DEADLINE):
                if self.on_shed is not None:
                    self.on_shed(shed_policy.REASON_DEADLINE)
                return None
            self._event.wait(claimed_timeout_s)
        with self._lock:
            return self.responses if self.state == DONE else None


class RequestQueue:
    """Bounded FIFO of tickets with flush-condition waits.

    The deque holds tickets in arrival order; non-PENDING entries
    (deadline-shed by their waiters) are pruned during scans.  All
    waits ride one condition variable, notified on put and stop, so
    the batcher reacts to occupancy without polling.
    """

    def __init__(self, capacity: int):
        self.capacity = capacity
        self._items: deque = deque()
        self._cond = threading.Condition()
        self._stopping = False

    def put(self, ticket: Ticket) -> None:
        with self._cond:
            if self._stopping:
                raise Stopped()
            if len(self._items) >= self.capacity:
                # only live tickets count against capacity
                self._items = deque(
                    t for t in self._items if t.state == PENDING)
                if len(self._items) >= self.capacity:
                    raise QueueFull()
            self._items.append(ticket)
            self._cond.notify_all()

    def wait_for_work(self) -> Optional[Ticket]:
        """Block until a PENDING ticket exists; None once stopping with
        an empty queue (the drain is complete)."""
        with self._cond:
            while True:
                for t in self._items:
                    if t.state == PENDING:
                        return t
                if self._stopping:
                    return None
                self._cond.wait()

    def wait_flush(self, key: Any, max_batch: int,
                   deadline: float) -> None:
        """Block until ``key`` reaches ``max_batch`` pending tickets,
        the deadline passes, or the queue is stopping (drain flushes
        immediately)."""
        with self._cond:
            while not self._stopping:
                n = sum(1 for t in self._items
                        if t.state == PENDING and t.key == key)
                if n >= max_batch:
                    return
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return
                self._cond.wait(remaining)

    def take_batch(self, key: Any, max_batch: int) -> List[Ticket]:
        """Claim and remove up to ``max_batch`` PENDING tickets of
        ``key`` (FIFO); prunes dead tickets encountered on the way."""
        with self._cond:
            batch: List[Ticket] = []
            keep: deque = deque()
            for t in self._items:
                if t.state != PENDING:
                    continue
                if t.key == key and len(batch) < max_batch and t.claim():
                    batch.append(t)
                else:
                    keep.append(t)
            self._items = keep
            self._cond.notify_all()
            return batch

    def take_all(self) -> List[Ticket]:
        """Claim and remove every pending ticket (no-drain shutdown)."""
        with self._cond:
            batch = [t for t in self._items if t.claim()]
            self._items.clear()
            self._cond.notify_all()
            return batch

    def depth(self) -> int:
        with self._cond:
            return sum(1 for t in self._items if t.state == PENDING)

    def stop(self) -> None:
        with self._cond:
            self._stopping = True
            self._cond.notify_all()
