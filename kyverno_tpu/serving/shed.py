"""Degradation policy for the admission micro-batcher.

A request leaves the batched fast path for exactly one of the reasons
below; every shed is counted (locally for ``stats()`` consumers and on
``kyverno_tpu_admission_shed_total{reason}`` when a metrics registry is
configured).  Shedding is never an error to the API server: the webhook
thread that owns the request runs the host engine loop instead —
identical verdicts, honoring the webhook failurePolicy semantics the
sync path already provides.

Accounting discipline: each reason is recorded exactly once, at the
site that makes the shed decision — ``queue_full`` / ``shutdown`` by
the submitting handler (the ticket never entered the queue or the
batcher is stopping without drain), ``deadline`` by the waiting webhook
thread when its compare-and-set from PENDING wins, ``poison_row`` /
``stage_retry_exhausted`` by the batcher's quarantine per ISOLATED row
(never per batch), ``scan_error`` by the batcher only for a group
still failing wholesale at the quarantine depth bound, and
``breaker_open`` by the validating handler when the policy set's
circuit breaker quarantined it to the host loop before a ticket could
even be submitted.
"""

from __future__ import annotations

import threading
from typing import Dict

from ..observability.metrics import global_registry

#: the bounded queue was at capacity when the request arrived
REASON_QUEUE_FULL = 'queue_full'
#: the request's future did not resolve within the effective deadline
#: (KTPU_SHED_DEADLINE_MS, tightened by the review's own timeoutSeconds)
REASON_DEADLINE = 'deadline'
#: a quarantined group still failed wholesale at the bisection depth
#: bound — un-isolated riders shed together
REASON_SCAN_ERROR = 'scan_error'
#: the batcher is stopped (post-drain submits)
REASON_SHUTDOWN = 'shutdown'
#: quarantine bisection isolated THIS row as the one poisoning its
#: shared dispatch; healthy riders stayed on device
REASON_POISON_ROW = 'poison_row'
#: the policy set's circuit breaker is open (or this caller lost the
#: half-open probe slot): host loop without entering the queue
REASON_BREAKER_OPEN = 'breaker_open'
#: the isolated row's dispatch died on a scan-pipeline stage that
#: burned its whole KTPU_STAGE_RETRIES budget
REASON_STAGE_RETRY_EXHAUSTED = 'stage_retry_exhausted'

REASONS = (REASON_QUEUE_FULL, REASON_DEADLINE, REASON_SCAN_ERROR,
           REASON_SHUTDOWN, REASON_POISON_ROW, REASON_BREAKER_OPEN,
           REASON_STAGE_RETRY_EXHAUSTED)

ADMISSION_SHED = 'kyverno_tpu_admission_shed_total'


class ShedLedger:
    """Thread-safe per-reason shed counters.

    Mirrors every count onto the process metrics registry when one is
    configured; keeps local totals either way so benchmarks and tests
    can read shed traffic without wiring a registry.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._counts: Dict[str, int] = {}

    def record(self, reason: str) -> None:
        with self._lock:
            self._counts[reason] = self._counts.get(reason, 0) + 1
        registry = global_registry()
        if registry is not None:
            registry.inc(ADMISSION_SHED, reason=reason)

    def counts(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._counts)

    def total(self) -> int:
        with self._lock:
            return sum(self._counts.values())

    def reset(self) -> None:
        with self._lock:
            self._counts.clear()
