"""The micro-batching loop: coalesce same-policy-set admission scans.

One daemon thread watches the bounded queue.  It picks the oldest
pending ticket, waits until that ticket's flush window expires
(``KTPU_BATCH_WINDOW_MS``, default ~2ms) or its key reaches
``KTPU_BATCH_MAX`` occupancy (default: the small canonical batch
capacity, ``compiler/shapes.py``), then dispatches all claimed tickets
of that key as ONE ``scanner.scan`` call and resolves their futures
row by row.

The coalescing key is the SCANNER ALONE (its monotonic serial): the
scanner threads each rider's admission tuple through the compiled
pipeline as per-row lanes (``compiler/admission.py``), so mixed-user,
mixed-role, mixed-verb bursts — the shape of real cluster traffic —
share one dispatch instead of degenerating to batch-of-one.  Scanners
without per-row admission support (``supports_row_admissions`` unset)
ride a residual key that appends the canonical admission tuple; every
such ticket is recorded on the coverage ledger
(``admission_unencodable``, path ``serving``) so the serialization is
never silent.

Batches are ragged: the scanner pads every dispatch to a canonical
capacity and the evaluator masks the tail rows in-graph, so a flush at
ANY occupancy reuses an already-compiled executable — there is no
bucket floor to align with, and ``KTPU_BATCH_MAX`` is purely a
latency/amortization trade (values above the small capacity make
batches pad to the next canonical capacity).

Dispatches are serialized on the batcher thread: ``BatchScanner.scan``
keeps per-scan state on the scanner instance, and one consumer at a
time is what makes the shared scanner safe by construction.  While a
dispatch runs, new arrivals accumulate in the queue — that accumulation
is where occupancy (and chip utilization) comes from.

Failure semantics: a dispatch that raises enters POISON QUARANTINE —
the batcher bisects the batch (bounded depth) and re-dispatches the
halves, so a single poison row no longer sheds N healthy riders: the
healthy riders resolve on device from their sub-dispatches, each
isolated poison row sheds to the host loop under reason ``poison_row``
(``stage_retry_exhausted`` when the failure was a pipeline stage that
exhausted its retry budget), and only a group still failing at the
depth bound sheds wholesale under ``scan_error``.  A singleton failure
gets one solo re-dispatch first, so transient device errors recover
with no shed at all.

The owning handler's per-policy-set circuit breaker hears at most ONE
verdict per original dispatch, and the verdict distinguishes
row-attributed evidence from infrastructure evidence: ``on_success``
when quarantine resolved any rider on device (the backend is healthy —
the failure was row-local); ``on_failure`` when nothing survived AND
the failure looks systemic — a wholesale shed (depth-bound group or a
retry-exhausted pipeline stage) or ``ALL_FAILED_BREAKER_AFTER``
consecutive all-failed dispatches of the same key.  A dispatch whose
only casualties were isolated poison rows (each failed twice solo —
row-attributed by construction) is breaker-NEUTRAL: an unlucky
all-poison batch must not quarantine the whole policy set to the host
loop, while a genuinely broken backend still trips the breaker via the
consecutive counter within a bounded number of dispatches.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Callable, Dict, Optional

from .. import faults
from ..observability import coverage, tracing
from ..observability.metrics import MetricsRegistry, global_registry
from . import shed as shed_policy
from .queue import RequestQueue, Ticket

QUEUE_DEPTH = 'kyverno_tpu_admission_queue_depth'
BATCH_OCCUPANCY = 'kyverno_tpu_admission_batch_occupancy'
HETERO_OCCUPANCY = 'kyverno_tpu_admission_hetero_occupancy'
QUEUE_WAIT = 'kyverno_tpu_admission_queue_wait_seconds'

#: occupancy counts requests per dispatch — power-of-two buckets up to
#: twice the default KTPU_BATCH_MAX
OCCUPANCY_BUCKETS = (1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0)
#: queue waits live at the flush window (~ms), far below the default
#: latency buckets' useful resolution
WAIT_BUCKETS = (0.0005, 0.001, 0.002, 0.005, 0.01, 0.025, 0.05, 0.1,
                0.25, 0.5, 1.0)

#: poison-quarantine bisection bound: KTPU_BATCH_MAX stays well under
#: 2**8, so singleton isolation always completes within the bound,
#: while a pathological failure storm stays O(depth * batch) dispatches
QUARANTINE_MAX_DEPTH = 8

#: consecutive all-failed dispatches of one key before poison-only
#: evidence escalates to a breaker failure anyway: poison sheds are
#: row-attributed (each row failed twice in isolation), so a single
#: all-poison batch is breaker-neutral — but a backend that fails
#: EVERY row of EVERY dispatch looks identical row-by-row, and this
#: bound is how long the batcher entertains the row-local theory
ALL_FAILED_BREAKER_AFTER = 3


def _canon(v):
    """Order-canonical view of one admission-tuple element: dict keys
    sort via json, and list/tuple values sort by their JSON form —
    roles/groups are membership sets for match semantics, so two
    requests differing only in list order must produce ONE key."""
    if isinstance(v, dict):
        return {str(k): _canon(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        items = [_canon(x) for x in v]
        try:
            return sorted(items, key=lambda x: json.dumps(
                x, sort_keys=True, default=str))
        except Exception:  # ktpu: noqa[KTPU304] -- key
            return items   # canonicalization, not a serving error:
            # mixed-type lists that refuse a total order keep their
            # arrival order (a worse coalescing key, never a failure)
    return v


def admission_key(admission: tuple) -> str:
    """Deterministic canonical string of the (admission_info,
    exclude_group_roles, namespace_labels, operation) tuple — JSON with
    sorted keys AND sorted scalar lists, positional at the top level.
    Used only by the residual fallback path (scanners without per-row
    admission lanes): such requests may only share a dispatch when this
    matches, and every use is recorded on the coverage ledger."""
    parts = [_canon(x) for x in admission] \
        if isinstance(admission, (list, tuple)) else _canon(admission)
    return json.dumps(parts, sort_keys=True, default=str,
                      separators=(',', ':'))


class AdmissionBatcher:
    """Queue + coalescing thread + shed accounting.

    ``on_success(policies)`` / ``on_failure(policies, error)`` hook the
    owning handler's circuit breaker, so a broken backend trips it from
    batched traffic exactly as it would from sync traffic.
    """

    def __init__(self,
                 window_ms: Optional[float] = None,
                 max_batch: Optional[int] = None,
                 queue_cap: Optional[int] = None,
                 shed_deadline_ms: Optional[float] = None,
                 on_success: Optional[Callable] = None,
                 on_failure: Optional[Callable] = None):
        if window_ms is None:
            window_ms = float(os.environ.get('KTPU_BATCH_WINDOW_MS', '2'))
        if max_batch is None:
            raw_max = os.environ.get('KTPU_BATCH_MAX', '')
            if raw_max.strip():
                max_batch = int(raw_max)
            else:
                # default: fill the small canonical capacity exactly —
                # any occupancy is shape-safe (ragged batches), this is
                # just the point past which padding jumps capacities
                from ..compiler.shapes import small_capacity
                max_batch = small_capacity()
        if queue_cap is None:
            queue_cap = int(os.environ.get('KTPU_QUEUE_CAP', '256'))
        if shed_deadline_ms is None:
            shed_deadline_ms = float(os.environ.get(
                'KTPU_SHED_DEADLINE_MS', '500'))
        self.window_s = window_ms / 1000.0
        self.max_batch = max(1, max_batch)
        self.shed_deadline_s = shed_deadline_ms / 1000.0
        self.queue = RequestQueue(max(1, queue_cap))
        self.sheds = shed_policy.ShedLedger()
        self.on_success = on_success
        self.on_failure = on_failure
        self._stats_lock = threading.Lock()
        self._occupancies: deque = deque(maxlen=4096)
        self._hetero_occupancies: deque = deque(maxlen=4096)
        self._waits_s: deque = deque(maxlen=8192)
        self._dispatches = 0
        self._hetero_dispatches = 0
        self._requests = 0
        self._quarantine_dispatches = 0
        # consecutive all-failed dispatch count per key; touched only
        # by the batcher thread (dispatches are serialized), reset the
        # moment any rider of the key resolves on device
        self._all_failed: Dict = {}
        self._registered_on: Optional[MetricsRegistry] = None
        self._stopped = False
        self._thread = threading.Thread(
            target=self._loop, name='ktpu-admission-batcher', daemon=True)
        self._thread.start()

    # -- submission (webhook threads) -------------------------------------

    def submit(self, resource: dict, context: Optional[dict], pctx,
               admission: tuple, scanner, policies,
               old_resource: Optional[dict] = None) -> Ticket:
        """Enqueue one request; raises QueueFull / Stopped (callers shed
        to the host loop).  The current span rides along so the batch
        span nests under the request's HTTP-handler span.  The key is
        the scanner's monotonic serial alone (validate and mutate
        compile distinct scanners, so program kinds never mix, while
        distinct users/roles/namespaces/verbs coalesce — the scanner
        consumes per-row admission tuples); scanners without per-row
        support fall back to serial + the canonical admission tuple,
        recorded on the coverage ledger.  UPDATE tickets carry their
        oldObject for the scanner's old-match retry."""
        serial = getattr(scanner, 'serial', None)
        sid = serial if serial is not None else id(scanner)
        if getattr(scanner, 'supports_row_admissions', False):
            key: tuple = ('s', sid)
        else:
            key = ('a', sid, admission_key(admission))
            coverage.record_fallback(
                'serving', coverage.REASON_ADMISSION_UNENCODABLE)
        ticket = Ticket(
            key=key,
            resource=resource, context=context, pctx=pctx,
            admission=admission, scanner=scanner, policies=policies,
            span=tracing.current_span(), on_shed=self.sheds.record,
            old_resource=old_resource)
        self.queue.put(ticket)
        self._set_depth()
        return ticket

    def record_shed(self, reason: str) -> None:
        self.sheds.record(reason)

    # -- the coalescing loop ----------------------------------------------

    def _loop(self) -> None:
        while True:
            first = self.queue.wait_for_work()
            if first is None:
                return  # stopping and drained
            self.queue.wait_flush(first.key, self.max_batch,
                                  first.enqueued_at + self.window_s)
            batch = self.queue.take_batch(first.key, self.max_batch)
            self._set_depth()
            if batch:
                self._dispatch(batch)

    def _dispatch(self, batch) -> None:
        t0 = time.monotonic()
        lead = batch[0]
        self._observe(batch, t0)
        from ..observability import provenance
        try:
            self._scan_and_resolve(batch, t0)
        except Exception as e:  # noqa: BLE001 - riders quarantine, never a 500
            resolved, _shed, wholesale = self._quarantine(
                batch, t0, depth=1)
            # the breaker hears at most one verdict per ORIGINAL
            # dispatch: any rider resolving on device proves the
            # backend healthy (the failure was row-local); nothing
            # surviving is a breaker failure only on systemic evidence
            # — a wholesale shed, or the key failing every row of
            # ALL_FAILED_BREAKER_AFTER consecutive dispatches.  An
            # all-poison batch (row-attributed sheds, first strike)
            # stays neutral: no verdict, scanner keeps serving.
            if resolved:
                self._all_failed.pop(lead.key, None)
                if self.on_success is not None:
                    self.on_success(lead.policies)
            else:
                strikes = self._all_failed.get(lead.key, 0) + 1
                self._all_failed[lead.key] = strikes
                while len(self._all_failed) > 512:  # stray-key bound
                    self._all_failed.pop(next(iter(self._all_failed)))
                if (wholesale or strikes >= ALL_FAILED_BREAKER_AFTER) \
                        and self.on_failure is not None:
                    self.on_failure(lead.policies, e)
            # flight-recorder dump last: the riders and the breaker are
            # already notified, so the (file-writing) dump never delays
            # recovery — the ring's history lands on disk next to the
            # failure that triggered this quarantine
            provenance.notify_scan_error(e)
            return
        self._all_failed.pop(lead.key, None)
        if self.on_success is not None:
            self.on_success(lead.policies)

    def _scan_and_resolve(self, batch, t0: float) -> None:
        """One shared device dispatch for ``batch``: scan, fill
        provenance, resolve every rider.  Raises on failure — the
        caller (``_dispatch`` / ``_quarantine``) owns shed and breaker
        accounting.  Quarantine sub-dispatches re-enter here, so the
        fault-injection row check re-fires per sub-batch and bisection
        can isolate marker-poisoned rows."""
        lead = batch[0]
        scanner = lead.scanner
        resources = [t.resource for t in batch]
        contexts = [t.context for t in batch]
        # host materialization must see each request's own
        # PolicyContext; scan hands the factory the resource document,
        # which is this request's freshly parsed dict
        pctx_of = {id(t.resource): t.pctx for t in batch}
        lead_pctx = lead.pctx

        def pctx_factory(doc):
            return pctx_of.get(id(doc), lead_pctx)

        from ..observability import device as devtel
        from ..observability import provenance
        # per-dispatch provenance capture: device_eval time of THIS
        # scan (not a registry-sum delta a concurrent rescan could
        # contaminate) amortizes over the riders as their device share
        cap = devtel.ScanCapture() if provenance.enabled() else None
        # UPDATE rows carry oldObject for the scanner's match retry; the
        # kwarg is only passed when present so CREATE-era scanner
        # doubles (and the mutate scanner) keep their signatures
        extra = {}
        if any(t.old_resource for t in batch):
            extra['old_resources'] = [t.old_resource for t in batch]
        # heterogeneous batches: each rider's own admission tuple rides
        # to the scanner as a per-row column (the scanner-only batch
        # key makes mixed tuples share this dispatch)
        if getattr(scanner, 'supports_row_admissions', False):
            extra['admissions'] = [t.admission for t in batch]
        with devtel.install_capture(cap), \
                tracing.tracer().start_span(
                    'kyverno/serving/batch',
                    {'occupancy': len(batch),
                     'window_ms': self.window_s * 1000.0},
                    parent=lead.span) as bspan:
            faults.check_rows(faults.SITE_BATCHER_DISPATCH, resources)
            rows = scanner.scan(resources, contexts=contexts,
                                admission=lead.admission,
                                pctx_factory=pctx_factory, **extra)
            if cap is not None and cap.critical_path is not None:
                from ..observability import timeline as tlmod
                bspan.set_attribute(
                    'critical_path',
                    tlmod.format_summary(cap.critical_path))
        if cap is not None:
            device_eval_s = cap.stage_s('device_eval')
            share = device_eval_s / len(batch)
            batch_id = provenance.next_batch_id()
            for t in batch:
                # filled before resolve(): the waiting webhook thread
                # reads prov right after its future resolves
                t.prov = {
                    'batch_id': batch_id,
                    'occupancy': len(batch),
                    'queue_wait_s': t0 - t.enqueued_at,
                    'device_share_s': share,
                    'device_eval_s': device_eval_s,
                    'aot_cache': cap.aot,
                    'coverage_ratio': cap.coverage_ratio,
                }
        for t, row in zip(batch, rows):
            t.resolve(row)

    def _shed_batch(self, batch, reason: str) -> None:
        for t in batch:
            t.shed(reason)
            self.sheds.record(reason)
            if reason == shed_policy.REASON_POISON_ROW:
                # the quarantined row is served by the host loop; the
                # coverage ledger attributes that fall like any other
                coverage.record_fallback(
                    'serving', coverage.REASON_POISON_ROW)

    def _quarantine(self, batch, t0: float, depth: int):
        """Bisect a failed dispatch to isolate poison rows.

        Returns ``(resolved, shed, wholesale)`` rider counts, where
        ``wholesale`` is the subset of ``shed`` that is
        infrastructure-shaped evidence: depth-bound groups (shed under
        ``scan_error``, un-isolated) and retry-exhausted pipeline
        failures (shed under ``stage_retry_exhausted``).  A singleton
        gets one solo re-dispatch — transient device errors recover
        with no shed at all — and only a persistently failing row
        sheds, under ``poison_row``; those row-attributed sheds count
        in ``shed`` but never in ``wholesale``, so the caller's breaker
        verdict can tell an unlucky all-poison batch from a broken
        backend, and the poison_row count stays an exact per-row
        signal.
        """
        if depth > QUARANTINE_MAX_DEPTH:
            self._shed_batch(batch, shed_policy.REASON_SCAN_ERROR)
            return 0, len(batch), len(batch)
        with self._stats_lock:
            self._quarantine_dispatches += 1
        if len(batch) == 1:
            try:
                self._scan_and_resolve(batch, t0)
            except Exception as e:  # noqa: BLE001 - row is poison, shed it
                exhausted = getattr(e, 'ktpu_retry_exhausted', False)
                reason = shed_policy.REASON_STAGE_RETRY_EXHAUSTED \
                    if exhausted else shed_policy.REASON_POISON_ROW
                self._shed_batch(batch, reason)
                return 0, 1, (1 if exhausted else 0)
            return 1, 0, 0
        mid = len(batch) // 2
        resolved = shed = wholesale = 0
        for half in (batch[:mid], batch[mid:]):
            try:
                self._scan_and_resolve(half, t0)
                resolved += len(half)
            except Exception:  # noqa: BLE001 - keep bisecting this half
                r, s, w = self._quarantine(half, t0, depth + 1)
                resolved += r
                shed += s
                wholesale += w
        return resolved, shed, wholesale

    # -- telemetry ---------------------------------------------------------

    def _registry(self) -> Optional[MetricsRegistry]:
        registry = global_registry()
        if registry is not None and registry is not self._registered_on:
            # bucket overrides must land before the first observe; the
            # calls are no-ops once each histogram exists
            registry.register_histogram(BATCH_OCCUPANCY,
                                        OCCUPANCY_BUCKETS)
            registry.register_histogram(HETERO_OCCUPANCY,
                                        OCCUPANCY_BUCKETS)
            registry.register_histogram(QUEUE_WAIT, WAIT_BUCKETS)
            # queue depth is a residency gauge: a drained server must
            # export 0 (swept by cmd/internal.Setup.shutdown)
            registry.mark_reset_on_close(QUEUE_DEPTH)
            self._registered_on = registry
        return registry

    def _set_depth(self) -> None:
        registry = self._registry()
        if registry is not None:
            registry.set_gauge(QUEUE_DEPTH, self.queue.depth())

    def _observe(self, batch, t0: float) -> None:
        waits = [t0 - t.enqueued_at for t in batch]
        # heterogeneous = the riders carry >1 distinct canonical
        # admission tuple; production telemetry must distinguish this
        # coalescing regime from same-tuple (homogeneous) batching
        hetero = len(batch) > 1 and \
            len({admission_key(t.admission) for t in batch}) > 1
        with self._stats_lock:
            self._dispatches += 1
            self._requests += len(batch)
            self._occupancies.append(len(batch))
            if hetero:
                self._hetero_dispatches += 1
                self._hetero_occupancies.append(len(batch))
            self._waits_s.extend(waits)
        registry = self._registry()
        if registry is not None:
            registry.observe(BATCH_OCCUPANCY, float(len(batch)))
            if hetero:
                registry.observe(HETERO_OCCUPANCY, float(len(batch)))
            for w in waits:
                registry.observe(QUEUE_WAIT, w)

    @staticmethod
    def _p50(values) -> float:
        data = sorted(values)
        return data[len(data) // 2] if data else 0.0

    def stats(self) -> Dict[str, object]:
        """Local counters for benchmarks/tests (no registry needed)."""
        with self._stats_lock:
            occ = list(self._occupancies)
            hocc = list(self._hetero_occupancies)
            waits = list(self._waits_s)
            dispatches = self._dispatches
            hetero = self._hetero_dispatches
            requests = self._requests
            quarantine = self._quarantine_dispatches
        return {
            'dispatches': dispatches,
            'quarantine_dispatches': quarantine,
            'requests': requests,
            'occupancy_mean': (sum(occ) / len(occ)) if occ else 0.0,
            'occupancy_p50': self._p50(occ),
            'hetero_dispatches': hetero,
            'hetero_occupancy_mean': (sum(hocc) / len(hocc))
            if hocc else 0.0,
            'queue_wait_p50_ms': self._p50(waits) * 1000.0,
            'shed_total': self.sheds.total(),
            'shed': self.sheds.counts(),
            'queue_depth': self.queue.depth(),
        }

    def reset_stats(self) -> None:
        with self._stats_lock:
            self._occupancies.clear()
            self._hetero_occupancies.clear()
            self._waits_s.clear()
            self._dispatches = 0
            self._hetero_dispatches = 0
            self._requests = 0
            self._quarantine_dispatches = 0
        self.sheds.reset()

    # -- lifecycle ---------------------------------------------------------

    def stop(self, drain: bool = True, timeout: float = 30.0) -> None:
        """Stop the loop.  ``drain=True`` (shutdown path) dispatches
        every pending ticket first — their waiting webhook threads get
        real batched responses; ``drain=False`` sheds them to the host
        loop immediately."""
        if self._stopped:
            return
        self._stopped = True
        if not drain:
            for t in self.queue.take_all():
                t.shed(shed_policy.REASON_SHUTDOWN)
                self.sheds.record(shed_policy.REASON_SHUTDOWN)
        self.queue.stop()
        self._thread.join(timeout=timeout)
