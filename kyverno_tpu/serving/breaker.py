"""Per-policy-set circuit breakers with half-open recovery.

Replaces the permanent ``_dead_keys`` trip in ``webhooks/handlers.py``
with a closed → open → half-open state machine:

* **closed** — device path serves; failures count toward the limit.
* **open** — the set is quarantined to the host engine loop for an
  exponential backoff window (``KTPU_BREAKER_BACKOFF_MS`` base,
  doubling per trip up to ``KTPU_BREAKER_BACKOFF_MAX_MS``, plus a
  deterministic per-(key, trip) jitter fraction so many sets tripped
  by one systemic event don't re-probe in lockstep).
* **half-open** — the backoff elapsed: exactly ONE request per window
  is admitted as a probe (``allow`` returns :data:`PROBE`); everyone
  else keeps shedding to the host loop.  A probe success closes the
  breaker and re-admits the set to the device path; a probe failure
  re-opens it with a doubled backoff.

The registry is bounded (``KTPU_BREAKER_CAP``).  Evicting an entry
forgets breaker state — under many policy sets that can silently
re-admit a broken backend — so every eviction counts on
``kyverno_tpu_breaker_evictions_total`` and closed entries are evicted
before tripped ones.  State is exported as the
``kyverno_tpu_breaker_state{state}`` gauge and as JSON on the profile
server's ``GET /debug/breakers``.

The clock is injectable so tests drive the full open → half-open →
closed round trip without sleeping.
"""

from __future__ import annotations

import os
import threading
import time
import weakref
from collections import OrderedDict
from typing import Callable, Dict, List, Optional, Tuple

from ..observability.metrics import global_registry

BREAKER_STATE = 'kyverno_tpu_breaker_state'
BREAKER_EVICTIONS = 'kyverno_tpu_breaker_evictions_total'

#: breaker states (also the ``allow`` decisions; PROBE is the
#: half-open decision handed to exactly one caller per window)
CLOSED = 'closed'
OPEN = 'open'
HALF_OPEN = 'half_open'
PROBE = 'probe'

STATES = (CLOSED, OPEN, HALF_OPEN)

#: deterministic jitter fraction added on top of the exponential
#: backoff (scaled by a per-(key, trips) hash in [0, 1))
JITTER = 0.2


def breaker_cap() -> int:
    try:
        return max(1, int(os.environ.get('KTPU_BREAKER_CAP', '64')))
    except ValueError:
        return 64


def base_backoff_s() -> float:
    try:
        return max(0.001, float(os.environ.get(
            'KTPU_BREAKER_BACKOFF_MS', '1000')) / 1000.0)
    except ValueError:
        return 1.0


def max_backoff_s() -> float:
    try:
        return max(0.001, float(os.environ.get(
            'KTPU_BREAKER_BACKOFF_MAX_MS', '60000')) / 1000.0)
    except ValueError:
        return 60.0


class _Entry:
    __slots__ = ('state', 'failures', 'policies', 'opened_at',
                 'backoff_s', 'trips', 'probe_inflight', 'probe_at',
                 'last_error')

    def __init__(self, policies):
        self.state = CLOSED
        self.failures = 0
        # pin the policy objects while counted: the key is a tuple of
        # id()s, so CPython id reuse after GC must be impossible
        self.policies = list(policies)
        self.opened_at = 0.0
        self.backoff_s = 0.0
        self.trips = 0
        self.probe_inflight = False
        self.probe_at = 0.0
        self.last_error = ''


#: live registries, for /debug/breakers aggregation (weak: a handler
#: teardown drops its registry from the debug view automatically)
_DEBUG: 'weakref.WeakSet[BreakerRegistry]' = weakref.WeakSet()


def debug_report() -> dict:
    """Aggregate JSON body for ``GET /debug/breakers``."""
    regs = [r for r in list(_DEBUG)]
    return {
        'enabled': bool(regs),
        'breakers': [item for r in regs for item in r.report()],
    }


class BreakerRegistry:
    """Keyed breaker states behind one lock.

    ``on_open(open_count)`` fires (outside the lock) whenever a trip
    raises the number of simultaneously open breakers — the handlers
    layer uses it for the systemic global device disable.
    """

    def __init__(self, failure_limit: int = 3,
                 cap: Optional[int] = None,
                 clock: Callable[[], float] = time.monotonic,
                 base_s: Optional[float] = None,
                 max_s: Optional[float] = None,
                 on_open: Optional[Callable[[int], None]] = None):
        self.failure_limit = max(1, failure_limit)
        self.cap = cap if cap is not None else breaker_cap()
        self.clock = clock
        self.base_s = base_s if base_s is not None else base_backoff_s()
        self.max_s = max_s if max_s is not None else max_backoff_s()
        self.on_open = on_open
        self._entries: 'OrderedDict[tuple, _Entry]' = OrderedDict()
        self._lock = threading.Lock()
        _DEBUG.add(self)

    # -- internals (lock held) --------------------------------------------

    def _backoff(self, key, trips: int) -> float:
        base = min(self.max_s, self.base_s * (2.0 ** max(0, trips - 1)))
        # tuple-of-int keys hash deterministically within a process, so
        # the jitter is stable per (key, trip) — replayable in tests —
        # while still de-synchronizing distinct sets
        frac = (hash((key, trips)) & 0xFFFF) / float(0xFFFF)
        return base * (1.0 + JITTER * frac)

    def _trip(self, key, entry: _Entry) -> None:
        entry.trips += 1
        entry.state = OPEN
        entry.opened_at = self.clock()
        entry.backoff_s = self._backoff(key, entry.trips)
        entry.probe_inflight = False

    def _evict_for_cap(self) -> None:
        registry = global_registry()
        while len(self._entries) >= self.cap:
            # evict closed (merely counting) entries before tripped
            # ones: forgetting an OPEN breaker re-admits a broken
            # backend, so it is the last thing to go — and either way
            # the eviction is counted, never silent
            victim = None
            for k, e in self._entries.items():
                if e.state == CLOSED:
                    victim = k
                    break
            if victim is None:
                victim = next(iter(self._entries))
            self._entries.pop(victim)
            if registry is not None:
                registry.inc(BREAKER_EVICTIONS)

    def _emit_states(self) -> None:
        registry = global_registry()
        if registry is None:
            return
        # breaker occupancy is a residency gauge: after shutdown the
        # sweep (cmd/internal.Setup.shutdown) zeroes every state series
        registry.mark_reset_on_close(BREAKER_STATE)
        counts = {s: 0 for s in STATES}
        for e in self._entries.values():
            counts[e.state] += 1
        for s, n in counts.items():
            registry.set_gauge(BREAKER_STATE, float(n), state=s)

    # -- decisions ---------------------------------------------------------

    def allow(self, key) -> str:
        """Admission decision for ``key``: :data:`CLOSED` (device
        path), :data:`OPEN` (host loop), or :data:`PROBE` (this caller
        is the single half-open probe)."""
        with self._lock:
            entry = self._entries.get(key)
            if entry is None or entry.state == CLOSED:
                return CLOSED
            if entry.state == OPEN:
                if self.clock() - entry.opened_at < entry.backoff_s:
                    return OPEN
                entry.state = HALF_OPEN
                entry.probe_inflight = True
                entry.probe_at = self.clock()
                self._emit_states()
                return PROBE
            # half-open: one probe per backoff-sized window.  A probe
            # whose request never reported back (shed before dispatch,
            # caller died) must not wedge the breaker: after a full
            # window with no verdict the slot re-opens
            if not entry.probe_inflight or \
                    self.clock() - entry.probe_at >= entry.backoff_s:
                entry.probe_inflight = True
                entry.probe_at = self.clock()
                return PROBE
            return OPEN

    def probe_abort(self, key) -> None:
        """The probe slot's caller could not actually run a request
        (scanner still building): release the slot so the next caller
        re-probes instead of the window wedging."""
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None and entry.state == HALF_OPEN:
                entry.probe_inflight = False

    def state(self, key) -> str:
        with self._lock:
            entry = self._entries.get(key)
            return entry.state if entry is not None else CLOSED

    def open_count(self) -> int:
        with self._lock:
            return sum(1 for e in self._entries.values()
                       if e.state != CLOSED)

    # -- outcomes ----------------------------------------------------------

    def record_failure(self, key, policies, error: str = '') -> str:
        """One device failure for ``key``; returns the state after.
        Fires ``on_open`` (outside the lock) on a trip."""
        opened: Optional[int] = None
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                self._evict_for_cap()
                entry = _Entry(policies)
                self._entries[key] = entry
            entry.failures += 1
            entry.last_error = str(error)[:200]
            if entry.state == HALF_OPEN:
                # the probe failed: back to open, doubled backoff
                self._trip(key, entry)
            elif entry.state == CLOSED and \
                    entry.failures >= self.failure_limit:
                self._trip(key, entry)
            if entry.state == OPEN and entry.trips == 1 and \
                    entry.failures == self.failure_limit:
                opened = sum(1 for e in self._entries.values()
                             if e.state != CLOSED)
            self._emit_states()
            state = entry.state
        if opened is not None and self.on_open is not None:
            self.on_open(opened)
        return state

    def migrate(self, old_key, new_key, policies=None) -> str:
        """Carry breaker state from a retired scanner key to its
        successor (scanner hot-swap: same logical policy set, new
        compiled serial).  Without this a swap silently forgives an
        open breaker — the recompiled set would re-enter the device
        path with a clean slate while the backend fault that tripped it
        may still be live.  The entry moves verbatim (state, failure
        count, trips, backoff clock); ``policies`` re-pins the entry on
        the successor's policy objects so the id()-tuple key stays
        collision-safe.  Returns the migrated state (:data:`CLOSED`
        when there was nothing to carry)."""
        with self._lock:
            entry = self._entries.pop(old_key, None)
            if entry is None:
                return CLOSED
            if policies is not None:
                entry.policies = list(policies)
            # an in-flight probe belonged to the retired scanner; the
            # successor's first allow() re-probes on its own clock
            entry.probe_inflight = False
            self._entries[new_key] = entry
            self._emit_states()
            return entry.state

    def record_success(self, key) -> None:
        """One device success for ``key``: closes a half-open breaker
        (recovery — the set is re-admitted to the device path) and
        forgets a closed entry's failure count."""
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                return
            # success in any state proves the backend serves this set
            # again: drop the entry entirely, unpinning its policies
            self._entries.pop(key, None)
            self._emit_states()

    # -- introspection -----------------------------------------------------

    def report(self) -> List[dict]:
        """Per-key rows for ``/debug/breakers``."""
        now = self.clock()
        with self._lock:
            items: List[Tuple[tuple, _Entry]] = list(self._entries.items())
        rows = []
        for key, e in items:
            names = []
            for p in e.policies:
                name = getattr(p, 'name', None)
                names.append(str(name) if name else type(p).__name__)
            row: Dict[str, object] = {
                'key': repr(key),
                'policies': names,
                'state': e.state,
                'failures': e.failures,
                'trips': e.trips,
                'probe_inflight': e.probe_inflight,
                'last_error': e.last_error,
            }
            if e.state == OPEN:
                row['reopens_in_s'] = round(
                    max(0.0, e.opened_at + e.backoff_s - now), 3)
            rows.append(row)
        return rows
