"""Self-subject access checks (reference: pkg/auth/auth.go CanIOptions,
pkg/policy/generate/auth.go Auth).

The generate machinery create/update/deletes the resources named in
generate rules using the controller's own service account; before a
generate policy is admitted — and before a background UR applies its
targets — the controller verifies it actually holds those permissions by
creating ``SelfSubjectAccessReview`` objects and reading
``.status.allowed`` (reference: auth.go:57 RunAccessCheck).
"""

from __future__ import annotations

import re
from typing import Optional, Tuple

_VERSION_RE = re.compile(r'^v\d((alpha|beta)\d)?$')

# irregular kind → resource plural forms (the discovery RESTMapper's job
# in the reference; a static table plus naive pluralization suffices for
# the kinds policies generate)
_IRREGULAR_PLURALS = {
    'Endpoints': 'endpoints',
    'NetworkPolicy': 'networkpolicies',
    'PodSecurityPolicy': 'podsecuritypolicies',
    'Ingress': 'ingresses',
    'IngressClass': 'ingressclasses',
    'StorageClass': 'storageclasses',
    'PriorityClass': 'priorityclasses',
    'RuntimeClass': 'runtimeclasses',
    'Gateway': 'gateways',
    'HTTPRoute': 'httproutes',
    'GRPCRoute': 'grpcroutes',
    'ReferenceGrant': 'referencegrants',
    'PodMetrics': 'pods',
    'NodeMetrics': 'nodes',
}


def _pluralize(kind: str) -> str:
    irregular = _IRREGULAR_PLURALS.get(kind)
    if irregular:
        return irregular
    low = kind.lower()
    # English pluralization only turns -y into -ies after a consonant
    # (Policy → policies); vowel + y just appends s (Gateway →
    # gateways) — the old unconditional rule produced 'gatewaies' and
    # SSAR probes against a nonexistent GVR
    if low.endswith('y') and len(low) > 1 and low[-2] not in 'aeiou':
        return low[:-1] + 'ies'
    if low.endswith(('s', 'x', 'z', 'ch', 'sh')):
        return low + 'es'
    return low + 's'


def gvr_from_kind(kind: str) -> Tuple[str, str]:
    """(group, resource-plural) for a policy 'kind' entry, accepting the
    bare ``Kind``, ``version/Kind`` and ``group/version/Kind`` forms
    (reference: auth.go:60 GetGVRFromKind via the discovery REST
    mapper)."""
    parts = [p for p in kind.split('/') if p]
    group = ''
    bare = parts[-1] if parts else ''
    if len(parts) == 2 and not _VERSION_RE.match(parts[0]):
        group = parts[0]
    elif len(parts) == 3:
        group = parts[0]
    return group, _pluralize(bare)


class CanI:
    """reference: pkg/auth/auth.go:30 canIOptions.

    One (kind, namespace, verb, subresource) permission probe; each
    ``run_access_check`` creates a SelfSubjectAccessReview through the
    client and evaluates the response.
    """

    def __init__(self, client, kind: str, namespace: str, verb: str,
                 subresource: str = ''):
        self.client = client
        self.kind = kind
        self.namespace = namespace
        self.verb = verb
        self.subresource = subresource

    def run_access_check(self) -> bool:
        """reference: auth.go:57 RunAccessCheck — builds the SSAR spec
        from the resolved GVR and returns ``.status.allowed``."""
        if not self.kind:
            raise ValueError('failed to get GVR for empty kind')
        group, resource = gvr_from_kind(self.kind)
        attrs = {
            'namespace': self.namespace,
            'verb': self.verb,
            'group': group,
            'resource': resource,
            'subresource': self.subresource,
        }
        status = self.client.create_access_review(attrs)
        return bool(status.get('allowed'))


class Auth:
    """reference: pkg/policy/generate/auth.go:24 Auth — the four verbs
    the generate controller needs on target kinds."""

    def __init__(self, client):
        self.client = client

    def _check(self, verb: str, kind: str, namespace: str) -> bool:
        return CanI(self.client, kind, namespace, verb).run_access_check()

    def can_i_create(self, kind: str, namespace: str) -> bool:
        return self._check('create', kind, namespace)

    def can_i_update(self, kind: str, namespace: str) -> bool:
        return self._check('update', kind, namespace)

    def can_i_delete(self, kind: str, namespace: str) -> bool:
        return self._check('delete', kind, namespace)

    def can_i_get(self, kind: str, namespace: str) -> bool:
        return self._check('get', kind, namespace)

    def can_i_list(self, kind: str, namespace: str) -> bool:
        return self._check('list', kind, namespace)


class FakeAuth:
    """Allow-everything Operations for offline/CLI validation
    (reference: pkg/policy/generate/fake/auth.go)."""

    def can_i_create(self, kind: str, namespace: str) -> bool:
        return True

    def can_i_update(self, kind: str, namespace: str) -> bool:
        return True

    def can_i_delete(self, kind: str, namespace: str) -> bool:
        return True

    def can_i_get(self, kind: str, namespace: str) -> bool:
        return True

    def can_i_list(self, kind: str, namespace: str) -> bool:
        return True


def is_variable(s: Optional[str]) -> bool:
    """reference: pkg/engine/variables/variables.go IsVariable — auth
    checks are skipped when kind/namespace contain unresolved
    variables."""
    return bool(s) and '{{' in s


def can_i_generate_error(auth, kind: str, namespace: str) -> Optional[str]:
    """The generate controller's four-verb pre-flight on one target
    kind; returns the reference's error message on the first denied
    verb, else None (reference: pkg/policy/generate/validate.go:130
    canIGenerate).  ``kind`` may carry group/version prefixes — the
    probe resolves them (auth checks skip unresolved variables)."""
    if is_variable(kind) or is_variable(namespace):
        return None
    bare = kind.split('/')[-1]  # the message names the kind as the
    # reference does; the probe itself keeps the group qualifier
    for verb, check in (('create', auth.can_i_create),
                        ('update', auth.can_i_update),
                        ('get', auth.can_i_get),
                        ('delete', auth.can_i_delete)):
        if not check(kind, namespace):
            return (f"kyverno does not have permissions to '{verb}' "
                    f'resource {bare}/{namespace}. Update permissions '
                    f"in ClusterRole 'kyverno:generate'")
    return None
