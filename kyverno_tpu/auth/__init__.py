from .auth import Auth, CanI, FakeAuth, gvr_from_kind

__all__ = ['Auth', 'CanI', 'FakeAuth', 'gvr_from_kind']
