"""Device mesh + sharded evaluation step.

The scaling model (SURVEY.md §2.6): policy evaluation is embarrassingly
data-parallel over the resource batch axis — the TPU-native equivalent of
the reference's horizontally replicated webhook pods. The compiled check
program is a trace-time constant (replicated), the batch is sharded over a
1-D ``data`` mesh axis, and the only cross-chip communication is the
verdict-summary reduction (``psum``), which rides ICI.

Multi-host: the same code runs under ``jax.distributed`` — the mesh spans
all slices and GSPMD inserts DCN collectives for the summary only.
"""

from __future__ import annotations

import time
from contextlib import nullcontext
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..compiler.ir import CompiledPolicySet


def make_mesh(devices: Optional[List] = None, axis: str = 'data') -> Mesh:
    if devices is None:
        devices = jax.devices()
    return Mesh(np.asarray(devices), (axis,))


def pad_to_multiple(n: int, multiple: int) -> int:
    return ((n + multiple - 1) // multiple) * multiple


def build_sharded_evaluator(cps: CompiledPolicySet, mesh: Mesh,
                            axis: str = 'data'):
    """A jitted, mesh-sharded evaluation step.

    Returns ``(statuses [R, P] sharded over R, summary [P, 3] replicated)``
    where summary counts pass/fail/skip per rule across all shards — the
    all-reduce that replaces the reference's report aggregation fan-in
    (reference: pkg/controllers/report/aggregate/controller.go).
    """
    from ..aotcache import enable_persistent_compilation_cache
    from ..compiler.ir import N_STATUS_CODES
    from ..ops.eval import build_evaluator, enable_x64, unpack_batch
    # sharded executables embed the mesh's device assignment, so the
    # AOT executable store cannot persist them; the XLA persistent
    # compilation cache (keyed on the computation fingerprint) still
    # skips the backend compile for a fresh process on the same mesh
    enable_persistent_compilation_cache()
    evaluator = build_evaluator(cps)
    n_codes = N_STATUS_CODES

    def step(packed: Dict[str, jnp.ndarray]):
        t = unpack_batch(packed, evaluator.layout_holder['layout'])
        # the encoder's row-validity lane: canonical-capacity padding
        # rows must not count in the cross-shard verdict summary
        rowmask = t.pop('__rowvalid__', None)
        # fdet is dropped here: the distributed summary path never
        # synthesizes messages, and leaving it out of the jit outputs
        # lets XLA DCE the whole fail-site computation
        statuses, details, _fdet = evaluator.raw(t)
        # per-rule verdict histogram over the status codes; with GSPMD
        # the partial sums are psum-reduced over ICI automatically
        one_hot = jax.nn.one_hot(statuses, n_codes, dtype=jnp.int32)
        if rowmask is not None:
            one_hot = one_hot * (rowmask != 0).astype(
                jnp.int32)[:, None, None]
        summary = jnp.sum(one_hot, axis=0)
        return statuses, details, summary

    out_shardings = (NamedSharding(mesh, P(axis)),
                     NamedSharding(mesh, P(axis)),
                     NamedSharding(mesh, P()))
    # input shardings propagate from the device_put placement in
    # shard_tensors; only outputs are constrained here
    jitted = jax.jit(step, out_shardings=out_shardings)
    # signatures this sharded jit has traced, mirroring the evaluator's
    # own hit/miss telemetry so the mesh path's compiles show up in the
    # kyverno_tpu_compile_cache counters too
    jit_seen: set = set()

    def run(tensors, layout):
        from ..observability import device as devtel
        # layout_holder is shared with the single-device evaluator's
        # traces — take its compile lock so a concurrent call cannot
        # bake this layout into the wrong executable
        with evaluator.compile_lock:
            evaluator.layout_holder['layout'] = layout
            with enable_x64():
                if devtel.enabled():
                    sig = tuple((k, str(v.dtype), tuple(v.shape))
                                for k, v in sorted(tensors.items()))
                    if sig not in jit_seen:
                        jit_seen.add(sig)
                        devtel.record_cache('miss')
                        with devtel.stage('compile') as st:
                            st.set_attribute('cache', 'miss')
                            st.set_attribute('mesh', True)
                            return jitted(tensors)
                    devtel.record_cache('hit')
                return jitted(tensors)

    return run


def shard_tensors(tensors: Dict[str, np.ndarray], mesh: Mesh,
                  axis: str = 'data') -> Dict[str, Any]:
    """Place batch tensors with the leading axis sharded over the mesh."""
    from ..ops.eval import shard_batch
    return shard_batch(tensors, mesh, axis)


# (cps id, mesh, axis) -> sharded evaluator. LRU with single-entry
# eviction; the cps entry keeps a strong reference to the keyed object so
# ids cannot be recycled while cached.
from collections import OrderedDict

_SHARDED_CACHE: 'OrderedDict[Tuple[int, Mesh, str], Tuple[CompiledPolicySet, Any]]' = OrderedDict()
_SHARDED_CACHE_MAX = 16


def _cached_sharded_evaluator(cps: CompiledPolicySet, mesh: Mesh, axis: str):
    key = (id(cps), mesh, axis)
    hit = _SHARDED_CACHE.get(key)
    if hit is not None and hit[0] is cps:
        _SHARDED_CACHE.move_to_end(key)
        return hit[1]
    step = build_sharded_evaluator(cps, mesh, axis)
    while len(_SHARDED_CACHE) >= _SHARDED_CACHE_MAX:
        _SHARDED_CACHE.popitem(last=False)
    _SHARDED_CACHE[key] = (cps, step)
    return step


def shard_wait_splits(array) -> List[float]:
    """Per-shard readback-wait splits: block on each addressable shard
    of a just-dispatched sharded array in batch-axis order and time
    each wait separately.  The split attributes wall to the shard the
    host was actually waiting on (with all shards in flight, the shard
    you block longest on IS the straggler); the ``mesh_shard`` fault
    site is checked inside each timed split, so an injected
    ``delay_ms`` clause inflates exactly one shard's wall."""
    from .. import faults

    def _order(sh):
        try:
            return sh.index[0].start or 0
        except Exception:  # noqa: BLE001 - fall back to device ids
            return getattr(sh.device, 'id', 0)

    walls: List[float] = []
    for sh in sorted(array.addressable_shards, key=_order):
        t0 = time.perf_counter()
        faults.check(faults.SITE_MESH_SHARD)
        sh.data.block_until_ready()
        walls.append(time.perf_counter() - t0)
    return walls


def record_sharded_dispatch(mesh: Mesh, axis: str, n_rows: int,
                            padded_rows: int,
                            shard_walls: List[float],
                            collective_s: float,
                            step_wall: Optional[float] = None,
                            span=None):
    """Publish one sharded dispatch's telemetry: per-shard device-eval
    walls, skew verdict, collective wall and padding waste — on the
    fleet-scoped mesh metrics (KTPU509 holds these write sites to
    their shard/mesh identity labels) and the ``kyverno/mesh/step``
    span when the caller passes one.  Returns the skew verdict."""
    from ..observability import fleet
    n_dev = mesh.devices.size
    mesh_key = f'{axis}{n_dev}'
    devices = [str(d) for d in mesh.devices.flat]
    verdict = fleet.record_step(mesh_key, shard_walls, devices)
    registry = fleet.registry()
    if registry is not None:
        for i, wall_s in enumerate(shard_walls):
            registry.observe(fleet.MESH_STEP_DURATION, wall_s,
                             shard=str(i))
        if step_wall is not None:
            registry.observe(fleet.MESH_STEP_DURATION, step_wall,
                             shard='all')
        # skew describes the mesh step in flight — reset-on-close so a
        # drained host doesn't export its last imbalance forever
        registry.mark_reset_on_close(fleet.MESH_SHARD_SKEW)
        registry.set_gauge(fleet.MESH_SHARD_SKEW, verdict['skew'],
                           mesh=mesh_key)
        registry.inc(fleet.MESH_COLLECTIVE_SECONDS, collective_s,
                     mesh=mesh_key)
        registry.inc(fleet.MESH_PADDING_ROWS,
                     float(max(0, padded_rows - n_rows)), mesh=mesh_key)
    if span is not None:
        per = padded_rows // max(1, len(shard_walls))
        occupancy = [min(max(n_rows - i * per, 0), per)
                     for i in range(len(shard_walls))]
        span.set_attribute('mesh', mesh_key)
        span.set_attribute('rows', n_rows)
        span.set_attribute('padding_rows', max(0, padded_rows - n_rows))
        span.set_attribute('shard_rows', ','.join(map(str, occupancy)))
        span.set_attribute('skew', verdict['skew'])
        span.set_attribute('slow_shard', verdict['slow_shard'])
        span.set_attribute('collective_s', round(collective_s, 6))
        if verdict.get('sustained'):
            span.set_attribute('bound_by', 'straggler')
    return verdict


def distributed_scan_step(cps: CompiledPolicySet, mesh: Mesh,
                          resources: List[dict], axis: str = 'data'):
    """Encode + evaluate a batch across the mesh; returns (statuses, summary).

    The batch pads to the canonical capacity (``compiler/shapes.py``),
    rounded up to a multiple of the mesh size so every shard gets
    identical shapes; the encoder's ``__rowvalid__`` lane keeps the
    padding rows out of the verdict summary.

    With the fleet observatory armed (``observability/fleet.py``;
    ``KTPU_FLEET=0`` pins it off) every dispatch additionally records
    per-shard readback-wait splits, the collective wall and padding
    waste under a ``kyverno/mesh/step`` span — the timing never
    touches the computed values, so output stays bit-identical.
    """
    from ..compiler.encode import encode_batch
    from ..compiler.shapes import canonical_capacity
    from ..observability import fleet
    fl = fleet.enabled()
    n = len(resources)
    n_dev = mesh.devices.size
    padded = pad_to_multiple(
        max(canonical_capacity(max(n, n_dev)), n), n_dev)
    span_cm = nullcontext()
    if fl:
        from ..observability import tracing
        span_cm = tracing.start_span('kyverno/mesh/step')
    with span_cm as span:
        t_start = time.perf_counter() if fl else 0.0
        batch = encode_batch(resources, cps, padded_n=padded)
        raw = batch.tensors()
        tensors, layout = shard_tensors(raw, mesh, axis)
        step = _cached_sharded_evaluator(cps, mesh, axis)
        statuses, details, summary = step(tensors, layout)
        shard_walls = None
        t_coll = 0.0
        if fl:
            shard_walls = shard_wait_splits(statuses)
            t_coll = time.perf_counter()
        if jax.process_count() > 1:
            # multi-host: each process only holds its local shards of
            # the batch axis — gather the full status matrix across
            # hosts (the psum'd summary is already replicated on every
            # device)
            from jax.experimental import multihost_utils
            statuses = multihost_utils.process_allgather(statuses,
                                                         tiled=True)
        collective_s = 0.0
        if fl:
            # the psum'd summary readback (plus the multi-host
            # allgather above) is the step's cross-shard collective
            summary.block_until_ready()
            collective_s = time.perf_counter() - t_coll
        statuses_np = np.asarray(statuses)[:n]
        summary_np = np.asarray(summary)
        if fl:
            record_sharded_dispatch(
                mesh, axis, n, padded, shard_walls, collective_s,
                step_wall=time.perf_counter() - t_start, span=span)
    from ..observability import coverage
    if coverage.enabled():
        # the padded rows are already masked out of the summary, so the
        # STATUS_HOST column IS the host-replay row count of this step
        from ..compiler.ir import STATUS_HOST
        total = int(summary_np.sum())
        host = int(summary_np[:, STATUS_HOST].sum())
        coverage.record_scan(total - host, host)
    return statuses_np, summary_np
