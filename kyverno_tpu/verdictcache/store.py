"""Digest-keyed verdict cache: in-memory LRU front + atomic on-disk
snapshots.

One **generation** per (policy-set fingerprint × engine rev) holds
``spec digest → verdict row`` where a row is the fused report-path
output of one resource (``BatchScanner.scan_report_results``): the
result dicts (timestamps stripped — replay stamps the current tick),
the summary, and the indexes of the contributing policies (the
fingerprint pins policy-set order, so indexes are stable across
processes).  Rescans replay hit rows in O(1) instead of re-evaluating
the resource×rule matrix; only digests that changed ship to the device.

Persistence reuses the ``aotcache/store.py`` protocol: one snapshot
file per generation (``<fingerprint>-<rev>.vrows``), written
tmp-file + ``os.replace`` so readers never observe a partial snapshot,
framed with a magic + SHA-256 header so a torn or bit-flipped file is
deleted and reloaded as empty — a bad snapshot costs a rescan, never a
crash or a stale verdict.  Disk eviction is LRU by mtime against a
byte budget; the memory front is an entry-capped LRU.

Knobs:

* ``KTPU_VERDICT_CACHE`` — ``0``/``off`` disables the cache entirely
  (default on); the dense full scan is always the correctness oracle.
* ``KTPU_VERDICT_CACHE_DIR`` — snapshot directory (default
  ``<repo>/.cache/verdicts``; empty string keeps the cache
  memory-only).
* ``KTPU_VERDICT_CACHE_MAX`` — on-disk byte budget, default 256 MiB.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import tempfile
import threading
import time
import zlib
from collections import OrderedDict
from typing import Dict, List, Optional, Set, Tuple

from .. import faults
from .keys import engine_rev, generation_key

_log = logging.getLogger('kyverno.verdictcache')

#: snapshot framing: magic + 32-byte SHA-256 of the payload, then payload
_MAGIC = b'KTVC1\n'
_DIGEST_LEN = 32
_SUFFIX = '.vrows'

VERDICT_CACHE_HITS = 'kyverno_tpu_verdict_cache_hits_total'
VERDICT_CACHE_MISSES = 'kyverno_tpu_verdict_cache_misses_total'
VERDICT_CACHE_EVICTIONS = 'kyverno_tpu_verdict_cache_evictions_total'
RESCAN_ROWS_SCANNED = 'kyverno_tpu_rescan_rows_scanned'
RESCAN_ROWS_REPLAYED = 'kyverno_tpu_rescan_rows_replayed'

_DEFAULT_MAX_BYTES = 256 << 20
#: memory-front entry cap (rows are a few hundred bytes; 2M entries is
#: the 1M-Pod steady state with headroom, bounded without a knob)
_MEM_MAX_ENTRIES = 2_000_000


def _reg():
    from ..observability.metrics import global_registry
    return global_registry()


def publish_tick(scanned: int, replayed: int) -> None:
    """Per-tick rescan gauges: how many rows the last reconcile shipped
    to the device vs replayed from the cache (no-op unconfigured)."""
    reg = _reg()
    if reg is None:
        return
    reg.set_gauge(RESCAN_ROWS_SCANNED, float(scanned))
    reg.set_gauge(RESCAN_ROWS_REPLAYED, float(replayed))


def _env_enabled() -> bool:
    return os.environ.get('KTPU_VERDICT_CACHE', '1') not in ('0', 'off')


def _env_root() -> Optional[str]:
    root = os.environ.get(
        'KTPU_VERDICT_CACHE_DIR',
        os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))), '.cache', 'verdicts'))
    return root or None


def _env_max_bytes() -> int:
    try:
        return int(os.environ.get('KTPU_VERDICT_CACHE_MAX',
                                  str(_DEFAULT_MAX_BYTES)))
    except ValueError:
        return _DEFAULT_MAX_BYTES


class VerdictCache:
    """One generation of digest-keyed verdict rows.

    Row schema (JSON-stable): ``{'u': uid, 'r': [result dicts, no
    timestamp key], 's': summary, 'p': [policy indexes]}``.
    """

    def __init__(self, fingerprint: str, root: Optional[str] = None,
                 max_bytes: Optional[int] = None,
                 max_entries: int = _MEM_MAX_ENTRIES,
                 rev: Optional[str] = None):
        self.fingerprint = fingerprint
        self.rev = rev or engine_rev()
        self.max_bytes = _env_max_bytes() if max_bytes is None else max_bytes
        self.max_entries = max_entries
        self._lock = threading.Lock()
        self._rows: 'OrderedDict[str, dict]' = OrderedDict()
        self._by_uid: Dict[str, Set[str]] = {}
        self._dirty = False
        # local lookup outcome counters: benchmarks and the decision-
        # provenance cross-checks read them without a metrics registry
        self._hits = 0
        self._misses = 0
        if root is not None:
            try:
                os.makedirs(root, exist_ok=True)
            except OSError:
                root = None
        self.root = root
        self._load()

    @classmethod
    def from_env(cls, fingerprint: str) -> Optional['VerdictCache']:
        """The env-configured cache, or None when KTPU_VERDICT_CACHE is
        off (callers then run every row through the dense scan)."""
        if not _env_enabled():
            return None
        root = _env_root()
        if root is not None:
            try:
                os.makedirs(root, exist_ok=True)
            except OSError:
                root = None
        return cls(fingerprint, root=root)

    def path(self) -> Optional[str]:
        if self.root is None:
            return None
        return os.path.join(
            self.root, generation_key(self.fingerprint, self.rev) + _SUFFIX)

    def __len__(self) -> int:
        with self._lock:
            return len(self._rows)

    # -- lookups -----------------------------------------------------------

    def lookup(self, digest: str) -> Optional[dict]:
        """The cached row for one spec digest, or None (miss).  Hits
        refresh the memory-LRU position; both outcomes count."""
        with self._lock:
            row = self._rows.get(digest)
            if row is not None:
                self._rows.move_to_end(digest)
                self._hits += 1
            else:
                self._misses += 1
        reg = _reg()
        if reg is not None:
            if row is None:
                reg.inc(VERDICT_CACHE_MISSES)
            else:
                reg.inc(VERDICT_CACHE_HITS)
        return row

    def peek(self, digest: str) -> Optional[dict]:
        """``lookup`` without outcome accounting: composite caches
        (``verdictcache/partitioned.py``) probe every member generation
        per digest but count ONE hit or miss for the whole lookup —
        per-member counting would inflate the metrics by the partition
        count.  Refreshes the LRU position like a real hit."""
        with self._lock:
            row = self._rows.get(digest)
            if row is not None:
                self._rows.move_to_end(digest)
            return row

    # -- writes ------------------------------------------------------------

    def store(self, digest: str, uid: str, results: List[dict],
              summary: dict, policy_indexes: List[int]) -> None:
        """Record one scanned row.  ``results`` are the shared fused-path
        flyweight dicts — never mutated; the stored copies drop the
        ``timestamp`` key so replay can stamp the replaying tick."""
        row = {
            'u': uid,
            'r': [{k: v for k, v in r.items() if k != 'timestamp'}
                  for r in results],
            's': dict(summary),
            'p': list(policy_indexes),
        }
        evicted = 0
        with self._lock:
            old = self._rows.get(digest)
            if old is not None:
                self._unindex(digest, old)
            self._rows[digest] = row
            self._rows.move_to_end(digest)
            self._by_uid.setdefault(uid, set()).add(digest)
            while len(self._rows) > self.max_entries:
                d, dropped = self._rows.popitem(last=False)
                self._unindex(d, dropped)
                evicted += 1
            self._dirty = True
        reg = _reg()
        if evicted and reg is not None:
            reg.inc(VERDICT_CACHE_EVICTIONS, float(evicted))

    def invalidate_uid(self, uid: str) -> int:
        """Drop every entry recorded for ``uid`` (resource changed or
        deleted — a recreated resource with a stale uid must never
        replay old verdicts).  Returns the number dropped."""
        with self._lock:
            digests = self._by_uid.pop(uid, None)
            if not digests:
                return 0
            dropped = 0
            for d in digests:
                if self._rows.pop(d, None) is not None:
                    dropped += 1
            if dropped:
                self._dirty = True
        return dropped

    def _unindex(self, digest: str, row: dict) -> None:
        digests = self._by_uid.get(row.get('u', ''))
        if digests is not None:
            digests.discard(digest)
            if not digests:
                self._by_uid.pop(row.get('u', ''), None)

    # -- replay ------------------------------------------------------------

    def replay(self, row: dict, policies, ts: int
               ) -> Tuple[List[dict], dict, list]:
        """Row → the ``(results, summary, row_policies)`` triple
        ``scan_report_results`` would yield, stamped with ``ts`` (all
        results of one fused row share the tick's timestamp, so sort
        order is unaffected).

        Re-stamping is lazy: the stamped form is written back onto the
        row with the tick second it carries, so replays within the same
        second (fast reconcile loops over a large cache) return the
        shared dicts with zero per-result copies.  Stamped results are
        immutable from then on — a later tick with a different second
        builds fresh copies, never mutating what an earlier report may
        still reference."""
        if row.get('t') == ts:
            results = row['r']
        else:
            stamp = {'seconds': ts}
            results = [dict(r, timestamp=stamp) for r in row['r']]
            row['r'] = results
            row['t'] = ts
        return (results, dict(row['s']),
                [policies[p] for p in row['p'] if p < len(policies)])

    # -- persistence -------------------------------------------------------

    def _load(self) -> None:
        """Populate the memory front from this generation's snapshot.
        A short, unframed, digest-mismatched, or undecodable snapshot
        is deleted and loaded as empty — never raised."""
        path = self.path()
        if path is None or not os.path.exists(path):
            return
        try:
            # an injected verdict_snapshot_read fault degrades exactly
            # like an unreadable file: load as empty, rescan refills
            faults.check(faults.SITE_VERDICT_SNAPSHOT)
            with open(path, 'rb') as f:
                raw = f.read()
        except Exception:  # noqa: BLE001 - unreadable snapshot: empty
            return
        header = len(_MAGIC) + _DIGEST_LEN
        payload = raw[header:]
        if (len(raw) < header or not raw.startswith(_MAGIC) or
                hashlib.sha256(payload).digest() != raw[len(_MAGIC):header]):
            _log.warning('verdict snapshot %s corrupt; dropping',
                         os.path.basename(path))
            self._drop_file(path)
            return
        try:
            rows = json.loads(zlib.decompress(payload).decode())
        except Exception:  # noqa: BLE001 - stale codec decodes as empty
            self._drop_file(path)
            return
        with self._lock:
            for digest, row in rows.items():
                self._rows[digest] = row
                self._by_uid.setdefault(row.get('u', ''), set()).add(digest)
            while len(self._rows) > self.max_entries:
                d, dropped = self._rows.popitem(last=False)
                self._unindex(d, dropped)
        try:
            os.utime(path)  # disk LRU works off mtime, like the AOT store
        except OSError:
            pass

    @staticmethod
    def _drop_file(path: str) -> None:
        try:
            os.unlink(path)
        except OSError:
            pass

    def flush(self) -> bool:
        """Atomically persist this generation's rows (tmp + rename) when
        dirty, then evict older generation snapshots LRU-by-mtime to fit
        the byte budget.  Returns True when a snapshot was written."""
        path = self.path()
        if path is None:
            return False
        with self._lock:
            if not self._dirty:
                return False
            payload = zlib.compress(json.dumps(
                self._rows, separators=(',', ':')).encode(), 3)
            self._dirty = False
        framed = _MAGIC + hashlib.sha256(payload).digest() + payload
        try:
            fd, tmp = tempfile.mkstemp(dir=self.root, suffix='.tmp')
            try:
                with os.fdopen(fd, 'wb') as f:
                    f.write(framed)
                os.replace(tmp, path)
            except BaseException:
                self._drop_file(tmp)
                raise
        except OSError:
            return False
        self._evict_disk(keep=path)
        return True

    def _evict_disk(self, keep: str) -> None:
        """Drop oldest generation snapshots until the directory fits the
        budget (the just-written snapshot always survives)."""
        entries = []
        try:
            names = os.listdir(self.root)
        except OSError:
            return
        for name in names:
            p = os.path.join(self.root, name)
            if name.endswith('.tmp'):
                try:  # orphaned partial writes from killed processes
                    if time.time() - os.stat(p).st_mtime > 600:
                        os.unlink(p)
                except OSError:
                    pass
                continue
            if not name.endswith(_SUFFIX):
                continue
            try:
                st = os.stat(p)
            except OSError:
                continue
            entries.append((st.st_mtime, st.st_size, p))
        entries.sort()
        total = sum(sz for _, sz, _ in entries)
        evicted = 0
        for _, sz, p in entries:
            if total <= self.max_bytes or p == keep:
                continue
            try:
                os.unlink(p)
                total -= sz
                evicted += 1
            except OSError:
                pass
        reg = _reg()
        if evicted and reg is not None:
            reg.inc(VERDICT_CACHE_EVICTIONS, float(evicted))

    def stats(self) -> Dict[str, int]:
        path = self.path()
        size = 0
        if path is not None:
            try:
                size = os.stat(path).st_size
            except OSError:
                size = 0
        with self._lock:
            return {'entries': len(self._rows), 'snapshot_bytes': size,
                    'hits': self._hits, 'misses': self._misses}
