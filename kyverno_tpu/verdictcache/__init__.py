"""Digest-keyed verdict cache: incremental O(churn) background rescans.

Background scans re-evaluated every resource×rule cell on every
reconcile tick — the scaling cliff on the road to the 1M-Pod north
star.  This package carries verdict state across ticks keyed by what
actually changed (the compiler-first caching discipline of the
"Portable O(1) Autoregressive Caching" line of work): a rescan looks
every pending resource up by **spec digest × policy-set fingerprint ×
engine rev** and only ships the misses — the rows whose content,
policy set, or engine changed — to the device, replaying everything
else from the cache in O(1) per row.  Steady-state rescan cost tracks
churn (~1% of rows per tick), not cluster size.

* :mod:`.keys` — spec-digest canonicalization (volatile server-side
  metadata excluded; everything policies can see included) and the
  engine-rev digest that invalidates rows across code changes.
* :mod:`.store` — the cache itself: entry-capped in-memory LRU front,
  atomic digest-framed on-disk snapshots per cache generation (the
  ``aotcache/store.py`` protocol), uid-keyed invalidation, and the
  hit/miss/eviction + per-tick rescan telemetry.
* :mod:`.partitioned` — per-partition generations over the
  :mod:`kyverno_tpu.partition` plan (``KTPU_PARTITIONS>0``): a policy
  edit rolls only the touched partitions' generations, unchanged
  verdict subrows keep replaying, and partial hits re-scan rows
  against only the touched partitions' policies.

The dense full scan stays the cold path and the correctness oracle:
``KTPU_VERDICT_CACHE=off`` produces bit-identical reports (pinned by
``tests/test_verdict_cache.py``), and cached rows are only ever read
back under the exact (fingerprint, engine-rev) generation that wrote
them.  Integration lives in ``reports/controllers.py:
BackgroundScanController.reconcile`` — the cache is a filter stage in
front of ``BatchScanner``, with ``MetadataCache`` update/remove deltas
feeding invalidation.
"""

from .keys import (VERDICT_VERSION, engine_rev, generation_key,
                   spec_digest)
from .partitioned import (VERDICT_CACHE_PARTIAL_HITS,
                          PartitionedVerdictCache)
from .store import (RESCAN_ROWS_REPLAYED, RESCAN_ROWS_SCANNED,
                    VERDICT_CACHE_EVICTIONS, VERDICT_CACHE_HITS,
                    VERDICT_CACHE_MISSES, VerdictCache, publish_tick)

__all__ = [
    'VERDICT_VERSION', 'engine_rev', 'generation_key', 'spec_digest',
    'RESCAN_ROWS_REPLAYED', 'RESCAN_ROWS_SCANNED',
    'VERDICT_CACHE_EVICTIONS', 'VERDICT_CACHE_HITS',
    'VERDICT_CACHE_MISSES', 'VERDICT_CACHE_PARTIAL_HITS',
    'PartitionedVerdictCache', 'VerdictCache', 'publish_tick',
]
