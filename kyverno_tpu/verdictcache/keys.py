"""Cache-key derivation for persisted verdict rows.

A cached verdict row is replayable only while three things hold: the
resource content the policies evaluated is unchanged (**spec digest**),
the policy set is unchanged (**policy-set fingerprint**, shared with the
AOT cache: ``aotcache/keys.py:policy_set_fingerprint``), and the engine
that produced the row still has the same semantics (**engine rev**).
The digest deliberately covers the *whole* resource document — match/
exclude, patterns, and JMESPath programs may reference any field,
including ``metadata.uid`` — minus the server-side bookkeeping fields
that change on every write without changing what policies see
(``managedFields``, ``resourceVersion``, ``generation``,
``creationTimestamp``).  Keeping ``uid`` in the digest means a
deleted-then-recreated resource never aliases its predecessor's entries
even before the uid-keyed invalidation hook drops them.
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Optional

#: bump to invalidate every persisted verdict row (snapshot format or
#: engine-semantics changes not captured by the source digests below)
VERDICT_VERSION = 1

#: metadata fields the API server rewrites on every update without
#: changing anything a policy can meaningfully evaluate — excluded from
#: the spec digest so a no-op resync never invalidates a row
VOLATILE_METADATA = ('managedFields', 'resourceVersion', 'generation',
                     'creationTimestamp', 'selfLink')

_ENGINE_REV: Optional[str] = None


def spec_digest(resource: dict) -> str:
    """Stable digest of one resource's policy-visible content.  Key
    order never matters (canonical JSON); the volatile metadata fields
    never matter; any other change — spec, labels, annotations, status,
    uid — produces a different digest (a changed resource must miss)."""
    meta = resource.get('metadata')
    if isinstance(meta, dict) and any(k in meta for k in VOLATILE_METADATA):
        resource = dict(resource)
        resource['metadata'] = {k: v for k, v in meta.items()
                                if k not in VOLATILE_METADATA}
    payload = json.dumps(resource, sort_keys=True, separators=(',', ':'),
                         default=str)
    return hashlib.sha256(payload.encode()).hexdigest()[:24]


def engine_rev() -> str:
    """Digest of the sources whose semantics are baked into a verdict
    row: the compiler/evaluator digest the AOT cache already maintains
    (``aotcache/keys.py:source_digest``) plus the scan-assembly and
    report-mapping layers that turn device cells into result dicts.
    Any change to them invalidates every persisted row — a stale row
    from an older engine can never replay."""
    global _ENGINE_REV
    if _ENGINE_REV is None:
        from ..aotcache.keys import source_digest
        h = hashlib.sha256()
        h.update(source_digest().encode())
        base = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        for rel in ('compiler/scan.py', 'reports/results.py'):
            try:
                with open(os.path.join(base, rel), 'rb') as f:
                    h.update(f.read())
            except OSError:
                h.update(rel.encode())
        h.update(str(VERDICT_VERSION).encode())
        _ENGINE_REV = h.hexdigest()[:16]
    return _ENGINE_REV


def generation_key(fingerprint: str, rev: Optional[str] = None) -> str:
    """One cache generation = one (policy set, engine rev) pair; a
    policy-set change flushes by switching generations."""
    return f'{fingerprint}-{rev or engine_rev()}'
