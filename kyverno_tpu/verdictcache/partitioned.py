"""Per-partition verdict-cache generations: verdicts survive policy
churn.

The monolithic :class:`~.store.VerdictCache` keys its single generation
by the whole-set fingerprint, so editing ONE policy invalidates every
cached row and the next reconcile re-scans the world.  This composite
keeps one :class:`VerdictCache` generation per partition of the
:mod:`kyverno_tpu.partition` plan, keyed by the **partition**
fingerprint: a policy edit only rolls the generations of the touched
partitions, and the unchanged partitions' rows keep replaying.

Row splitting is exact because the fused report contract is
per-policy: each result dict names its policy
(``results.py:_rule_result`` sets ``result['policy']`` to the policy
key), the summary is a pure bucket count of the results
(``results.py:calculate_summary``), and the contributing-policy
indexes partition by plan assignment.  A stored subrow keeps
partition-**local** policy indexes — the partition fingerprint pins
the member list and its order, so local indexes stay stable while
global indexes shift under add/delete churn elsewhere in the set.

Composition merges the per-partition sorted result lists with the
``sort_report_results`` key (fused rows are device-only when cacheable
— ``controllers.py:_verdicts_cacheable`` — and arrive pre-sorted by
``(policy, rule)``; all results of one row share the tick timestamp),
sums the summaries bucket-wise, and unions the local indexes back to
global.  ``KTPU_PARTITIONS=0`` keeps the monolithic cache as the
bit-identity oracle (pinned by ``tests/test_partition.py``).

The **partial hit** is the churn payoff: when only touched partitions
miss, :meth:`partial` hands the cached unchanged subrows to the
controller, which re-scans the row against a scanner scoped to the
touched partitions' member policies and :meth:`merge_scoped` composes
+ stores the result — O(touched policies) device work per row instead
of O(set).
"""

from __future__ import annotations

import heapq
import os
import threading
from typing import Dict, FrozenSet, List, Optional, Tuple

from .store import (VERDICT_CACHE_HITS, VERDICT_CACHE_MISSES, VerdictCache,
                    _env_enabled, _env_root)

VERDICT_CACHE_PARTIAL_HITS = 'kyverno_tpu_verdict_cache_partial_hits_total'

_EMPTY_SUMMARY = {'pass': 0, 'fail': 0, 'warn': 0, 'error': 0, 'skip': 0}


def _reg():
    from ..observability.metrics import global_registry
    return global_registry()


def _sort_key(r: dict) -> Tuple[str, str]:
    # the fused-row restriction of results.py:sort_report_results: rows
    # are per-resource (no 'resources' lists) and share one timestamp,
    # so only (policy, rule) discriminates
    return (r.get('policy', ''), r.get('rule', ''))


class PartitionedVerdictCache:
    """One :class:`VerdictCache` generation per plan partition, exposed
    behind the monolithic cache's interface (``lookup`` / ``replay`` /
    ``store`` / ``invalidate_uid`` / ``flush`` / ``stats``) plus the
    scoped-rescan pair ``partial`` / ``merge_scoped``.

    Hit/miss accounting is per whole-row lookup (sub-generations are
    probed with the uncounted ``peek``), so the
    ``kyverno_tpu_verdict_cache_*`` series stay comparable with the
    monolithic cache regardless of the partition count.
    """

    def __init__(self, plan, policies, root: Optional[str] = None,
                 max_bytes: Optional[int] = None,
                 prev: Optional['PartitionedVerdictCache'] = None):
        self.plan = plan
        self.root = root
        self._lock = threading.Lock()
        self._hits = 0
        self._misses = 0
        self._partials = 0
        self._parts: Dict[int, VerdictCache] = {}
        self._l2g: Dict[int, List[int]] = {}
        self._g2l: Dict[int, Dict[int, int]] = {}
        self._key_pid: Dict[str, int] = {}
        # carry the predecessor's sub-caches for partitions whose
        # fingerprint survived the churn: in memory-only mode this IS
        # the replay-across-churn property (there is no snapshot to
        # reload); with a root it just skips a redundant reload
        prev_by_fp: Dict[str, VerdictCache] = {}
        if prev is not None:
            for sub in prev._parts.values():
                prev_by_fp[sub.fingerprint] = sub
        for part in plan.partitions:
            sub = prev_by_fp.get(part.fingerprint)
            if sub is None or sub.root != root:
                sub = VerdictCache(part.fingerprint, root=root,
                                   max_bytes=max_bytes)
            self._parts[part.pid] = sub
            l2g = list(part.policy_indices)
            self._l2g[part.pid] = l2g
            self._g2l[part.pid] = {g: loc for loc, g in enumerate(l2g)}
            for g in l2g:
                self._key_pid[policies[g].get_kind_and_name()] = part.pid

    @classmethod
    def from_env(cls, plan, policies,
                 prev: Optional['PartitionedVerdictCache'] = None
                 ) -> Optional['PartitionedVerdictCache']:
        """Env-gated exactly like :meth:`VerdictCache.from_env` (same
        ``KTPU_VERDICT_CACHE`` / ``_DIR`` / ``_MAX`` knobs — partition
        generations share the snapshot directory and byte budget)."""
        if not _env_enabled():
            return None
        root = _env_root()
        if root is not None:
            try:
                os.makedirs(root, exist_ok=True)
            except OSError:
                root = None
        return cls(plan, policies, root=root, prev=prev)

    def __len__(self) -> int:
        # sub-generations store in lockstep; LRU/invalidations can skew
        # them, so the largest is the honest upper bound
        return max((len(s) for s in self._parts.values()), default=0)

    # -- lookups -----------------------------------------------------------

    def lookup(self, digest: str) -> Optional[dict]:
        """The composed whole-row for one spec digest, or None.  A hit
        requires EVERY partition generation to hold the digest —
        otherwise the split would silently drop the missing partition's
        results.  Counts one hit or miss total."""
        subs: Dict[int, dict] = {}
        missed = False
        for pid, sub in self._parts.items():
            row = sub.peek(digest)
            if row is None:
                missed = True
                break
            subs[pid] = row
        with self._lock:
            if missed:
                self._misses += 1
            else:
                self._hits += 1
        reg = _reg()
        if reg is not None:
            if missed:
                reg.inc(VERDICT_CACHE_MISSES)
            else:
                reg.inc(VERDICT_CACHE_HITS)
        return None if missed else self._compose(subs)

    def partial(self, digest: str, scoped_pids: FrozenSet[int]
                ) -> Optional[Dict[int, dict]]:
        """After a full-lookup miss: the cached subrows of every
        partition OUTSIDE ``scoped_pids`` — the unchanged half of a
        scoped rescan — or None when any of those also misses (the row
        then takes the dense path).  Uncounted against hit/miss; counts
        on the partial-hit series instead."""
        subs: Dict[int, dict] = {}
        for pid, sub in self._parts.items():
            if pid in scoped_pids:
                continue
            row = sub.peek(digest)
            if row is None:
                return None
            subs[pid] = row
        with self._lock:
            self._partials += 1
        reg = _reg()
        if reg is not None:
            reg.inc(VERDICT_CACHE_PARTIAL_HITS)
        return subs

    def _compose(self, subs: Dict[int, dict]) -> dict:
        """Subrows → one whole-row in the monolithic row schema.  The
        composed row is ephemeral (rebuilt per lookup); ``replay``'s
        lazy stamping writes onto it, never onto the stored subrows."""
        lists = [subs[pid]['r'] for pid in sorted(subs) if subs[pid]['r']]
        if len(lists) == 1:
            merged = list(lists[0])
        else:
            merged = list(heapq.merge(*lists, key=_sort_key))
        summary = dict(_EMPTY_SUMMARY)
        gidx: List[int] = []
        uid = ''
        for pid in sorted(subs):
            row = subs[pid]
            uid = row.get('u') or uid
            for k, v in row['s'].items():
                summary[k] = summary.get(k, 0) + v
            l2g = self._l2g[pid]
            gidx.extend(l2g[loc] for loc in row['p'] if loc < len(l2g))
        return {'u': uid, 'r': merged, 's': summary, 'p': sorted(gidx)}

    # -- replay ------------------------------------------------------------

    def replay(self, row: dict, policies, ts: int
               ) -> Tuple[List[dict], dict, list]:
        """Identical contract to :meth:`VerdictCache.replay`; operates
        on the composed row, so stored subrows stay timestamp-free."""
        if row.get('t') == ts:
            results = row['r']
        else:
            stamp = {'seconds': ts}
            results = [dict(r, timestamp=stamp) for r in row['r']]
            row['r'] = results
            row['t'] = ts
        return (results, dict(row['s']),
                [policies[p] for p in row['p'] if p < len(policies)])

    # -- writes ------------------------------------------------------------

    def store(self, digest: str, uid: str, results: List[dict],
              summary: dict, policy_indexes: List[int]) -> None:
        """Split one whole-row across every partition generation.  Every
        partition stores a subrow — an empty one when none of its
        policies contributed — so a later lookup can tell "partition
        didn't match" from "partition's row was never scanned"."""
        del summary  # recomputed per partition: exact bucket counts
        self._store_split(digest, uid, results, policy_indexes,
                          list(self._parts))

    def _store_split(self, digest: str, uid: str, results: List[dict],
                     global_indexes, pids: List[int]) -> None:
        by_pid: Dict[int, List[dict]] = {pid: [] for pid in pids}
        for r in results:
            target = by_pid.get(self._key_pid.get(r.get('policy', '')))
            if target is not None:
                target.append(r)
        for pid in pids:
            sub_results = by_pid[pid]
            summary = dict(_EMPTY_SUMMARY)
            for r in sub_results:
                s = r.get('result', '')
                if s in summary:
                    summary[s] += 1
            g2l = self._g2l[pid]
            self._parts[pid].store(
                digest, uid, sub_results, summary,
                [g2l[g] for g in global_indexes if g in g2l])

    def merge_scoped(self, digest: str, uid: str, cached: Dict[int, dict],
                     results: List[dict], summary: dict,
                     scoped_global_indexes: List[int], ts: int
                     ) -> Tuple[List[dict], dict, List[int]]:
        """Complete a partial hit: ``results`` came from a scanner
        scoped to the partitions NOT in ``cached`` (the touched ones).
        Stores their split — the digest becomes a full hit from here on
        — and returns the whole-row ``(results, summary,
        global_policy_indexes)`` composed from cache + scoped scan."""
        del summary
        scoped_pids = [pid for pid in self._parts if pid not in cached]
        self._store_split(digest, uid, results, scoped_global_indexes,
                          scoped_pids)
        stamp = {'seconds': ts}
        lists = []
        for pid in sorted(cached):
            row = cached[pid]
            if row['r']:
                lists.append([dict(r, timestamp=stamp) for r in row['r']])
        if results:
            lists.append(list(results))
        merged = list(heapq.merge(*lists, key=_sort_key)) if lists else []
        msum = dict(_EMPTY_SUMMARY)
        for r in results:
            s = r.get('result', '')
            if s in msum:
                msum[s] += 1
        gidx = set(scoped_global_indexes)
        for pid, row in cached.items():
            for k, v in row['s'].items():
                msum[k] = msum.get(k, 0) + v
            l2g = self._l2g[pid]
            gidx.update(l2g[loc] for loc in row['p'] if loc < len(l2g))
        return merged, msum, sorted(gidx)

    def invalidate_uid(self, uid: str) -> int:
        return sum(sub.invalidate_uid(uid)
                   for sub in self._parts.values())

    # -- persistence -------------------------------------------------------

    def flush(self) -> bool:
        wrote = False
        for sub in self._parts.values():
            wrote = sub.flush() or wrote
        return wrote

    def stats(self) -> Dict[str, int]:
        entries = len(self)
        snapshot = sum(s.stats()['snapshot_bytes']
                       for s in self._parts.values())
        with self._lock:
            return {'entries': entries, 'snapshot_bytes': snapshot,
                    'partitions': len(self._parts),
                    'hits': self._hits, 'misses': self._misses,
                    'partial_hits': self._partials}
