"""Autogen: rewrite Pod rules for pod controllers.

Re-implements the reference's autogen expansion
(reference: pkg/autogen/autogen.go:280 ComputeRules, rule.go):

* Pod rules are cloned as ``autogen-<name>`` rules targeting
  DaemonSet/Deployment/Job/StatefulSet/ReplicaSet/ReplicationController with
  patterns re-rooted under ``spec.template`` and as ``autogen-cronjob-<name>``
  rules re-rooted under ``spec.jobTemplate.spec.template``
* controlled by the ``pod-policies.kyverno.io/autogen-controllers`` annotation
* JMESPath references inside messages/variables are shifted the same way the
  reference does (string replacement on the serialized rule).
"""

from __future__ import annotations

import copy
import json
import re
from typing import Any, List, Optional, Tuple

from ..api.policy import POD_CONTROLLERS_ANNOTATION, Policy
from ..api.unstructured import contains_kind

POD_CONTROLLER_CRONJOB = 'CronJob'
POD_CONTROLLERS = 'DaemonSet,Deployment,Job,StatefulSet,ReplicaSet,ReplicationController,CronJob'
_POD_CONTROLLERS_SET = set(POD_CONTROLLERS.split(',')) | {'Pod'}
_NON_CRONJOB = 'DaemonSet,Deployment,Job,StatefulSet,ReplicaSet,ReplicationController'


def _is_kind_other_than_pod(kinds: List[str]) -> bool:
    return len(kinds) > 1 and contains_kind(kinds, 'Pod')


def _check_autogen_support(state: dict, *subjects: dict) -> bool:
    for subject in subjects:
        subject = subject or {}
        if (subject.get('name') or subject.get('names') or
                subject.get('selector') is not None or
                subject.get('annotations') is not None or
                _is_kind_other_than_pod(subject.get('kinds') or [])):
            return False
        state['needed'] = state['needed'] or any(
            k in _POD_CONTROLLERS_SET for k in subject.get('kinds') or [])
    return True


def _strip_cronjob(controllers: str) -> str:
    out = [c for c in controllers.split(',') if c != POD_CONTROLLER_CRONJOB]
    return ','.join(out)


def can_auto_gen(spec: dict) -> Tuple[bool, str]:
    """reference: pkg/autogen/autogen.go:70 CanAutoGen"""
    state = {'needed': False}
    for rule in spec.get('rules') or []:
        mutate = rule.get('mutate') or {}
        if mutate.get('patchesJson6902') or rule.get('generate'):
            return False, 'none'
        match = rule.get('match') or {}
        exclude = rule.get('exclude') or {}
        if not _check_autogen_support(state, match.get('resources') or {},
                                      exclude.get('resources') or {}):
            return False, ''
        for block in (match.get('any') or []) + (match.get('all') or []) + \
                     (exclude.get('any') or []) + (exclude.get('all') or []):
            if not _check_autogen_support(state, block.get('resources') or {}):
                return False, ''
    if not state['needed']:
        return False, ''
    return True, POD_CONTROLLERS


def get_requested_controllers(metadata: dict) -> Optional[List[str]]:
    annotations = (metadata or {}).get('annotations') or {}
    controllers = annotations.get(POD_CONTROLLERS_ANNOTATION)
    if not controllers:
        return None
    if controllers == 'none':
        return []
    return controllers.split(',')


def get_supported_controllers(spec: dict) -> Optional[List[str]]:
    apply_autogen, controllers = can_auto_gen(spec)
    if not apply_autogen or controllers == 'none':
        return None
    return controllers.split(',')


def get_controllers(metadata: dict, spec: dict):
    """Return (requested, supported, activated)
    (reference: pkg/autogen/autogen.go:139 GetControllers)."""
    supported = get_supported_controllers(spec) or []
    requested = get_requested_controllers(metadata)
    if requested is None:
        return requested, supported, supported
    activated = [c for c in supported if c in requested]
    return requested, supported, activated


def compute_rules(policy: Policy) -> List[dict]:
    """Expand a policy's rules with autogen rules
    (reference: pkg/autogen/autogen.go:284 computeRules)."""
    spec = policy.spec
    apply_autogen, desired = can_auto_gen(spec)
    if not apply_autogen:
        desired = 'none'
    actual = policy.annotations.get(POD_CONTROLLERS_ANNOTATION)
    if actual is None or not apply_autogen:
        actual = desired
    if actual == 'none':
        return copy.deepcopy(spec.get('rules') or [])
    gen_rules = _generate_rules(copy.deepcopy(spec), actual)
    if not gen_rules:
        return copy.deepcopy(spec.get('rules') or [])
    out = [copy.deepcopy(r) for r in spec.get('rules') or []
           if not _is_autogen_name(r.get('name', ''))]
    out.extend(gen_rules)
    return out


def _generate_rules(spec: dict, controllers: str) -> List[dict]:
    rules = []
    for rule in spec.get('rules') or []:
        gen = _generate_rule_for_controllers(rule, _strip_cronjob(controllers))
        if gen is not None:
            rules.append(_convert_rule(gen, 'Pod'))
        cron = _generate_cronjob_rule(rule, controllers)
        if cron is not None:
            rules.append(_convert_rule(cron, 'Cronjob'))
    return rules


def _is_autogen_name(name: str) -> bool:
    return name.startswith('autogen-')


def _autogen_rule_name(prefix: str, name: str) -> str:
    name = f'{prefix}-{name}'
    return name[:63]


def _replace_kinds_in_filters(filters: List[dict], match: str,
                              kinds: List[str]) -> List[dict]:
    out = copy.deepcopy(filters)
    for f in out:
        res = f.get('resources') or {}
        if contains_kind(res.get('kinds') or [], match):
            res['kinds'] = list(kinds)
    return out


def _generate_rule_for_controllers(rule: dict, controllers: str) -> Optional[dict]:
    # reference: pkg/autogen/rule.go:228
    if _is_autogen_name(rule.get('name', '')) or controllers == '':
        return None
    match = rule.get('match') or {}
    exclude = rule.get('exclude') or {}
    match_kinds = _get_kinds(match)
    exclude_kinds = _get_kinds(exclude)
    if not contains_kind(match_kinds, 'Pod') or \
            (exclude_kinds and not contains_kind(exclude_kinds, 'Pod')):
        return None
    valid = [c for c in controllers.split(',')
             if c in _NON_CRONJOB.split(',')] if controllers not in ('all', 'none') else []
    if controllers == 'all':
        controllers = _NON_CRONJOB
    elif valid:
        controllers = ','.join(valid)
    return _generate_rule(
        _autogen_rule_name('autogen', rule.get('name', '')),
        rule, 'template', 'spec/template', controllers.split(','), 'Pod')


def _generate_cronjob_rule(rule: dict, controllers: str) -> Optional[dict]:
    # reference: pkg/autogen/rule.go:281
    if POD_CONTROLLER_CRONJOB not in controllers and 'all' not in controllers:
        return None
    base = _generate_rule_for_controllers(rule, controllers)
    if base is None:
        return None
    return _generate_rule(
        _autogen_rule_name('autogen-cronjob', rule.get('name', '')),
        base, 'jobTemplate', 'spec/jobTemplate/spec/template',
        [POD_CONTROLLER_CRONJOB], 'Job')


def _get_kinds(match: dict) -> List[str]:
    kinds = list((match.get('resources') or {}).get('kinds') or [])
    for f in (match.get('any') or []) + (match.get('all') or []):
        kinds.extend((f.get('resources') or {}).get('kinds') or [])
    return kinds


def _generate_rule(name: str, rule: dict, tpl_key: str, shift: str,
                   kinds: List[str], filter_match: str) -> Optional[dict]:
    # reference: pkg/autogen/rule.go:73 generateRule
    rule = copy.deepcopy(rule)
    rule['name'] = name
    match = rule.get('match') or {}
    if match.get('any'):
        match['any'] = _replace_kinds_in_filters(match['any'], filter_match, kinds)
    elif match.get('all'):
        match['all'] = _replace_kinds_in_filters(match['all'], filter_match, kinds)
    else:
        match.setdefault('resources', {})['kinds'] = list(kinds)
    rule['match'] = match
    exclude = rule.get('exclude') or {}
    if exclude.get('any'):
        exclude['any'] = _replace_kinds_in_filters(exclude['any'], filter_match, kinds)
        rule['exclude'] = exclude
    elif exclude.get('all'):
        exclude['all'] = _replace_kinds_in_filters(exclude['all'], filter_match, kinds)
        rule['exclude'] = exclude
    elif (exclude.get('resources') or {}).get('kinds'):
        exclude['resources']['kinds'] = list(kinds)
        rule['exclude'] = exclude

    mutate = rule.get('mutate') or {}
    validate = rule.get('validate') or {}

    if mutate.get('patchStrategicMerge') is not None:
        rule['mutate'] = {'patchStrategicMerge': {
            'spec': {tpl_key: mutate['patchStrategicMerge']}}}
        return rule
    if mutate.get('foreach'):
        new_foreach = []
        for fe in mutate['foreach']:
            entry = {k: v for k, v in fe.items()
                     if k in ('list', 'context', 'preconditions')}
            entry['patchStrategicMerge'] = {
                'spec': {tpl_key: fe.get('patchStrategicMerge')}}
            new_foreach.append(entry)
        rule['mutate'] = {'foreach': new_foreach}
        return rule
    if validate.get('pattern') is not None:
        rule['validate'] = {
            'message': find_and_shift_references(
                validate.get('message', ''), shift, 'pattern'),
            'pattern': {'spec': {tpl_key: validate['pattern']}},
        }
        return rule
    if validate.get('deny') is not None:
        rule['validate'] = {
            'message': find_and_shift_references(
                validate.get('message', ''), shift, 'deny'),
            'deny': validate['deny'],
        }
        return rule
    if validate.get('podSecurity') is not None:
        rule['validate'] = {
            'message': find_and_shift_references(
                validate.get('message', ''), shift, 'podSecurity'),
            'podSecurity': copy.deepcopy(validate['podSecurity']),
        }
        return rule
    if validate.get('anyPattern') is not None:
        patterns = [{'spec': {tpl_key: p}} for p in validate['anyPattern']]
        rule['validate'] = {
            'message': find_and_shift_references(
                validate.get('message', ''), shift, 'anyPattern'),
            'anyPattern': patterns,
        }
        return rule
    if validate.get('foreach'):
        rule['validate'] = {
            'message': find_and_shift_references(
                validate.get('message', ''), shift, 'pattern'),
            'foreach': copy.deepcopy(validate['foreach']),
        }
        return rule
    if rule.get('verifyImages'):
        return rule
    return None


def _convert_rule(rule: dict, kind: str) -> dict:
    """Re-root JMESPath references via JSON string replacement
    (reference: pkg/autogen/autogen.go:238 convertRule)."""
    raw = json.dumps(rule)
    validate = rule.get('validate') or {}
    if validate.get('podSecurity') is not None:
        if kind == 'Pod':
            raw = raw.replace('"restrictedField":"spec',
                              '"restrictedField":"spec.template.spec')
        if kind == 'Cronjob':
            raw = raw.replace('"restrictedField":"spec',
                              '"restrictedField":"spec.jobTemplate.spec.template.spec')
        raw = raw.replace('metadata', 'spec.template.metadata')
    else:
        if kind == 'Pod':
            raw = raw.replace('request.object.spec',
                              'request.object.spec.template.spec')
        if kind == 'Cronjob':
            raw = raw.replace('request.object.spec',
                              'request.object.spec.jobTemplate.spec.template.spec')
        raw = raw.replace('request.object.metadata',
                          'request.object.spec.template.metadata')
    return json.loads(raw)


_REFERENCES_RE = re.compile(r'\$\(.[^\ ]*\)')


def find_and_shift_references(value: str, shift: str, pivot: str) -> str:
    """Shift $(...) references past the re-rooted prefix
    (reference: pkg/engine/variables/vars.go:517 FindAndShiftReferences)."""
    if not value:
        return value
    for m in list(_REFERENCES_RE.finditer(value)):
        reference = m.group(0)
        idx = reference.find(pivot)
        if idx == -1:
            continue
        local_pivot = pivot
        if pivot == 'anyPattern':
            rule_index = reference[idx + len(pivot) + 1:].split('/')[0]
            local_pivot = f'{pivot}/{rule_index}'
        shifted = reference.replace(local_pivot, f'{local_pivot}/{shift}')
        value = value.replace(reference, shifted, 1)
    return value
