"""Cleanup-controller daemon (reference: cmd/cleanup-controller/main.go):
evaluates CleanupPolicy schedules and deletes matching resources."""

from __future__ import annotations

from typing import List, Optional

from ..controllers.cleanup import CleanupController
from ..controllers.leaderelection import mesh_is_leader
from .internal import Setup, base_parser


class CleanupDaemon:
    def __init__(self, setup: Setup):
        self.setup = setup
        self.controller = CleanupController(setup.client)

    def tick(self) -> None:
        if not mesh_is_leader():
            return
        for kind in ('ClusterCleanupPolicy', 'CleanupPolicy'):
            try:
                for doc in self.setup.client.list_resource(
                        'kyverno.io/v2alpha1', kind, '', None):
                    self.controller.set_policy(doc)
            except Exception:  # noqa: BLE001
                continue
        self.controller.tick()

    def run(self) -> None:
        self.setup.install_signal_handlers()
        self.setup.run_until_stopped(self.tick, interval=10.0)


def main(args: Optional[List[str]] = None) -> int:
    setup = Setup('kyverno-cleanup-controller', args,
                  base_parser('kyverno-cleanup-controller'))
    CleanupDaemon(setup).run()
    return 0


if __name__ == '__main__':
    raise SystemExit(main())
