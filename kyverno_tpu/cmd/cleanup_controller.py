"""Cleanup-controller daemon (reference: cmd/cleanup-controller/main.go):
reconciles a CronJob CR per CleanupPolicy and serves the ``/cleanup``
HTTP endpoint the CronJobs call back (reference:
cmd/cleanup-controller/handlers/cleanup/handlers.go); the in-process
cron tick additionally runs due policies directly so deletions happen
even without an external job runner."""

from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import List, Optional
from urllib.parse import parse_qs, urlparse

from ..controllers.cleanup import (CleanupController,
                                   validate_cleanup_admission)
from ..controllers.leaderelection import mesh_is_leader
from .internal import Setup, base_parser


class CleanupHTTPServer:
    """Serves GET /cleanup?policy=<ns/name>
    (reference: cmd/cleanup-controller/handlers/cleanup) and POST
    /validate — CleanupPolicy admission with the delete/list permission
    pre-flight (reference: cmd/cleanup-controller/handlers/admission/
    policy.go + pkg/validation/cleanuppolicy/validate.go)."""

    def __init__(self, controller: CleanupController, port: int = 0,
                 host: str = '', certfile: Optional[str] = None,
                 keyfile: Optional[str] = None):
        # default bind is all interfaces: the CronJobs this controller
        # reconciles call back via the cluster Service address, which a
        # localhost-only listener could never serve
        self.controller = controller
        self.host = host
        self.port = port
        self.certfile = certfile
        self.keyfile = keyfile
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    @property
    def scheme(self) -> str:
        return 'https' if self.certfile else 'http'

    def start(self) -> int:
        controller = self.controller

        class _Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # noqa: A003 - quiet
                pass

            def do_POST(self):  # noqa: N802
                import json
                if urlparse(self.path).path != '/validate':
                    self.send_response(404)
                    self.end_headers()
                    return
                n = int(self.headers.get('Content-Length') or 0)
                review = json.loads(self.rfile.read(n) or b'{}')
                request = review.get('request') or {}
                resp = validate_cleanup_admission(request,
                                                  controller.client)
                body = json.dumps({
                    'apiVersion': 'admission.k8s.io/v1',
                    'kind': 'AdmissionReview',
                    'response': resp}).encode()
                self.send_response(200)
                self.send_header('Content-Type', 'application/json')
                self.send_header('Content-Length', str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802
                parsed = urlparse(self.path)
                if parsed.path != CleanupController.CLEANUP_SERVICE_PATH:
                    self.send_response(404)
                    self.end_headers()
                    return
                policy = parse_qs(parsed.query).get('policy', [''])[0]
                try:
                    deleted = controller.handle_cleanup_request(policy)
                except KeyError:
                    self.send_response(404)
                    self.end_headers()
                    return
                body = f'cleaned {len(deleted)} resources\n'.encode()
                self.send_response(200)
                self.send_header('Content-Length', str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self._httpd = ThreadingHTTPServer((self.host, self.port), _Handler)
        if self.certfile:
            import ssl
            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            ctx.load_cert_chain(self.certfile, self.keyfile)
            self._httpd.socket = ctx.wrap_socket(self._httpd.socket,
                                                 server_side=True)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name='ktpu-cleanup', daemon=True)
        self._thread.start()
        return self.port

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None


class CleanupDaemon:
    def __init__(self, setup: Setup, http_port: int = 0,
                 certfile: Optional[str] = None,
                 keyfile: Optional[str] = None):
        self.setup = setup
        self.controller = CleanupController(setup.client)
        self.server = CleanupHTTPServer(self.controller, http_port,
                                        certfile=certfile, keyfile=keyfile)

    def sync_policies(self) -> None:
        seen = set()
        all_listed = True
        for kind in ('ClusterCleanupPolicy', 'CleanupPolicy'):
            try:
                for doc in self.setup.client.list_resource(
                        'kyverno.io/v2alpha1', kind, '', None):
                    self.controller.set_policy(doc)
                    seen.add(CleanupController._key(doc))
            except Exception:  # noqa: BLE001
                # a transient list failure must NOT cascade into pruning
                # (and hence CronJob deletion) of this kind's policies
                all_listed = False
        if all_listed:
            self.controller.retain_policies(seen)

    def tick(self) -> None:
        if not mesh_is_leader():
            return
        self.sync_policies()
        # the callback URL's scheme must match how the server actually
        # serves, or every reconciled CronJob would fail its curl forever
        ns = self.setup.options.namespace
        self.controller.reconcile_cronjobs(
            ns, service=f'{self.server.scheme}://cleanup-controller.'
                        f'{ns}.svc')
        self.controller.tick()

    def run(self) -> None:
        self.server.start()
        self.setup.install_signal_handlers()
        self.setup.run_until_stopped(self.tick, interval=10.0)
        self.server.stop()


def main(args: Optional[List[str]] = None) -> int:
    setup = Setup('kyverno-cleanup-controller', args,
                  base_parser('kyverno-cleanup-controller'))
    CleanupDaemon(setup).run()
    return 0


if __name__ == '__main__':
    raise SystemExit(main())
