"""Deployable binaries (L6): admission controller, background
controller, reports controller, cleanup controller, init job
(reference: cmd/kyverno, cmd/background-controller,
cmd/reports-controller, cmd/cleanup-controller, cmd/kyverno-init)."""
