"""Admission-controller daemon (reference: cmd/kyverno/main.go:210).

Wires cert renewal, the policy cache, the webhook server, and the
leader-only reconcilers (webhook configurations, lease watchdog)."""

from __future__ import annotations

import tempfile
from typing import List, Optional

from ..api.policy import Policy
from ..controllers.leaderelection import LeaderElector, mesh_is_leader
from ..controllers.webhook import WebhookConfigReconciler
from ..policycache.cache import Cache
from ..tls.certs import CertRenewer
from ..webhooks.handlers import ResourceHandlers
from ..webhooks.server import WebhookServer
from .internal import Setup, base_parser


class AdmissionController:
    def __init__(self, setup: Setup, port: int = 9443, tls: bool = True):
        self.setup = setup
        self.cache = Cache()
        self.cert_renewer = CertRenewer(setup.client,
                                        setup.options.namespace)
        # the CA/pair secrets are always provisioned — webhook configs
        # need the CA bundle even when serving plain HTTP in tests
        _ca, cert, key = self.cert_renewer.renew()
        certfile = keyfile = None
        if tls:
            self._cert_tmp = tempfile.NamedTemporaryFile(suffix='.crt')
            self._key_tmp = tempfile.NamedTemporaryFile(suffix='.key')
            self._cert_tmp.write(cert)
            self._cert_tmp.flush()
            self._key_tmp.write(key)
            self._key_tmp.flush()
            certfile, keyfile = self._cert_tmp.name, self._key_tmp.name
        self.handlers = ResourceHandlers(
            self.cache, configuration=setup.configuration,
            ur_sink=self._create_ur)
        # CRD schema ingestion feeding the mutation schema checks
        # (reference: pkg/controllers/openapi/controller.go:148)
        from ..controllers.openapi import OpenAPIController
        self.openapi_controller = OpenAPIController(
            setup.client, self.handlers.openapi_manager)
        self.openapi_controller.reconcile()
        # policy change/rule-info metrics driven by policy events
        # (reference: pkg/controllers/metrics/policy/controller.go:155)
        from ..controllers.policymetrics import PolicyMetricsController
        self.policy_metrics = PolicyMetricsController(
            setup.client, setup.metrics)
        self.server = WebhookServer(
            self.handlers, configuration=setup.configuration,
            port=port, certfile=certfile, keyfile=keyfile)
        self.reconciler = WebhookConfigReconciler(
            setup.client, self.cert_renewer.ca_bundle(),
            setup.options.namespace)
        self.elector = None
        if setup.options.leader_election:
            self.elector = LeaderElector(setup.client, 'kyverno',
                                         setup.options.namespace)

    def _create_ur(self, ur_spec: dict) -> None:
        from ..background.updaterequest import UpdateRequestGenerator
        UpdateRequestGenerator(self.setup.client).apply(
            dict(ur_spec, requestType=ur_spec.get('type', 'generate')))

    def sync_policies(self) -> List[Policy]:
        """Refresh the cache from stored Policy CRs (informer-driven in
        the reference: pkg/controllers/policycache/controller.go:133)."""
        docs = []
        for kind in ('ClusterPolicy', 'Policy'):
            try:
                docs += self.setup.client.list_resource(
                    'kyverno.io/v1', kind, '', None)
            except Exception:  # noqa: BLE001
                continue
        policies = [Policy(d) for d in docs]
        self.cache.warm_up(policies)
        return policies

    def tick(self) -> None:
        policies = self.sync_policies()
        self.openapi_controller.reconcile()
        is_leader = mesh_is_leader() and (
            self.elector is None or self.elector.is_leader())
        if is_leader:
            self.reconciler.reconcile(policies)
            self.reconciler.heartbeat()

    def run(self) -> None:
        if self.elector is not None:
            self.elector.run()
        self.server.start()
        self.setup.install_signal_handlers()
        self.setup.run_until_stopped(self.tick, interval=5.0)
        self.server.stop()
        if self.elector is not None:
            self.elector.release()


def main(args: Optional[List[str]] = None) -> int:
    parser = base_parser('kyverno-admission-controller')
    parser.add_argument('--port', type=int, default=9443)
    parser.add_argument('--insecure', action='store_true',
                        help='serve plain HTTP (tests/dev)')
    setup = Setup('kyverno-admission-controller', args, parser)
    controller = AdmissionController(setup, port=setup.options.port,
                                     tls=not setup.options.insecure)
    controller.run()
    return 0


if __name__ == '__main__':
    raise SystemExit(main())
