"""Admission-controller daemon (reference: cmd/kyverno/main.go:210).

Wires cert renewal, the policy cache, the webhook server, and the
leader-only reconcilers (webhook configurations, lease watchdog)."""

from __future__ import annotations

import tempfile
import threading
from typing import List, Optional

from ..api.policy import Policy
from ..controllers.leaderelection import LeaderElector, mesh_is_leader
from ..controllers.webhook import WebhookConfigReconciler
from ..policycache.cache import Cache
from ..tls.certs import CertRenewer
from ..webhooks.handlers import ResourceHandlers
from ..webhooks.server import WebhookServer
from .internal import Setup, base_parser


class AdmissionController:
    def __init__(self, setup: Setup, port: int = 9443, tls: bool = True):
        self.setup = setup
        self.cache = Cache()
        self.cert_renewer = CertRenewer(setup.client,
                                        setup.options.namespace)
        # the CA/pair secrets are always provisioned — webhook configs
        # need the CA bundle even when serving plain HTTP in tests
        _ca, cert, key = self.cert_renewer.renew()
        certfile = keyfile = None
        if tls:
            self._cert_tmp = tempfile.NamedTemporaryFile(suffix='.crt')
            self._key_tmp = tempfile.NamedTemporaryFile(suffix='.key')
            self._cert_tmp.write(cert)
            self._cert_tmp.flush()
            self._key_tmp.write(key)
            self._key_tmp.flush()
            certfile, keyfile = self._cert_tmp.name, self._key_tmp.name
        self._audit_threads: List[threading.Thread] = []
        # admission events ride the bounded event controller (reference:
        # pkg/event/controller.go wired in cmd/kyverno/main.go)
        from ..observability.events import EventGenerator
        self.event_generator = EventGenerator(setup.client)
        self.event_generator.run()
        self.handlers = ResourceHandlers(
            self.cache, configuration=setup.configuration,
            ur_sink=self._create_ur, audit_sink=self._audit,
            event_sink=self._events,
            client=setup.client)
        # CRD schema ingestion feeding the mutation schema checks
        # (reference: pkg/controllers/openapi/controller.go:148)
        from ..controllers.openapi import OpenAPIController
        self.openapi_controller = OpenAPIController(
            setup.client, self.handlers.openapi_manager)
        self.openapi_controller.reconcile()
        # policy change/rule-info metrics driven by policy events
        # (reference: pkg/controllers/metrics/policy/controller.go:155)
        from ..controllers.policymetrics import PolicyMetricsController
        self.policy_metrics = PolicyMetricsController(
            setup.client, setup.metrics)
        # background AOT warm-up: pre-compile (or pre-load from the
        # persistent executable store) the admission graph for the
        # installed enforce policy set before first traffic; readiness
        # is reported through /health/warmup and the warm-duration
        # histogram.  Requests serve the host engine loop meanwhile.
        self.warmer = setup.start_aot_warmer(self._warm_admission)
        from ..webhooks.server import PolicyHandlers
        self.server = WebhookServer(
            self.handlers, configuration=setup.configuration,
            policy_handlers=PolicyHandlers(setup.client),
            port=port, certfile=certfile, keyfile=keyfile,
            warmer=self.warmer)
        self.reconciler = WebhookConfigReconciler(
            setup.client, self.cert_renewer.ca_bundle(),
            setup.options.namespace)
        # graceful shutdown (LIFO): stop the server first — which
        # drains the admission micro-batcher so queued futures resolve
        # — then close the event/audit workers
        setup.register_shutdown(self.close)
        setup.register_shutdown(self.server.stop)
        self.elector = None
        if setup.options.leader_election:
            self.elector = LeaderElector(setup.client, 'kyverno',
                                         setup.options.namespace)

    def _warm_admission(self):
        """Warm-fn for the AOT warmer: build (or AOT-load) the compiled
        scanner for the installed enforce policy set — then bring EVERY
        canonical batch capacity to readiness on a small thread pool
        (the audit path scans at the bulk capacity, admission at the
        small one; a warm AOT store loads them in ~max, not sum).  The
        span reports how many shapes came up."""
        from ..policycache import cache as pcache
        self.sync_policies()
        enforce = self.cache.get_policies(pcache.VALIDATE_ENFORCE,
                                          'Pod', '')
        if not enforce:
            return 'no enforce policies installed'
        if not self.handlers.device:
            return 'device path disabled'
        ok = self.handlers.wait_device_ready(enforce, timeout=600.0)
        if not ok:
            return 'device path unavailable; host loop serves'
        scanner = self.handlers._device_scanner(enforce)
        shapes = {}
        if scanner is not None and hasattr(scanner, 'warmup_shapes'):
            shapes = scanner.warmup_shapes()
        detail = 'compiled scanner serving' + (
            ' (capacities ' +
            ', '.join(f'{c}:{s:.1f}s' for c, s in sorted(shapes.items()))
            + ')' if shapes else '')
        return detail, {'shapes_warmed': len(shapes),
                        'shape_caps': ','.join(str(c)
                                               for c in sorted(shapes))}

    def _create_ur(self, ur_spec: dict) -> None:
        from ..background.updaterequest import UpdateRequestGenerator
        UpdateRequestGenerator(self.setup.client).apply(
            dict(ur_spec, requestType=ur_spec.get('type', 'generate')))

    def _events(self, responses, blocked: bool) -> None:
        from ..observability.events import events_for_responses
        self.event_generator.add(
            *events_for_responses(responses, blocked))

    def _audit(self, request: dict, _enforce_responses) -> None:
        """Audit-report hand-off: runs on a worker thread like the
        reference's goroutine (validation.go:182 handleAudit) so the
        admission response never waits on the audit engine pass or the
        report CR write."""
        if request.get('operation') == 'DELETE':
            return
        t = threading.Thread(target=self._audit_sync,
                             args=(request, list(_enforce_responses or [])),
                             daemon=True, name='audit-report')
        t.start()
        self._audit_threads.append(t)
        del self._audit_threads[:-32]  # drop handles of finished work

    def flush_audits(self) -> None:
        """Join outstanding audit threads (tests / graceful shutdown)."""
        for t in list(self._audit_threads):
            t.join(timeout=30)

    def _audit_sync(self, request: dict,
                    enforce_responses=()) -> None:
        """reference: validation.go:156 buildAuditResponses — the AUDIT
        policy set plus the already-computed enforce responses produce
        per-request AdmissionReport CRs for the reports controller to
        aggregate (the reference reports over ALL engine responses)."""
        resource = request.get('object') or {}
        responses = list(enforce_responses) +             self.handlers.audit_responses(request)
        relevant = [r for r in responses if r.policy_response.rules]
        if not relevant:
            return
        from ..dclient.client import AlreadyExistsError
        from ..reports.types import build_admission_report
        report = build_admission_report(resource, request, *relevant)
        ns = (resource.get('metadata') or {}).get('namespace', '')
        try:
            self.setup.client.create_resource(
                'kyverno.io/v1alpha2', report['kind'], ns, report)
        except AlreadyExistsError:
            pass  # duplicate request uid: the first report stands

    def sync_policies(self) -> List[Policy]:
        """Refresh the cache from stored Policy CRs (informer-driven in
        the reference: pkg/controllers/policycache/controller.go:133)."""
        docs = []
        # policy CRDs are served at multiple versions (v1 is the
        # storage version; v2beta1 manifests are conversion-identical
        # for the fields the engine reads)
        for api_version in ('kyverno.io/v1', 'kyverno.io/v2beta1'):
            for kind in ('ClusterPolicy', 'Policy'):
                try:
                    docs += self.setup.client.list_resource(
                        api_version, kind, '', None)
                except Exception:  # noqa: BLE001
                    continue
        policies = [Policy(d) for d in docs]
        self.cache.warm_up(policies)
        return policies

    def tick(self) -> None:
        policies = self.sync_policies()
        self.openapi_controller.reconcile()
        is_leader = mesh_is_leader() and (
            self.elector is None or self.elector.is_leader())
        if is_leader:
            self.reconciler.reconcile(policies)
            self.reconciler.heartbeat()

    def close(self) -> None:
        """Stop owned worker threads (event generator, audits)."""
        self.flush_audits()
        self.event_generator.stop()

    def run(self) -> None:
        if self.elector is not None:
            self.elector.run()
        self.server.start()
        self.setup.install_signal_handlers()
        self.setup.run_until_stopped(self.tick, interval=5.0)
        self.setup.shutdown()
        if self.elector is not None:
            self.elector.release()


def main(args: Optional[List[str]] = None) -> int:
    parser = base_parser('kyverno-admission-controller')
    parser.add_argument('--port', type=int, default=9443)
    parser.add_argument('--insecure', action='store_true',
                        help='serve plain HTTP (tests/dev)')
    setup = Setup('kyverno-admission-controller', args, parser)
    controller = AdmissionController(setup, port=setup.options.port,
                                     tls=not setup.options.insecure)
    controller.run()
    return 0


if __name__ == '__main__':
    raise SystemExit(main())
