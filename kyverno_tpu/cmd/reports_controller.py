"""Reports-controller daemon (reference: cmd/reports-controller/main.go)
— the batch-scan path the TPU backend accelerates: resource metadata
sync → device-batched background scan → admission-report dedup →
PolicyReport aggregation."""

from __future__ import annotations

from typing import List, Optional

from ..api.policy import Policy
from ..controllers.leaderelection import mesh_is_leader
from ..reports.aggregate import AggregateController
from ..reports.controllers import (AdmissionReportController,
                                   BackgroundScanController, MetadataCache,
                                   ResourceController)
from .internal import Setup, base_parser


class ReportsController:
    def __init__(self, setup: Setup):
        self.setup = setup
        self.cache = MetadataCache()
        self.resource_controller = ResourceController(setup.client,
                                                      self.cache)
        self.scan_controller = BackgroundScanController(
            setup.client, [], cache=self.cache)
        self.admission_controller = AdmissionReportController(setup.client)
        self.aggregate_controller = AggregateController(setup.client)
        self._policy_snapshot = None
        # persist the verdict cache on shutdown so the next process
        # restarts its background rescans at O(churn), not O(cluster)
        setup.register_shutdown(self.scan_controller.close)

    def _policies(self) -> List[Policy]:
        docs = []
        # policy CRDs are multi-version served (v1 storage, v2beta1
        # conversion-identical for the fields the engine reads)
        for api_version in ('kyverno.io/v1', 'kyverno.io/v2beta1'):
            for kind in ('ClusterPolicy', 'Policy'):
                try:
                    docs += self.setup.client.list_resource(
                        api_version, kind, '', None)
                except Exception:  # noqa: BLE001
                    continue
        return [Policy(d) for d in docs]

    def tick(self) -> None:
        if not mesh_is_leader():
            return
        policies = self._policies()
        snapshot = [p.raw for p in policies]
        if snapshot != self._policy_snapshot:
            self._policy_snapshot = snapshot
            self.resource_controller.update_policies(policies)
            self.scan_controller.set_policies(policies)
            self.scan_controller.enqueue_all()
        for changed in self.resource_controller.sync():
            self.scan_controller.enqueue(changed)
        self.scan_controller.reconcile()
        self.admission_controller.reconcile()
        self.aggregate_controller.reconcile()

    def run(self) -> None:
        self.setup.install_signal_handlers()
        self.setup.run_until_stopped(self.tick, interval=2.0)


def main(args: Optional[List[str]] = None) -> int:
    setup = Setup('kyverno-reports-controller', args,
                  base_parser('kyverno-reports-controller'))
    ReportsController(setup).run()
    return 0


if __name__ == '__main__':
    raise SystemExit(main())
