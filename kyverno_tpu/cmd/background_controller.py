"""Background-controller daemon (reference:
cmd/background-controller/main.go): drains UpdateRequests through the
generate / mutate-existing processors and runs the policy lifecycle
controller."""

from __future__ import annotations

from typing import List, Optional

from ..background.update_request_controller import UpdateRequestController
from ..controllers.leaderelection import mesh_is_leader
from ..policy.controller import PolicyController
from .internal import Setup, base_parser


class BackgroundController:
    def __init__(self, setup: Setup):
        self.setup = setup
        from ..engine.apicall import make_context_loader
        from ..engine.engine import Engine
        engine = Engine(context_loader=make_context_loader(
            dclient=setup.client))
        self.ur_controller = UpdateRequestController(
            setup.client, engine,
            policy_getter=self._get_policy)
        self.policy_controller = PolicyController(setup.client)
        self._seen_policies: dict = {}

    def _get_policy(self, key: str):
        from ..background.common import get_policy
        try:
            return get_policy(self.setup.client, key)
        except Exception:  # noqa: BLE001 - deleted policy
            return None

    def tick(self) -> None:
        if not mesh_is_leader():
            return
        # policy lifecycle events from the stored CRs
        current = {}
        for api_version in ('kyverno.io/v1', 'kyverno.io/v2beta1'):
          for kind in ('ClusterPolicy', 'Policy'):
            try:
                for doc in self.setup.client.list_resource(
                        api_version, kind, '', None):
                    meta = doc.get('metadata') or {}
                    key = f"{meta.get('namespace', '')}/{meta.get('name')}"
                    current[key] = doc
            except Exception:  # noqa: BLE001
                continue
        for key, doc in current.items():
            old = self._seen_policies.get(key)
            if old is None:
                self.policy_controller.add_policy(doc)
            elif old != doc:
                self.policy_controller.update_policy(old, doc)
        for key, doc in list(self._seen_policies.items()):
            if key not in current:
                self.policy_controller.delete_policy(doc)
        self._seen_policies = current
        # drain pending UpdateRequests
        self.ur_controller.process_pending()

    def run(self) -> None:
        self.setup.install_signal_handlers()
        self.setup.run_until_stopped(self.tick, interval=2.0)


def main(args: Optional[List[str]] = None) -> int:
    setup = Setup('kyverno-background-controller', args,
                  base_parser('kyverno-background-controller'))
    BackgroundController(setup).run()
    return 0


if __name__ == '__main__':
    raise SystemExit(main())
