"""Pre-install init job (reference: cmd/kyverno-init/main.go): removes
stale webhook configurations, health leases and old report CRs left by a
previous deployment so a fresh install starts clean."""

from __future__ import annotations

from typing import List, Optional

from ..controllers.webhook import LEASE_NAME, MUTATING_NAME, VALIDATING_NAME
from .internal import Setup, base_parser

_REPORT_KINDS = (
    ('kyverno.io/v1alpha2', 'AdmissionReport'),
    ('kyverno.io/v1alpha2', 'ClusterAdmissionReport'),
    ('kyverno.io/v1alpha2', 'BackgroundScanReport'),
    ('kyverno.io/v1alpha2', 'ClusterBackgroundScanReport'),
)


def cleanup_stale_state(client, namespace: str = 'kyverno') -> int:
    removed = 0
    for kind, name in (('ValidatingWebhookConfiguration', VALIDATING_NAME),
                       ('MutatingWebhookConfiguration', MUTATING_NAME)):
        try:
            client.delete_resource('admissionregistration.k8s.io/v1',
                                   kind, '', name)
            removed += 1
        except Exception:  # noqa: BLE001
            pass
    try:
        client.delete_resource('coordination.k8s.io/v1', 'Lease',
                               namespace, LEASE_NAME)
        removed += 1
    except Exception:  # noqa: BLE001
        pass
    for api_version, kind in _REPORT_KINDS:
        try:
            for report in client.list_resource(api_version, kind, '', None):
                meta = report.get('metadata') or {}
                client.delete_resource(api_version, kind,
                                       meta.get('namespace', ''),
                                       meta.get('name', ''))
                removed += 1
        except Exception:  # noqa: BLE001
            continue
    return removed


def main(args: Optional[List[str]] = None) -> int:
    setup = Setup('kyverno-init', args, base_parser('kyverno-init'))
    removed = cleanup_stale_state(setup.client, setup.options.namespace)
    setup.logger.info('cleaned %d stale objects', removed)
    return 0


if __name__ == '__main__':
    raise SystemExit(main())
