"""Shared process bootstrap for the deployable binaries.

Mirrors the reference's setup sequence — logging → flags → maxprocs →
profiling → signal handling → metrics (reference: cmd/internal/setup.go:21
Setup, flag registry cmd/internal/flag.go:35-63).
"""

from __future__ import annotations

import argparse
import logging
import signal
import threading
from typing import Callable, List, Optional

from ..config.config import Configuration
from ..observability.logging import FORMAT_TEXT, setup as setup_logging
from ..observability.metrics import MetricsRegistry


def base_parser(name: str) -> argparse.ArgumentParser:
    """reference: cmd/internal/flag.go:35-63"""
    p = argparse.ArgumentParser(prog=name)
    p.add_argument('--logging-format', default=FORMAT_TEXT,
                   choices=('text', 'json'))
    p.add_argument('--log-level', default='info',
                   choices=('debug', 'info', 'warning', 'error'))
    p.add_argument('--namespace', default='kyverno')
    p.add_argument('--metrics-port', type=int, default=8000)
    p.add_argument('--disable-metrics', action='store_true')
    p.add_argument('--leader-election', action='store_true')
    # reference: cmd/internal/flag.go:40-42 (-profile/-profilePort) and
    # :46-49 (enableTracing/tracingAddress/tracingPort)
    p.add_argument('--profile', action='store_true')
    p.add_argument('--profile-port', type=int, default=6060)
    p.add_argument('--enable-tracing', action='store_true')
    p.add_argument('--kubeconfig', default='',
                   help='unused with the in-memory client; reserved for '
                        'a real cluster transport')
    return p


class Setup:
    """Process-wide wiring shared by every binary."""

    def __init__(self, name: str, args: Optional[List[str]] = None,
                 parser: Optional[argparse.ArgumentParser] = None,
                 client=None):
        parser = parser or base_parser(name)
        self.options = parser.parse_args(args)
        self.logger = setup_logging(
            self.options.logging_format,
            getattr(logging, self.options.log_level.upper()))
        self.metrics = MetricsRegistry() if not self.options.disable_metrics \
            else MetricsRegistry(disabled=['*'])
        if not self.options.disable_metrics:
            # publish the daemon registry process-wide and light up the
            # device-pipeline telemetry (stage histograms, compile-cache
            # counters, d2h stall watchdog — KTPU_D2H_STALL_S)
            from ..observability.metrics import set_global_registry
            from ..observability import coverage
            from ..observability import device as device_telemetry
            from ..observability import executables
            from ..observability import provenance
            from ..observability import slo
            set_global_registry(self.metrics)
            device_telemetry.configure(self.metrics)
            # device-coverage ledger: per-rule placement + attributed
            # host-fallback counters (GET /debug/coverage with --profile)
            coverage.configure(self.metrics)
            # decision provenance: per-decision serving-path records +
            # the flight recorder (GET /debug/decisions with --profile;
            # KTPU_FLIGHT_N=0 keeps it off)
            provenance.configure(self.metrics)
            # executable lifecycle ledger (GET /debug/executables;
            # KTPU_EXEC_LEDGER_N=0 keeps it off)
            executables.configure(self.metrics)
            # admission-latency SLO engine (GET /debug/slo; off unless
            # KTPU_SLO_WINDOW_S > 0)
            slo.configure(self.metrics)
            # fleet observatory: mesh-step telemetry, straggler blame +
            # cross-host federation (GET /debug/fleet; KTPU_FLEET=0
            # pins it off)
            from ..observability import fleet
            fleet.configure(self.metrics)
        self.configuration = Configuration()
        if client is None:
            from ..dclient.client import FakeClient
            client = FakeClient()
        self.client = client
        self.stop_event = threading.Event()
        # populated by start_aot_warmer (admission controller)
        self.aot_warmer = None
        # LIFO shutdown hooks (drain the admission batcher, stop
        # servers); run by shutdown() when the daemon loop exits
        self._shutdown_hooks: List[Callable[[], None]] = []
        # profiling + tracing (reference: setup.go:21 setup order)
        self.profiling_server = None
        if getattr(self.options, 'profile', False):
            from ..observability.profiling import ProfilingServer
            self.profiling_server = ProfilingServer(
                self.options.profile_port)
            self.profiling_server.start()
        if getattr(self.options, 'enable_tracing', False):
            from ..observability import tracing
            tracing.configure()

    def start_aot_warmer(self, warm_fn, name: str = 'admission'):
        """Kick off the background AOT warm-up (pre-compile / pre-load
        of the serving graph before first traffic).  Honors KTPU_WARM=0
        (no thread, state 'disabled').  Returns the Warmer so callers
        can report readiness (webhook health endpoints, benchmarks)."""
        from ..aotcache.warmer import Warmer
        registry = None if self.options.disable_metrics else self.metrics
        warmer = Warmer(warm_fn, name=name, registry=registry)
        warmer.start()
        self.aot_warmer = warmer
        return warmer

    def register_shutdown(self, hook: Callable[[], None]) -> None:
        """Register a graceful-shutdown hook (run LIFO by shutdown()).
        The admission controller registers WebhookServer.stop here,
        which drains the serving micro-batcher — queued admission
        futures resolve before the process exits."""
        self._shutdown_hooks.append(hook)

    def shutdown(self) -> None:
        """Run registered shutdown hooks, newest first.  Hooks must be
        idempotent (the daemon run loop may also call them directly);
        a failing hook is logged and never blocks the rest."""
        while self._shutdown_hooks:
            hook = self._shutdown_hooks.pop()
            try:
                hook()
            except Exception:  # noqa: BLE001
                self.logger.exception('shutdown hook failed')
        # residency gauges (queue depth, in-flight chunks, breaker
        # states) describe live occupancy: once everything above has
        # drained, a scrape must see 0, not the last sampled value
        self.metrics.reset_residency_gauges()

    def install_signal_handlers(self) -> None:
        def handler(signum, frame):
            self.logger.info('shutting down (signal %s)', signum)
            self.stop_event.set()
        try:
            signal.signal(signal.SIGTERM, handler)
            signal.signal(signal.SIGINT, handler)
        except ValueError:
            pass  # not the main thread (tests)

    def run_until_stopped(self, tick: Callable[[], None],
                          interval: float = 1.0) -> None:
        while not self.stop_event.wait(interval):
            try:
                tick()
            except Exception:  # noqa: BLE001
                self.logger.exception('controller tick failed')
