"""Cosign-style verification against a registry client (reference:
pkg/cosign/cosign.go:63 VerifySignature, :256 FetchAttestations).

A signature entry matches when the attestor's key id equals the stored
key (static keys), or its subject/issuer match (keyless) — wildcards
allowed, the same matching the reference performs on certificate
identity.
"""

from __future__ import annotations

from typing import List, Optional

from ..utils import wildcard
from ..registry.client import RegistryError


class Options:
    """reference: pkg/cosign/cosign.go Options (subset used by the engine)"""

    __slots__ = ('image_ref', 'key', 'cert', 'cert_chain', 'roots',
                 'subject', 'issuer', 'annotations', 'repository',
                 'ignore_tlog', 'rekor_url', 'predicate_type',
                 'fetch_attestations')

    def __init__(self, image_ref: str, key: str = '', cert: str = '',
                 cert_chain: str = '', roots: str = '', subject: str = '',
                 issuer: str = '', annotations: Optional[dict] = None,
                 repository: str = '', ignore_tlog: bool = False,
                 rekor_url: str = '', predicate_type: str = '',
                 fetch_attestations: bool = False):
        self.image_ref = image_ref
        self.key = key
        self.cert = cert
        self.cert_chain = cert_chain
        self.roots = roots
        self.subject = subject
        self.issuer = issuer
        self.annotations = annotations or {}
        self.repository = repository
        self.ignore_tlog = ignore_tlog
        self.rekor_url = rekor_url
        self.predicate_type = predicate_type
        self.fetch_attestations = fetch_attestations


class Response:
    """reference: pkg/cosign/cosign.go Response"""

    __slots__ = ('digest', 'statements')

    def __init__(self, digest: str = '', statements: Optional[List[dict]] = None):
        self.digest = digest
        self.statements = statements or []


def _signature_matches(sig: dict, opts: Options) -> bool:
    if opts.key:
        return sig.get('key', '') == opts.key.strip()
    matched = True
    if opts.subject:
        matched = matched and wildcard.match(opts.subject,
                                             sig.get('subject', ''))
    if opts.issuer:
        matched = matched and wildcard.match(opts.issuer,
                                             sig.get('issuer', ''))
    if not opts.subject and not opts.issuer:
        # keyless with no identity constraints: any signature counts
        matched = bool(sig)
    return matched


def verify_signature(rclient, opts: Options) -> Response:
    """reference: cosign.go:63 VerifySignature — raises on no match."""
    signatures = rclient.get_signatures(opts.image_ref)
    digest = rclient.fetch_image_descriptor(opts.image_ref).digest
    for sig in signatures:
        if _signature_matches(sig, opts):
            return Response(digest=digest)
    raise RegistryError(
        f'no matching signatures for {opts.image_ref}')


def fetch_attestations(rclient, opts: Options) -> Response:
    """reference: cosign.go:256 FetchAttestations — returns the in-toto
    statements whose signer matches the attestor options."""
    attestations = rclient.get_attestations(opts.image_ref)
    digest = rclient.fetch_image_descriptor(opts.image_ref).digest
    statements = []
    for att in attestations:
        sig = {'key': att.get('key', ''), 'subject': att.get('subject', ''),
               'issuer': att.get('issuer', '')}
        if opts.key or opts.subject or opts.issuer:
            if not _signature_matches(sig, opts):
                continue
        statements.append(att['statement'])
    return Response(digest=digest, statements=statements)
