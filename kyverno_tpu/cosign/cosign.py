"""Cosign verification against a registry client (reference:
pkg/cosign/cosign.go:63 VerifySignature, :256 FetchAttestations).

Real signature cryptography over the cosign "simple signing" model:

* a signature entry carries ``payload`` (base64 JSON) + ``signature``
  (base64 over the payload bytes) and optionally a signing ``cert`` (+
  ``chain``) for keyless flows;
* static-key attestors verify the signature with the provided PEM public
  key (ECDSA P-256/P-384 SHA-256, RSA PKCS1v15 SHA-256, or Ed25519);
* keyless attestors verify the leaf certificate chains to the provided
  roots, verify the payload signature with the leaf's public key, and
  match the certificate identity — SAN email/URI vs ``subject``, the
  Fulcio OIDC-issuer extension (1.3.6.1.4.1.57264.1.1) vs ``issuer`` —
  with the same wildcard semantics the reference applies;
* the payload's ``critical.image.docker-manifest-digest`` must equal the
  image's digest, and attestor ``annotations`` must be present in the
  payload's ``optional`` block (cosign.go payload checks).

Rekor transparency-log verification is OFFLINE, from the signature
entry's attached bundle (what ``cosign sign`` stores under the
``dev.sigstore.cosign/bundle`` annotation), matching the reference's
cosign-library behavior when a Rekor client is configured
(pkg/cosign/cosign.go:204 buildCosignOptions → RekorClient; the
library prefers the offline bundle when present):

* the SignedEntryTimestamp must verify over the RFC 8785-canonical
  JSON of {body, integratedTime, logID, logIndex} with the configured
  Rekor public key (``rekor.pubkey`` in the policy's rekor block, or
  the SIGSTORE_REKOR_PUBLIC_KEY env var — cosign's own override);
* the bundle body (hashedrekord / rekord) must be consistent with the
  verified signature: same payload hash and same signature bytes;
* for keyless entries the integratedTime must fall inside the signing
  certificate's validity window (cosign CheckExpiry).

Per the reference CRD semantics (image_verification_types.go:149 "If
the value is nil, Rekor is not checked"), tlog verification runs
whenever the attestor carries a ``rekor:`` block (unless its
``ignoreTlog`` is set) — and an entry without a valid bundle then
FAILS verification.

Legacy metadata-only entries (a bare ``key`` id, no payload) remain
accepted ONLY when the attestor key is not a PEM block — the CLI mock
registry uses those; any PEM-keyed attestor requires real signatures.
"""

from __future__ import annotations

import base64
import json
from typing import List, Optional, Tuple

from ..utils import wildcard
from ..registry.client import RegistryError

_FULCIO_ISSUER_OID = '1.3.6.1.4.1.57264.1.1'


class Options:
    """reference: pkg/cosign/cosign.go Options (subset used by the engine)"""

    __slots__ = ('image_ref', 'key', 'cert', 'cert_chain', 'roots',
                 'subject', 'issuer', 'annotations', 'repository',
                 'ignore_tlog', 'rekor_url', 'rekor_pubkey',
                 'predicate_type', 'fetch_attestations')

    def __init__(self, image_ref: str, key: str = '', cert: str = '',
                 cert_chain: str = '', roots: str = '', subject: str = '',
                 issuer: str = '', annotations: Optional[dict] = None,
                 repository: str = '', ignore_tlog: bool = False,
                 rekor_url: str = '', rekor_pubkey: str = '',
                 predicate_type: str = '',
                 fetch_attestations: bool = False):
        self.image_ref = image_ref
        self.key = key
        self.cert = cert
        self.cert_chain = cert_chain
        self.roots = roots
        self.subject = subject
        self.issuer = issuer
        self.annotations = annotations or {}
        self.repository = repository
        self.ignore_tlog = ignore_tlog
        self.rekor_url = rekor_url
        self.rekor_pubkey = rekor_pubkey
        self.predicate_type = predicate_type
        self.fetch_attestations = fetch_attestations

    def tlog_required(self) -> bool:
        """Tlog verification applies when the attestor configures Rekor
        (CRD: 'If the value is nil, Rekor is not checked' —
        image_verification_types.go:149) and ignoreTlog is unset."""
        return bool(self.rekor_url or self.rekor_pubkey) and \
            not self.ignore_tlog


class Response:
    """reference: pkg/cosign/cosign.go Response"""

    __slots__ = ('digest', 'statements')

    def __init__(self, digest: str = '', statements: Optional[List[dict]] = None):
        self.digest = digest
        self.statements = statements or []


class VerificationError(Exception):
    """One signature entry failed cryptographic verification."""


def _is_pem(blob: str) -> bool:
    return isinstance(blob, str) and '-----BEGIN' in blob


# ---------------------------------------------------------------------------
# crypto primitives

def _load_public_key(pem: str):
    from cryptography.hazmat.primitives import serialization
    try:
        return serialization.load_pem_public_key(pem.strip().encode())
    except Exception as e:  # noqa: BLE001
        raise VerificationError(f'bad public key: {e}') from e


def _verify_blob(public_key, signature: bytes, payload: bytes) -> None:
    """Verify ``signature`` over ``payload`` for the supported key types
    (cosign defaults: ECDSA-SHA256; RSA PKCS1v15-SHA256; Ed25519)."""
    from cryptography.exceptions import InvalidSignature
    from cryptography.hazmat.primitives import hashes
    from cryptography.hazmat.primitives.asymmetric import (ec, ed25519,
                                                           padding, rsa)
    try:
        if isinstance(public_key, ec.EllipticCurvePublicKey):
            public_key.verify(signature, payload,
                              ec.ECDSA(hashes.SHA256()))
        elif isinstance(public_key, rsa.RSAPublicKey):
            public_key.verify(signature, payload, padding.PKCS1v15(),
                              hashes.SHA256())
        elif isinstance(public_key, ed25519.Ed25519PublicKey):
            public_key.verify(signature, payload)
        else:
            raise VerificationError(
                f'unsupported key type {type(public_key).__name__}')
    except InvalidSignature as e:
        raise VerificationError('signature verification failed') from e


def _load_certs(pem_blob: str) -> List:
    from cryptography import x509
    certs = []
    block: List[str] = []
    for line in (pem_blob or '').splitlines():
        block.append(line)
        if '-----END CERTIFICATE-----' in line:
            try:
                certs.append(x509.load_pem_x509_certificate(
                    '\n'.join(block).encode()))
            except Exception as e:  # noqa: BLE001 - registry data is
                # untrusted; a malformed cert must fail only this entry
                raise VerificationError(f'bad certificate: {e}') from e
            block = []
    return certs


def _verify_cert_chain(leaf, intermediates: List, roots: List) -> None:
    """Walk issuer links from the leaf to any of ``roots``, verifying
    each certificate's signature with its issuer's public key
    (cosign.go cert verification against the provided root pool)."""
    if not roots:
        raise VerificationError('no roots provided for certificate chain')
    pool = {c.subject.rfc4514_string(): c for c in intermediates}
    root_by_subject = {c.subject.rfc4514_string(): c for c in roots}
    current = leaf
    for _hop in range(len(intermediates) + 2):
        issuer_name = current.issuer.rfc4514_string()
        issuer = root_by_subject.get(issuer_name)
        terminal = issuer is not None
        if issuer is None:
            issuer = pool.get(issuer_name)
        if issuer is None:
            raise VerificationError(
                f'certificate chain broken at issuer {issuer_name!r}')
        try:
            current.verify_directly_issued_by(issuer)
        except Exception as e:  # noqa: BLE001
            raise VerificationError(
                f'certificate signature invalid: {e}') from e
        if terminal:
            return
        current = issuer
    raise VerificationError('certificate chain too long')


def _cert_identities(cert) -> Tuple[List[str], str]:
    """(SAN subjects, OIDC issuer) of a Fulcio-style signing cert."""
    from cryptography import x509
    subjects: List[str] = []
    try:
        san = cert.extensions.get_extension_for_class(
            x509.SubjectAlternativeName).value
        subjects += san.get_values_for_type(x509.RFC822Name)
        subjects += [str(u) for u in san.get_values_for_type(
            x509.UniformResourceIdentifier)]
    except x509.ExtensionNotFound:
        pass
    issuer = ''
    for ext in cert.extensions:
        if ext.oid.dotted_string == _FULCIO_ISSUER_OID:
            raw = ext.value.value if hasattr(ext.value, 'value') else b''
            issuer = raw.decode('utf-8', 'replace') if raw else ''
    return subjects, issuer


# ---------------------------------------------------------------------------
# payload checks (cosign simple-signing)

def _check_payload(payload: bytes, digest: str, opts: Options) -> None:
    try:
        doc = json.loads(payload)
    except ValueError as e:
        raise VerificationError(f'malformed signature payload: {e}') from e
    got = ((doc.get('critical') or {}).get('image') or {}).get(
        'docker-manifest-digest', '')
    if got != digest:
        raise VerificationError(
            f'payload digest {got!r} does not match image digest {digest!r}')
    optional = doc.get('optional') or {}
    for k, v in opts.annotations.items():
        if optional.get(k) != v:
            raise VerificationError(f'annotation {k!r} mismatch')


def _verify_crypto_sig(sig: dict, payload: bytes, signature: bytes,
                       opts: Options) -> None:
    """Shared signature + signer verification for signature and
    attestation entries (keyed or keyless)."""
    if opts.key:
        _verify_blob(_load_public_key(opts.key), signature, payload)
        return
    if opts.cert:
        # pinned certificate: the signature MUST verify with the
        # attestor's cert — an entry-supplied cert is never trusted here
        certs = _load_certs(opts.cert)
        if not certs:
            raise VerificationError('no pinned certificate parsed')
        leaf = certs[0]
        roots = _load_certs(opts.roots)
        if roots:
            _verify_cert_chain(leaf,
                               certs[1:] + _load_certs(opts.cert_chain),
                               roots)
    else:
        # Fulcio-style keyless: the entry carries its signing cert, which
        # must chain to the configured roots — without roots there is no
        # trust anchor at all
        cert_pem = sig.get('cert', '')
        if not cert_pem:
            raise VerificationError('no certificate for keyless entry')
        certs = _load_certs(cert_pem)
        if not certs:
            raise VerificationError('no certificate parsed')
        leaf = certs[0]
        roots = _load_certs(opts.roots)
        if not roots:
            raise VerificationError(
                'keyless verification requires roots or a pinned cert')
        _verify_cert_chain(
            leaf,
            certs[1:] + _load_certs(sig.get('chain', '')) +
            _load_certs(opts.cert_chain),
            roots)
    _verify_blob(leaf.public_key(), signature, payload)
    subjects, issuer = _cert_identities(leaf)
    if opts.subject and not any(
            wildcard.match(opts.subject, s) for s in subjects):
        raise VerificationError(
            f'certificate subjects {subjects} do not match '
            f'{opts.subject!r}')
    if opts.issuer and not wildcard.match(opts.issuer, issuer):
        raise VerificationError(
            f'certificate issuer {issuer!r} does not match '
            f'{opts.issuer!r}')
    return leaf


# ---------------------------------------------------------------------------
# Rekor transparency log (offline bundle verification)

def _rekor_public_key(opts: Options) -> str:
    import os
    pem = opts.rekor_pubkey or os.environ.get(
        'SIGSTORE_REKOR_PUBLIC_KEY', '')
    if not pem:
        raise VerificationError(
            'tlog verification required but no Rekor public key is '
            'configured (rekor.pubkey or SIGSTORE_REKOR_PUBLIC_KEY)')
    return pem


def canonical_tlog_payload(bundle_payload: dict) -> bytes:
    """RFC 8785-style canonical JSON of the Rekor log entry the
    SignedEntryTimestamp covers (sigstore verifySET: sorted keys, no
    whitespace)."""
    return json.dumps({
        'body': bundle_payload.get('body'),
        'integratedTime': bundle_payload.get('integratedTime'),
        'logID': bundle_payload.get('logID'),
        'logIndex': bundle_payload.get('logIndex'),
    }, sort_keys=True, separators=(',', ':')).encode()


def _verify_tlog(sig: dict, payload: bytes, signature: bytes,
                 opts: Options) -> int:
    """Offline Rekor bundle verification; returns integratedTime.

    Mirrors the cosign library's VerifyBundle path the reference engages
    through cosign.go:204: SET signature over the canonical entry, then
    entry↔signature consistency (hashedrekord / rekord body)."""
    import hashlib
    bundle = sig.get('bundle')
    if not isinstance(bundle, dict):
        raise VerificationError(
            'tlog verification required but the signature carries no '
            'transparency log bundle')
    pl = bundle.get('Payload')
    if not isinstance(pl, dict):
        raise VerificationError('malformed tlog bundle: no Payload')
    set_b64 = bundle.get('SignedEntryTimestamp', '')
    try:
        set_sig = base64.b64decode(set_b64)
    except Exception as e:  # noqa: BLE001
        raise VerificationError(f'undecodable SignedEntryTimestamp: {e}') \
            from e
    _verify_blob(_load_public_key(_rekor_public_key(opts)), set_sig,
                 canonical_tlog_payload(pl))
    # entry body must describe THIS signature (cosign
    # verifyBundleMatchesSignature)
    try:
        body = json.loads(base64.b64decode(pl.get('body', '')))
    except Exception as e:  # noqa: BLE001
        raise VerificationError(f'undecodable tlog entry body: {e}') from e
    kind = body.get('kind', '')
    spec = body.get('spec') or {}
    data = spec.get('data') or {}
    if kind in ('intoto', 'dsse'):
        # cosign attest logs attestations as intoto/dsse entries whose
        # hash covers the logged envelope/payload; the raw-signature
        # comparison of rekord entries does not apply.  Check the
        # content hash against the signed payload when present.
        content = spec.get('content') or {}
        got = ((content.get('hash') or {}).get('value', '') or
               (content.get('payloadHash') or {}).get('value', '') or
               ((spec.get('envelopeHash') or {}).get('value', '')))
        if got:
            want = hashlib.sha256(payload).hexdigest()
            if got.lower() != want:
                raise VerificationError(
                    f'tlog entry payload hash {got!r} does not match '
                    f'the signed attestation')
    elif kind in ('hashedrekord', 'rekord'):
        sig_content = (spec.get('signature') or {}).get('content', '')
        try:
            body_sig = base64.b64decode(sig_content)
        except Exception as e:  # noqa: BLE001
            raise VerificationError(
                f'undecodable tlog signature: {e}') from e
        if body_sig != signature:
            raise VerificationError(
                'tlog entry signature does not match the verified '
                'signature')
        if kind == 'hashedrekord':
            want = hashlib.sha256(payload).hexdigest()
            got = (data.get('hash') or {}).get('value', '')
            if got.lower() != want:
                raise VerificationError(
                    f'tlog entry payload hash {got!r} does not match the '
                    f'signed payload')
        else:
            try:
                content = base64.b64decode(data.get('content', ''))
            except Exception as e:  # noqa: BLE001
                raise VerificationError(
                    f'undecodable tlog entry content: {e}') from e
            if content != payload:
                raise VerificationError(
                    'tlog entry content does not match the signed payload')
    else:
        raise VerificationError(f'unsupported tlog entry kind {kind!r}')
    it = pl.get('integratedTime')
    if not isinstance(it, int):
        raise VerificationError('tlog entry has no integratedTime')
    return it


def _check_cert_expiry_at(leaf, integrated_time: int) -> None:
    """cosign CheckExpiry: the Rekor inclusion time must fall inside the
    signing certificate's validity window."""
    from datetime import datetime, timezone
    at = datetime.fromtimestamp(integrated_time, tz=timezone.utc)
    not_before = getattr(leaf, 'not_valid_before_utc', None) or \
        leaf.not_valid_before.replace(tzinfo=timezone.utc)
    not_after = getattr(leaf, 'not_valid_after_utc', None) or \
        leaf.not_valid_after.replace(tzinfo=timezone.utc)
    if at < not_before or at > not_after:
        raise VerificationError(
            f'tlog integratedTime {at.isoformat()} outside certificate '
            f'validity [{not_before.isoformat()}, {not_after.isoformat()}]')


def _decode_entry(entry: dict) -> Tuple[bytes, bytes]:
    try:
        return (base64.b64decode(entry['payload']),
                base64.b64decode(entry['signature']))
    except Exception as e:  # noqa: BLE001
        raise VerificationError(f'undecodable signature entry: {e}') from e


def _verify_entry(sig: dict, digest: str, opts: Options) -> None:
    """Cryptographically verify one stored signature entry."""
    payload, signature = _decode_entry(sig)
    leaf = _verify_crypto_sig(sig, payload, signature, opts)
    if opts.tlog_required():
        integrated_time = _verify_tlog(sig, payload, signature, opts)
        if leaf is not None:
            _check_cert_expiry_at(leaf, integrated_time)
    _check_payload(payload, digest, opts)


# ---------------------------------------------------------------------------
# legacy metadata matching (CLI mock-registry fixtures only)

def _signature_matches(sig: dict, opts: Options) -> bool:
    if opts.key:
        return sig.get('key', '') == opts.key.strip()
    matched = True
    if opts.subject:
        matched = matched and wildcard.match(opts.subject,
                                             sig.get('subject', ''))
    if opts.issuer:
        matched = matched and wildcard.match(opts.issuer,
                                             sig.get('issuer', ''))
    if not opts.subject and not opts.issuer:
        # keyless with no identity constraints: any signature counts
        matched = bool(sig)
    return matched


def _is_crypto_entry(sig: dict) -> bool:
    return 'payload' in sig and 'signature' in sig


def verify_signature(rclient, opts: Options) -> Response:
    """reference: cosign.go:63 VerifySignature — raises on no match."""
    signatures = rclient.get_signatures(opts.image_ref)
    digest = rclient.fetch_image_descriptor(opts.image_ref).digest
    errors: List[str] = []
    pem_attestor = _is_pem(opts.key) or _is_pem(opts.roots) or \
        _is_pem(opts.cert)
    for sig in signatures:
        if _is_crypto_entry(sig):
            try:
                _verify_entry(sig, digest, opts)
                return Response(digest=digest)
            except VerificationError as e:
                errors.append(str(e))
                continue
        elif not pem_attestor and _signature_matches(sig, opts):
            # legacy metadata entry — only for non-PEM attestor fixtures
            return Response(digest=digest)
    detail = f': {"; ".join(errors)}' if errors else ''
    raise RegistryError(
        f'no matching signatures for {opts.image_ref}{detail}')


def fetch_attestations(rclient, opts: Options) -> Response:
    """reference: cosign.go:256 FetchAttestations — returns the in-toto
    statements whose signer verifies against the attestor options."""
    attestations = rclient.get_attestations(opts.image_ref)
    digest = rclient.fetch_image_descriptor(opts.image_ref).digest
    pem_attestor = _is_pem(opts.key) or _is_pem(opts.roots) or \
        _is_pem(opts.cert)
    statements = []
    for att in attestations:
        if _is_crypto_entry(att):
            try:
                payload, signature = _decode_entry(att)
                leaf = _verify_crypto_sig(att, payload, signature, opts)
                if opts.tlog_required():
                    it = _verify_tlog(att, payload, signature, opts)
                    if leaf is not None:
                        _check_cert_expiry_at(leaf, it)
                statements.append(json.loads(payload))
            except VerificationError:
                pass
            continue
        sig = {'key': att.get('key', ''), 'subject': att.get('subject', ''),
               'issuer': att.get('issuer', '')}
        if pem_attestor:
            continue
        if opts.key or opts.subject or opts.issuer:
            if not _signature_matches(sig, opts):
                continue
        statements.append(att['statement'])
    return Response(digest=digest, statements=statements)


# ---------------------------------------------------------------------------
# signing helpers (test fixtures / local signing — the produce side of the
# simple-signing model, mirroring what `cosign sign` writes to a registry)

def make_payload(image_ref: str, digest: str,
                 annotations: Optional[dict] = None) -> bytes:
    doc = {
        'critical': {
            'identity': {'docker-reference': image_ref.split('@')[0]},
            'image': {'docker-manifest-digest': digest},
            'type': 'cosign container image signature',
        },
        'optional': annotations or {},
    }
    return json.dumps(doc, sort_keys=True, separators=(',', ':')).encode()


def sign_payload(private_key, payload: bytes) -> bytes:
    """Sign payload bytes with a cryptography private key object."""
    from cryptography.hazmat.primitives import hashes
    from cryptography.hazmat.primitives.asymmetric import (ec, ed25519,
                                                           padding, rsa)
    if isinstance(private_key, ec.EllipticCurvePrivateKey):
        return private_key.sign(payload, ec.ECDSA(hashes.SHA256()))
    if isinstance(private_key, rsa.RSAPrivateKey):
        return private_key.sign(payload, padding.PKCS1v15(),
                                hashes.SHA256())
    if isinstance(private_key, ed25519.Ed25519PrivateKey):
        return private_key.sign(payload)
    raise TypeError(f'unsupported key type {type(private_key).__name__}')


def signature_entry(private_key, payload: bytes, cert_pem: str = '',
                    chain_pem: str = '') -> dict:
    """A registry signature entry as stored by ``cosign sign``."""
    entry = {
        'payload': base64.b64encode(payload).decode(),
        'signature': base64.b64encode(
            sign_payload(private_key, payload)).decode(),
    }
    if cert_pem:
        entry['cert'] = cert_pem
    if chain_pem:
        entry['chain'] = chain_pem
    return entry


def make_bundle(rekor_private_key, payload: bytes, signature: bytes,
                log_index: int = 1, integrated_time: Optional[int] = None,
                log_id: str = 'c0ffee', kind: str = 'hashedrekord') -> dict:
    """The offline Rekor bundle ``cosign sign`` attaches to a signature
    (test fixtures / local signing — the produce side of what
    ``_verify_tlog`` checks)."""
    import hashlib
    import time as _time
    if integrated_time is None:
        integrated_time = int(_time.time())
    if kind == 'hashedrekord':
        spec = {
            'data': {'hash': {
                'algorithm': 'sha256',
                'value': hashlib.sha256(payload).hexdigest()}},
            'signature': {'content': base64.b64encode(signature).decode()},
        }
    elif kind in ('intoto', 'dsse'):
        spec = {
            'content': {'hash': {
                'algorithm': 'sha256',
                'value': hashlib.sha256(payload).hexdigest()}},
        }
    else:  # rekord
        spec = {
            'data': {'content': base64.b64encode(payload).decode()},
            'signature': {'content': base64.b64encode(signature).decode()},
        }
    body = base64.b64encode(json.dumps({
        'apiVersion': '0.0.1', 'kind': kind, 'spec': spec,
    }, sort_keys=True, separators=(',', ':')).encode()).decode()
    bundle_payload = {
        'body': body,
        'integratedTime': integrated_time,
        'logID': log_id,
        'logIndex': log_index,
    }
    set_sig = sign_payload(rekor_private_key,
                           canonical_tlog_payload(bundle_payload))
    return {
        'SignedEntryTimestamp': base64.b64encode(set_sig).decode(),
        'Payload': bundle_payload,
    }
