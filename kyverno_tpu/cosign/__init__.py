"""Signature/attestation verification (reference: pkg/cosign).

Network sigstore verification is environment-gated; the verification
*logic* (attestor option building, key/keyless matching, statement
decoding) runs against whatever registry client is plugged in.
"""

from .cosign import (  # noqa: F401
    Options, Response, fetch_attestations, verify_signature,
)
