"""Policy lifecycle controller.

Watches policy add/update/delete and spawns UpdateRequests so
generate-existing and mutate-existing rules are applied to resources
already in the cluster; re-enqueues everything on a periodic force
reconcile (reference: pkg/policy/policy_controller.go:98 NewController,
:428-551 the UR spawning paths, :388 forceReconciliation, default 1h).
"""

from __future__ import annotations

import threading
from typing import List, Optional

from ..api.policy import Policy
from ..api.unstructured import Resource
from ..background.updaterequest import (UR_GENERATE, UR_MUTATE,
                                        UpdateRequestGenerator)
from ..engine.api import PolicyContext, RuleStatus
from ..engine.engine import Engine


class PolicyController:
    """reference: pkg/policy/policy_controller.go:57"""

    FORCE_RECONCILE_INTERVAL = 3600.0  # policy_controller.go:388 (1h)

    def __init__(self, client, engine: Optional[Engine] = None,
                 ur_generator: Optional[UpdateRequestGenerator] = None):
        self.client = client
        self.engine = engine or Engine()
        self.ur_generator = ur_generator or UpdateRequestGenerator(client)
        self._policies: dict = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- event handlers (informer-driven in the reference) ----------------

    def add_policy(self, doc: dict) -> None:
        policy = Policy(doc)
        with self._lock:
            self._policies[self._key(policy)] = policy
        self._spawn_update_requests(policy)

    def update_policy(self, old_doc: dict, new_doc: dict) -> None:
        policy = Policy(new_doc)
        with self._lock:
            self._policies[self._key(policy)] = policy
        if (old_doc.get('spec') or {}) != (new_doc.get('spec') or {}):
            self._spawn_update_requests(policy)

    def delete_policy(self, doc: dict) -> None:
        policy = Policy(doc)
        with self._lock:
            self._policies.pop(self._key(policy), None)
        # deleting a synchronize=true DATA generate policy deletes its
        # downstream resources (reference: the UR cleanup path triggered
        # by policy deletion — generate.go:848 deleteGeneratedResources;
        # cloned downstream is preserved, generate.go:242).  The list is
        # scoped to the rule's generated kind AND the per-rule label so
        # a sibling clone rule's downstream is never swept.
        from ..background.labels import (BACKGROUND_GEN_RULE_LABEL,
                                         POLICY_NAME_LABEL)
        for rule in policy.rules:
            gen = rule.raw.get('generate') or {}
            if not rule.has_generate() or not gen.get('synchronize'):
                continue
            if gen.get('clone') or gen.get('cloneList'):
                continue
            selector = {'matchLabels': {POLICY_NAME_LABEL: policy.name}}
            try:
                downstream = self.client.list_resource(
                    gen.get('apiVersion', ''), gen.get('kind', ''), '',
                    selector)
            except Exception:  # noqa: BLE001 - kind not listable
                continue
            for obj in downstream:
                meta = obj.get('metadata') or {}
                labels = meta.get('labels') or {}
                # the rule label is only stamped on generate-existing
                # downstream; when present it must name THIS rule
                stamped = labels.get(BACKGROUND_GEN_RULE_LABEL)
                if stamped is not None and stamped != rule.name:
                    continue
                try:
                    self.client.delete_resource(
                        obj.get('apiVersion', ''), obj.get('kind', ''),
                        meta.get('namespace', ''), meta.get('name', ''))
                except Exception:  # noqa: BLE001 - already gone
                    pass

    @staticmethod
    def _key(policy: Policy) -> str:
        return f'{policy.namespace}/{policy.name}' if policy.namespace \
            else policy.name

    # -- UR spawning ------------------------------------------------------

    def _spawn_update_requests(self, policy: Policy) -> None:
        """Create URs for the triggers each generate / mutate-existing
        rule matches (reference: policy_controller.go:428-551)."""
        has_generate = any(r.has_generate() for r in policy.rules)
        mutate_existing = any(
            r.has_mutate() and (r.raw.get('mutate') or {}).get('targets')
            for r in policy.rules)
        if not has_generate and not mutate_existing:
            return
        if has_generate and not policy.raw.get(
                'spec', {}).get('generateExisting',
                                policy.raw.get('spec', {}).get(
                                    'generateExistingOnPolicyUpdate')):
            has_generate = False
        if not has_generate and not mutate_existing:
            return
        for trigger in self._triggers(policy):
            resp = self.engine.filter_background_rules(
                PolicyContext(policy, new_resource=trigger.obj))
            applied = [r for r in resp.policy_response.rules
                       if r.status == RuleStatus.PASS]
            if not applied:
                continue
            request_type = UR_GENERATE if has_generate else UR_MUTATE
            self.ur_generator.apply({
                'requestType': request_type,
                'policy': self._key(Policy(policy.raw)),
                'resource': {
                    'kind': trigger.kind,
                    'apiVersion': trigger.api_version,
                    'namespace': trigger.namespace,
                    'name': trigger.name,
                },
                'context': {},
            })

    def _triggers(self, policy: Policy) -> List[Resource]:
        """List cluster resources matching the policy's rule kinds
        (reference: policy_controller.go:552 generateTriggers)."""
        out: List[Resource] = []
        seen = set()
        for rule in policy.rules:
            match = rule.raw.get('match') or {}
            filters = [match] + (match.get('any') or []) + \
                (match.get('all') or [])
            for f in filters:
                for kind in (f.get('resources') or {}).get('kinds') or []:
                    bare = str(kind).split('/')[-1]
                    try:
                        items = self.client.list_resource(
                            '', bare, '', None)
                    except Exception:  # noqa: BLE001
                        continue
                    for item in items:
                        r = Resource(item)
                        key = (r.kind, r.namespace, r.name)
                        if key not in seen:
                            seen.add(key)
                            out.append(r)
        return out

    # -- periodic force reconcile ----------------------------------------

    def run(self, interval: Optional[float] = None) -> None:
        """Start the force-reconciliation loop
        (reference: policy_controller.go:388 forceReconciliation)."""
        interval = interval or self.FORCE_RECONCILE_INTERVAL

        def loop():
            while not self._stop.wait(interval):
                self.reconcile()

        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()

    def reconcile(self) -> None:
        with self._lock:
            policies = list(self._policies.values())
        for policy in policies:
            self._spawn_update_requests(policy)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
