"""Policy / PolicyException admission validation.

Self-protection of the control plane: Policy CRs are validated on
create/update before they enter the cache (reference:
pkg/policy/validate.go:128 Validate, served by
pkg/webhooks/policy/handlers.go:43).  Implements the structural rule
checks, the background-mode variable allow-list, JSON-patch path checks
and wildcard restrictions; cluster-discovery-dependent checks (namespaced
kinds, openapi mutation dry-runs) are host concerns wired in later.
"""

from __future__ import annotations

import re
from typing import Any, List, Optional, Tuple

from ..api.policy import Policy
from ..engine.variables import RE_VARIABLES

# variables permitted in background-mode policies (reference:
# pkg/policy/background.go:21 containsUserVariables and the allow-list in
# pkg/policy/allowed_vars_test.go)
_ALLOWED_BACKGROUND_PREFIX = re.compile(
    r'^(request\.object|request\.namespace|request\.operation|'
    r'images|element|elementIndex|@|serviceAccountName|'
    r'serviceAccountNamespace)')

_RULE_TYPES = ('validate', 'mutate', 'generate', 'verifyImages')

_VALID_OPERATORS = {
    'equal', 'equals', 'notequal', 'notequals', 'in', 'anyin', 'allin',
    'notin', 'anynotin', 'allnotin', 'greaterthanorequals', 'greaterthan',
    'lessthanorequals', 'lessthan', 'durationgreaterthanorequals',
    'durationgreaterthan', 'durationlessthanorequals', 'durationlessthan',
}


class PolicyValidationError(Exception):
    pass


def validate_policy(doc: dict, client=None) -> List[str]:
    """Validate a Policy/ClusterPolicy document; returns warnings, raises
    PolicyValidationError on rejection.

    ``client`` enables the generate permission pre-flight (SSAR probes,
    reference: pkg/policy/actions.go:50); without one the mock allow-all
    auth is used, matching the reference's offline mode."""
    warnings: List[str] = []
    if not isinstance(doc, dict):
        raise PolicyValidationError('policy must be an object')
    spec = doc.get('spec') or {}
    rules = spec.get('rules')
    if not isinstance(rules, list) or not rules:
        raise PolicyValidationError('spec.rules must be a non-empty list')

    action = str(spec.get('validationFailureAction', 'Audit'))
    if action.lower() not in ('enforce', 'audit'):
        raise PolicyValidationError(
            f'spec.validationFailureAction must be Enforce or Audit, '
            f'got {action!r}')
    if action in ('enforce', 'audit'):
        # reference: checkValidationFailureAction (validate.go:138)
        warnings.append(
            'Field \'validationFailureAction\' should have the value '
            '\'Audit\' or \'Enforce\'')

    background = spec.get('background', True)
    names = set()
    for i, rule in enumerate(rules):
        path = f'spec.rules[{i}]'
        if not isinstance(rule, dict):
            raise PolicyValidationError(f'{path} must be an object')
        name = rule.get('name', '')
        if not name:
            raise PolicyValidationError(f'{path}.name is required')
        if len(name) > 63:
            raise PolicyValidationError(
                f'{path}.name must be no more than 63 characters')
        if name in names:
            raise PolicyValidationError(
                f'duplicate rule name: {name!r}')
        names.add(name)

        present = [t for t in _RULE_TYPES if rule.get(t) is not None]
        if len(present) != 1:
            raise PolicyValidationError(
                f'{path}: exactly one of {_RULE_TYPES} is required, '
                f'found {present or "none"}')

        _validate_match_block(rule.get('match'), f'{path}.match',
                              required=True)
        _validate_match_block(rule.get('exclude'), f'{path}.exclude',
                              required=False)
        if rule.get('validate') is not None:
            _validate_validate_rule(rule['validate'], f'{path}.validate')
        if rule.get('mutate') is not None:
            _validate_mutate_rule(rule['mutate'], f'{path}.mutate')
        if rule.get('generate') is not None:
            from .generate_validate import validate_generate_rule
            policy_ns = (doc.get('metadata') or {}).get('namespace', '') \
                if doc.get('kind') == 'Policy' else ''
            err = validate_generate_rule(rule, i, client, policy_ns)
            if err is not None:
                raise PolicyValidationError(err)
        _validate_conditions_shape(rule.get('preconditions'),
                                   f'{path}.preconditions')
        if background:
            _check_background_vars(rule, path, i)
        _check_wildcard_kinds(rule, path, background=bool(background))
    return warnings


def _validate_match_block(block: Any, path: str, required: bool) -> None:
    if block is None:
        if required:
            raise PolicyValidationError(f'{path} is required')
        return
    if not isinstance(block, dict):
        raise PolicyValidationError(f'{path} must be an object')
    any_f, all_f = block.get('any'), block.get('all')
    if any_f is not None and all_f is not None:
        # reference: api/kyverno/v1/match_resources_types.go validation
        raise PolicyValidationError(
            f"{path}: 'any' and 'all' cannot be used together")
    has_direct = any(k in block for k in
                     ('resources', 'subjects', 'roles', 'clusterRoles'))
    if has_direct and (any_f is not None or all_f is not None):
        raise PolicyValidationError(
            f"{path}: cannot mix 'any'/'all' with direct match filters")
    if required and not has_direct and any_f is None and all_f is None:
        raise PolicyValidationError(f'{path} must specify resources')


def _validate_validate_rule(validate: Any, path: str) -> None:
    if not isinstance(validate, dict):
        raise PolicyValidationError(f'{path} must be an object')
    forms = [k for k in ('pattern', 'anyPattern', 'deny', 'podSecurity',
                         'foreach', 'manifests', 'cel')
             if validate.get(k) is not None]
    if len(forms) != 1:
        raise PolicyValidationError(
            f'{path}: exactly one validation form is required, '
            f'found {forms or "none"}')
    if validate.get('deny') is not None:
        _validate_conditions_shape(
            (validate['deny'] or {}).get('conditions'),
            f'{path}.deny.conditions')


def _validate_mutate_rule(mutate: Any, path: str) -> None:
    if not isinstance(mutate, dict):
        raise PolicyValidationError(f'{path} must be an object')
    patches = mutate.get('patchesJson6902')
    if patches:
        # reference: validateJSONPatchPathForForwardSlash (validate.go:194)
        import yaml
        try:
            ops = yaml.safe_load(patches) if isinstance(patches, str) \
                else patches
        except Exception as e:  # noqa: BLE001
            raise PolicyValidationError(f'{path}.patchesJson6902: {e}')
        for op in ops if isinstance(ops, list) else []:
            p = (op or {}).get('path', '')
            if p and not str(p).startswith('/'):
                raise PolicyValidationError(
                    f'path must begin with a forward slash: {path}')


def _validate_conditions_shape(conditions: Any, path: str) -> None:
    if conditions is None:
        return
    blocks: List[Tuple[str, Any]] = []
    if isinstance(conditions, dict):
        blocks = [(k, conditions.get(k)) for k in ('any', 'all')
                  if conditions.get(k) is not None]
    elif isinstance(conditions, list):
        for c in conditions:
            if isinstance(c, dict) and ('any' in c or 'all' in c):
                blocks.extend((k, c.get(k)) for k in ('any', 'all')
                              if c.get(k) is not None)
            else:
                blocks.append(('', [c]))
    for _, conds in blocks:
        if not isinstance(conds, list):
            raise PolicyValidationError(f'{path} blocks must be lists')
        for c in conds:
            if not isinstance(c, dict):
                raise PolicyValidationError(
                    f'{path} entries must be objects')
            op = str(c.get('operator', ''))
            if op and op.lower() not in _VALID_OPERATORS:
                raise PolicyValidationError(
                    f'{path}: invalid operator {op!r}')


def _iter_strings(node: Any):
    if isinstance(node, str):
        yield node
    elif isinstance(node, dict):
        for k, v in node.items():
            yield from _iter_strings(k)
            yield from _iter_strings(v)
    elif isinstance(node, list):
        for v in node:
            yield from _iter_strings(v)


_FORBIDDEN_BACKGROUND_VARS = [
    re.compile(p) for p in (
        r'(?:^|[^.])(serviceAccountName)\b',
        r'(?:^|[^.])(serviceAccountNamespace)\b',
        r'(?:^|[^.])(request\.userInfo)',
        r'(?:^|[^.])(request\.roles)',
        r'(?:^|[^.])(request\.clusterRoles)',
    )]


def _userinfo_field(block: Any) -> str:
    f = block or {}
    for key in ('roles', 'clusterRoles', 'subjects'):
        if f.get(key):
            return key
    return ''


def _check_background_vars(rule: dict, path: str, idx: int = 0) -> None:
    """Background policies cannot filter on user info or reference
    admission-only variables (reference: pkg/policy/background.go:20
    containsUserVariables + :42 hasUserMatchExclude)."""
    for block_name in ('match', 'exclude'):
        block = rule.get(block_name) or {}
        p = _userinfo_field(block)
        if p:
            raise PolicyValidationError(
                f'invalid variable used at path: '
                f'spec/rules[{idx}]/{block_name}/{p}')
        for sub in ('any', 'all'):
            for i, f in enumerate(block.get(sub) or []):
                p = _userinfo_field(f)
                if p:
                    raise PolicyValidationError(
                        f'invalid variable used at path: '
                        f'spec/rules[{idx}]/{block_name}/{sub}[{i}]/{p}')
    # mutate-existing rules legitimately reference the admission request
    # (reference: background.go:28)
    if (rule.get('mutate') or {}).get('targets'):
        return
    for s in _iter_strings(rule):
        for m in RE_VARIABLES.finditer(s):
            var = m.group(2)  # the {{...}} form, as the reference reports
            for banned in _FORBIDDEN_BACKGROUND_VARS:
                if banned.search(var):
                    raise PolicyValidationError(
                        f'variable {var} is not allowed')


def _check_wildcard_kinds(rule: dict, path: str,
                          background: bool = True) -> None:
    """Wildcard kinds restrict the usable features
    (reference: pkg/policy/validate.go:1192 validateWildcard)."""
    kinds = []
    match = rule.get('match') or {}
    for f in [match] + (match.get('any') or []) + (match.get('all') or []):
        kinds.extend((f.get('resources') or {}).get('kinds') or [])
    if '*' in [str(k) for k in kinds]:
        if background:
            raise PolicyValidationError(
                'wildcard policy not allowed in background mode. Set '
                'spec.background=false to disable background mode for '
                'this policy rule')
        if len(kinds) > 1:
            raise PolicyValidationError(
                'wildard policy can not deal more than one kind')
        validate = rule.get('validate') or {}
        if rule.get('generate') is not None or \
                rule.get('verifyImages') is not None or \
                validate.get('foreach') is not None:
            raise PolicyValidationError(
                'wildcard policy does not support rule type')
    if any('*' in str(k) for k in kinds):
        validate = rule.get('validate') or {}
        if validate.get('pattern') is not None or \
                validate.get('anyPattern') is not None:
            raise PolicyValidationError(
                f'{path}: wildcard policy can only deal with the '
                f'metadata field of the resource if none of the '
                f"'request.object.spec' fields are used")


# ---------------------------------------------------------------------------
# admission endpoints (reference: pkg/webhooks/policy/handlers.go:43)

def validate_policy_admission(request: dict, client=None) -> dict:
    from ..webhooks import admission
    uid = request.get('uid', '')
    doc = admission.request_resource(request)
    try:
        warnings = validate_policy(doc, client)
    except PolicyValidationError as e:
        return admission.response(uid, False, str(e))
    return admission.response(uid, True, '', warnings)


def validate_exception_admission(request: dict) -> dict:
    from ..webhooks import admission
    uid = request.get('uid', '')
    doc = admission.request_resource(request)
    spec = (doc or {}).get('spec') or {}
    errs = []
    if not spec.get('match'):
        errs.append('spec.match is required')
    exceptions = spec.get('exceptions')
    if not isinstance(exceptions, list) or not exceptions:
        errs.append('spec.exceptions must be a non-empty list')
    else:
        for i, ex in enumerate(exceptions):
            if not (ex or {}).get('policyName'):
                errs.append(f'spec.exceptions[{i}].policyName is required')
    if errs:
        return admission.response(uid, False, '; '.join(errs))
    return admission.response(uid, True)
