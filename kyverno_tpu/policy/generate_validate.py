"""Generate-rule validation with permission pre-flight (reference:
pkg/policy/generate/validate.go Generate.Validate, pkg/policy/actions.go
validateActions).

Before a generate policy is admitted, the controller verifies its own
service account can create/update/get/delete the target kinds — each
probe is a SelfSubjectAccessReview (``auth.CanI``).  Offline contexts
(CLI apply/test) use :class:`~..auth.FakeAuth`, mirroring the
reference's mock mode (actions.go:53).
"""

from __future__ import annotations

from typing import Optional, Tuple

from ..auth import Auth, FakeAuth
from ..auth.auth import is_variable
from ..utils.wildcard import contains_wildcard

_PERM_HINT = ("Update permissions in ClusterRole 'kyverno:generate'")


class GenerateValidator:
    """reference: pkg/policy/generate/validate.go:19 Generate."""

    def __init__(self, generation: dict, auth=None):
        self.rule = generation or {}
        self.auth = auth if auth is not None else FakeAuth()

    def validate(self) -> Tuple[str, Optional[str]]:
        """Returns (path, error-message) — error None means valid
        (reference: validate.go:40 Validate)."""
        rule = self.rule
        clone = rule.get('clone') or {}
        clone_list = rule.get('cloneList') or {}
        has_data = rule.get('data') is not None
        has_clone = bool(clone)
        if has_data and has_clone:
            return '', 'only one of data or clone can be specified'
        if has_clone and clone_list.get('kinds'):
            return '', 'only one of clone or cloneList can be specified'

        kind = rule.get('kind', '')
        name = rule.get('name', '')
        namespace = rule.get('namespace', '')

        if not clone_list.get('kinds'):
            if not name:
                return 'name', 'name cannot be empty'
            if not kind:
                return 'kind', 'kind cannot be empty'
        else:
            if name:
                return 'name', \
                    'with cloneList, generate.name. should not be specified.'
            if kind:
                return 'kind', \
                    'with cloneList, generate.kind. should not be specified.'

        selector = clone_list.get('selector')
        if selector is not None and contains_wildcard(str(selector)):
            return 'selector', 'wildcard characters `*/?` not supported'

        if has_clone:
            path, err = self._validate_clone(clone, clone_list, kind)
            if err is not None:
                return f'clone.{path}' if path else 'clone', err

        if clone_list.get('kinds'):
            for gvk in clone_list['kinds']:
                # the full group/version/Kind string rides into the SSAR
                # so group-qualified kinds probe the right GVR
                err = self._can_i_generate(str(gvk), namespace)
                if err is not None:
                    return '', err
        else:
            err = self._can_i_generate(kind, namespace)
            if err is not None:
                return '', err
        return '', None

    def _validate_clone(self, clone: dict, clone_list: dict,
                        kind: str) -> Tuple[str, Optional[str]]:
        """reference: validate.go:106 validateClone — clone sources need
        'get' (and the sync sweep 'delete' on the target kind)."""
        if not clone_list.get('kinds') and not clone.get('name'):
            return 'name', 'name cannot be empty'
        namespace = clone.get('namespace', '')
        if is_variable(kind) or is_variable(namespace):
            return '', None
        if not self.auth.can_i_get(kind, namespace):
            return '', (f"kyverno does not have permissions to 'get' "
                        f'resource {kind}/{namespace}. {_PERM_HINT}')
        return '', None

    def _can_i_generate(self, kind: str, namespace: str) -> Optional[str]:
        """reference: validate.go:130 canIGenerate — create/update/get/
        delete on the target kind, skipped when either field is an
        unresolved variable."""
        from ..auth.auth import can_i_generate_error
        return can_i_generate_error(self.auth, kind, namespace)


_CLUSTER_SCOPED_KINDS = {
    'Namespace', 'Node', 'ClusterRole', 'ClusterRoleBinding',
    'CustomResourceDefinition', 'ClusterPolicy', 'PriorityClass',
    'StorageClass', 'PersistentVolume', 'ValidatingWebhookConfiguration',
    'MutatingWebhookConfiguration',
}


def _check_namespaced_generate(rule: dict, generation: dict,
                               policy_namespace: str) -> Optional[str]:
    """A namespaced Policy may only generate into its own namespace
    (reference: pkg/policy/validate.go:1115-1140)."""
    name = rule.get('name', '')
    kind = generation.get('kind', '')
    if kind and kind in _CLUSTER_SCOPED_KINDS:
        return (f'path: spec.rules[{name}]: a namespaced policy cannot '
                f'generate cluster-wide resources')
    target_ns = generation.get('namespace', '')
    if kind and not is_variable(target_ns) and \
            target_ns != policy_namespace:
        return (f'path: spec.rules[{name}]: a namespaced policy cannot '
                f'generate resources in other namespaces, expected: '
                f'{policy_namespace}, received: {target_ns}')
    clone = generation.get('clone') or {}
    if clone.get('name'):
        clone_ns = clone.get('namespace', '')
        if not is_variable(clone_ns) and clone_ns != policy_namespace:
            return (f'path: spec.rules[{name}]: a namespaced policy '
                    f'cannot clone resources to or from other '
                    f'namespaces, expected: {policy_namespace}, '
                    f'received: {clone_ns}')
    clone_list = generation.get('cloneList') or {}
    if clone_list.get('kinds'):
        cl_ns = clone_list.get('namespace', '')
        if not is_variable(cl_ns) and cl_ns != policy_namespace:
            return (f'path: spec.rules[{name}]: a namespaced policy '
                    f'cannot clone resources to or from other '
                    f'namespaces, expected: {policy_namespace}, '
                    f'received: {cl_ns}')
    return None


def validate_generate_rule(rule: dict, index: int, client=None,
                           policy_namespace: str = '') -> Optional[str]:
    """Validate one rule's generate action; returns an error string or
    None (reference: pkg/policy/actions.go:24 validateActions — mock mode
    when no client is supplied)."""
    generation = rule.get('generate')
    if generation is None:
        return None
    if policy_namespace:
        err = _check_namespaced_generate(rule, generation,
                                         policy_namespace)
        if err is not None:
            return err
    auth = Auth(client) if client is not None else FakeAuth()
    path, err = GenerateValidator(generation, auth).validate()
    if err is not None:
        prefix = f'spec.rules[{index}].generate.'
        return f'path: {prefix}{path}.: {err}' if path \
            else f'path: {prefix}: {err}'
    # reference: actions.go:65 — generating the kind the rule matches on
    # would retrigger itself
    match = rule.get('match') or {}
    match_kinds = list((match.get('resources') or {}).get('kinds') or [])
    for f in (match.get('any') or []) + (match.get('all') or []):
        match_kinds.extend((f.get('resources') or {}).get('kinds') or [])
    if generation.get('kind') and generation.get('kind') in match_kinds:
        return 'generation kind and match resource kind should not be ' \
            'the same'
    return None
