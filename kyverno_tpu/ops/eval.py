"""Batched rule evaluation on device.

``build_evaluator(cps)`` returns a jitted function mapping the encoded batch
tensors to a status matrix ``[R, P]`` (0=pass, 1=fail, 2=skip) for the
compiled programs. The program structure is baked in at trace time, so XLA
sees straight-line fused elementwise ops over ``[R]`` / ``[R, E]`` tensors —
the policy set is *compiled*, not interpreted.

Sharding: the batch axis is data-parallel; ``shard_batch`` places tensors on
a 1-D mesh so the same jitted function scales across chips via pjit/GSPMD.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, List, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ..compiler.encode import TAIL_LEN, Batch
from ..compiler.ir import (MAX_ELEMS, STR_LEN, TAG_ARRAY, TAG_BOOL, TAG_FLOAT,
                           TAG_INT, TAG_MISSING, TAG_NULL, TAG_STRING,
                           BoolExpr, CompiledPolicySet, ElementBlock, Leaf,
                           RuleProgram)

STATUS_PASS, STATUS_FAIL, STATUS_SKIP = 0, 1, 2

_CONVERTIBLE_TAGS = (TAG_STRING, TAG_INT, TAG_FLOAT, TAG_BOOL)


def _str_const(s: str, length: int) -> np.ndarray:
    b = s.encode('utf-8')[:length]
    out = np.zeros(length, np.uint8)
    out[:len(b)] = np.frombuffer(b, np.uint8)
    return out


def _tail_const(s: str) -> np.ndarray:
    b = s.encode('utf-8')[-TAIL_LEN:]
    out = np.zeros(TAIL_LEN, np.uint8)
    out[TAIL_LEN - len(b):] = np.frombuffer(b, np.uint8)
    return out


class _SlotRef:
    """Names of the tensors for one slot inside the flat batch dict."""

    def __init__(self, prefix: str):
        self.prefix = prefix

    def __getattr__(self, name):
        return f'{self.prefix}_{name}'


def build_evaluator(cps: CompiledPolicySet):
    slot_prefix = {slot: f's{i}' for i, slot in enumerate(cps.slots)}
    array_prefix = {}
    array_paths = []
    for prog in cps.programs:
        for block in prog.elements:
            if block.array_path not in array_prefix:
                array_prefix[block.array_path] = f'a{len(array_paths)}'
                array_paths.append(block.array_path)

    def leaf_eval(t: Dict[str, jnp.ndarray], leaf: Leaf) -> jnp.ndarray:
        p = slot_prefix[leaf.slot]
        tag = t[f'{p}_tag']
        op = leaf.op

        def is_tag(*tags):
            r = tag == tags[0]
            for x in tags[1:]:
                r = r | (tag == x)
            return r

        convertible = is_tag(*_CONVERTIBLE_TAGS)
        if op == 'true':
            result = jnp.ones_like(tag, dtype=bool)
        elif op == 'absent':
            return tag == TAG_MISSING  # missing_ok does not apply
        elif op == 'star':
            return ~is_tag(TAG_MISSING, TAG_NULL)
        elif op == 'any_str':
            result = convertible
        elif op == 'nonempty':
            result = (is_tag(TAG_INT, TAG_FLOAT, TAG_BOOL) |
                      ((tag == TAG_STRING) & (t[f'{p}_str_len'] > 0)))
        elif op == 'convertible':
            result = convertible
        elif op == 'eq_bool':
            result = (tag == TAG_BOOL) & (
                (t[f'{p}_milli'] != 0) == bool(leaf.operand))
        elif op == 'eq_null':
            result = ((tag == TAG_NULL) |
                      (is_tag(TAG_BOOL, TAG_INT, TAG_FLOAT) &
                       t[f'{p}_milli_ok'] & (t[f'{p}_milli'] == 0)) |
                      ((tag == TAG_STRING) & (t[f'{p}_str_len'] == 0)))
        elif op == 'eq_int':
            target = int(leaf.operand) * 1000
            ok = t[f'{p}_milli_ok'] & (t[f'{p}_milli'] == target)
            result = ok & (is_tag(TAG_INT, TAG_FLOAT) |
                           ((tag == TAG_STRING) & t[f'{p}_str_is_int']))
        elif op == 'eq_float':
            from fractions import Fraction
            target = int(Fraction(str(leaf.operand)) * 1000)
            ok = t[f'{p}_milli_ok'] & (t[f'{p}_milli'] == target)
            result = ok & (is_tag(TAG_INT, TAG_FLOAT) |
                           ((tag == TAG_STRING) & t[f'{p}_str_is_float']))
        elif op == 'cmp_qty':
            # compareDuration/Quantity/String are a plain OR chain in the
            # reference, so quantity validity is just "parses as quantity"
            # (milli_ok covers that for strings)
            cmp, operand = leaf.operand
            valid = t[f'{p}_milli_ok'] & is_tag(TAG_INT, TAG_FLOAT, TAG_NULL,
                                                TAG_STRING)
            result = valid & _cmp(t[f'{p}_milli'], operand, cmp)
        elif op == 'cmp_dur':
            cmp, operand = leaf.operand
            valid = t[f'{p}_nanos_ok'] & is_tag(TAG_STRING, TAG_NULL)
            result = valid & _cmp(t[f'{p}_nanos'], operand, cmp)
        elif op == 'eq_str':
            const = _str_const(leaf.operand, STR_LEN)
            blen = len(leaf.operand.encode('utf-8'))
            result = (convertible & (t[f'{p}_str_len'] == blen) &
                      jnp.all(t[f'{p}_str_head'] == const, axis=-1))
        elif op == 'prefix':
            b = leaf.operand.encode('utf-8')
            const = np.frombuffer(b, np.uint8)
            head = t[f'{p}_str_head'][..., :len(b)]
            result = (convertible & (t[f'{p}_str_len'] >= len(b)) &
                      jnp.all(head == const, axis=-1))
        elif op == 'suffix':
            b = leaf.operand.encode('utf-8')
            const = np.frombuffer(b, np.uint8)
            tail = t[f'{p}_str_tail'][..., TAIL_LEN - len(b):]
            result = (convertible & (t[f'{p}_str_len'] >= len(b)) &
                      jnp.all(tail == const, axis=-1))
        elif op == 'min_len':
            result = convertible & (t[f'{p}_str_len'] >= int(leaf.operand))
        else:
            raise ValueError(f'unknown leaf op {op!r}')

        if leaf.missing_ok:
            return result | (tag == TAG_MISSING)
        return result

    def expr_eval(t, expr: BoolExpr) -> jnp.ndarray:
        if expr.kind == 'leaf':
            return leaf_eval(t, expr.leaf)
        if expr.kind == 'and':
            out = expr_eval(t, expr.children[0])
            for c in expr.children[1:]:
                out = out & expr_eval(t, c)
            return out
        if expr.kind == 'or':
            out = expr_eval(t, expr.children[0])
            for c in expr.children[1:]:
                out = out | expr_eval(t, c)
            return out
        if expr.kind == 'not':
            return ~expr_eval(t, expr.children[0])
        raise ValueError(expr.kind)

    def block_status(t, block: ElementBlock) -> jnp.ndarray:
        """Tri-state per resource for one element block."""
        ap = array_prefix[block.array_path]
        arr_tag = t[f'{ap}_tag']
        count = t[f'{ap}_count']
        valid = jnp.arange(MAX_ELEMS)[None, :] < count[:, None]
        cons = expr_eval(t, block.constraint)
        if cons.ndim == 1:  # constraint referenced no element slot
            cons = jnp.broadcast_to(cons[:, None], valid.shape)
        if block.condition is not None:
            cond = expr_eval(t, block.condition)
            if cond.ndim == 1:
                cond = jnp.broadcast_to(cond[:, None], valid.shape)
        else:
            cond = jnp.ones_like(valid)
        if block.mode == 'exists':
            # existence anchor: ≥1 element must satisfy; empty array fails,
            # missing key passes (reference: anchor/handlers.go:228)
            satisfied = jnp.any(valid & cons, axis=1)
            missing = arr_tag == TAG_MISSING
            wrong_type = (arr_tag != TAG_ARRAY) & ~missing
            status = jnp.where(
                missing, STATUS_PASS,
                jnp.where(wrong_type | ~satisfied, STATUS_FAIL, STATUS_PASS))
            return status.astype(jnp.int8)
        fail_e = valid & cond & ~cons
        skip_e = valid & ~cond
        pass_e = valid & cond & cons
        any_fail = jnp.any(fail_e, axis=1)
        any_pass = jnp.any(pass_e, axis=1)
        any_skip = jnp.any(skip_e, axis=1)
        # array itself missing or not a list → structural failure
        bad_array = arr_tag != TAG_ARRAY
        status = jnp.where(
            bad_array | any_fail, STATUS_FAIL,
            jnp.where(~any_pass & any_skip, STATUS_SKIP, STATUS_PASS))
        return status.astype(jnp.int8)

    def program_status(t, prog: RuleProgram) -> jnp.ndarray:
        n = t[next(iter(t))].shape[0]
        units: List[jnp.ndarray] = []
        if prog.scalar_condition is not None:
            cond_ok = expr_eval(t, prog.scalar_condition)
            units.append(jnp.where(cond_ok, STATUS_PASS,
                                   STATUS_SKIP).astype(jnp.int8))
        if prog.scalar is not None:
            ok = expr_eval(t, prog.scalar)
            units.append(jnp.where(ok, STATUS_PASS,
                                   STATUS_FAIL).astype(jnp.int8))
        for block in prog.elements:
            units.append(block_status(t, block))
        if not units:
            return jnp.zeros(n, jnp.int8)
        # first non-pass unit in order decides (mirrors the walk's
        # first-error-wins semantics)
        status = units[0]
        for u in units[1:]:
            status = jnp.where(status == STATUS_PASS, u, status)
        return status

    def evaluate(t: Dict[str, jnp.ndarray]) -> jnp.ndarray:
        cols = [program_status(t, prog) for prog in cps.programs]
        if not cols:
            n = t[next(iter(t))].shape[0] if t else 0
            return jnp.zeros((n, 0), jnp.int8)
        return jnp.stack(cols, axis=1)

    jitted = jax.jit(evaluate)

    def call(t: Dict[str, Any]) -> jnp.ndarray:
        # i64 lanes are required: quantity milli-values span past 2^31
        # (4Gi milli ≈ 4.3e12). Scope x64 to this call instead of flipping
        # the process-global flag at import time; transfers of the int64
        # inputs must happen inside the scope too (see shard_batch).
        with enable_x64():
            return jitted(t)

    call.jitted = jitted
    return call


def enable_x64():
    return jax.enable_x64()


def _cmp(value, operand, cmp):
    if cmp == '>':
        return value > operand
    if cmp == '>=':
        return value >= operand
    if cmp == '<':
        return value < operand
    if cmp == '<=':
        return value <= operand
    if cmp == '==':
        return value == operand
    if cmp == '!=':
        return value != operand
    raise ValueError(cmp)


def shard_batch(tensors: Dict[str, np.ndarray], mesh=None,
                axis: str = 'data') -> Dict[str, Any]:
    """Place batch tensors, optionally sharded over a 1-D mesh. int64
    inputs are transferred inside an x64 scope so they are not downcast."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    with enable_x64():
        if mesh is None:
            return {k: jnp.asarray(v) for k, v in tensors.items()}
        out = {}
        for k, v in tensors.items():
            spec = P(axis, *([None] * (v.ndim - 1)))
            out[k] = jax.device_put(v, NamedSharding(mesh, spec))
        return out
