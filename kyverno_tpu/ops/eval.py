"""Batched rule evaluation on device (IR v2: tri-state status programs).

``build_evaluator(cps)`` returns a jitted function mapping the encoded
batch tensors to ``(status [R, P], detail [R, P], fdet [R, P])`` matrices for the
compiled programs, where status is one of

  0 PASS   1 FAIL   2 SKIP   3 HOST   4 SKIP_PRECOND

``HOST`` marks (resource, rule) pairs the device could not decide exactly
(Kleene UNKNOWN anywhere in the tree); the scanner re-runs just those on
the host engine, so exactness is never lost.  ``detail`` carries the
anyPattern index that passed (for the pass-message template).

The program structure is baked in at trace time: XLA sees straight-line
fused elementwise ops over ``[R]`` / ``[R, E]`` tensors — the policy set
is *compiled*, not interpreted (reference's per-resource tree walk:
pkg/engine/validate/validate.go).

Boolean facts are tracked as Kleene pairs ``(t, f)`` (known-true,
known-false); any value the encoder could not represent exactly simply
never sets either bit and surfaces as HOST.
"""

from __future__ import annotations

import json as _json
import os
from fractions import Fraction
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ..compiler.encode import _needs_cached
from ..compiler.ir import (STR_LEN, TAG_ARRAY, TAG_BOOL, TAG_FLOAT, TAG_INT,
                           TAG_MAP, TAG_MISSING, TAG_NULL, TAG_STRING,
                           TAIL_LEN, BoolExpr, CompiledPolicySet, CondCheck,
                           Leaf, RuleProgram, StatusExpr)
from ..compiler.ir import (STATUS_FAIL, STATUS_HOST, STATUS_PASS, STATUS_SKIP,
                           STATUS_SKIP_PRECOND, STATUS_VAR_ERR)
from ..engine import pattern as leaf_pattern
from ..engine.operators import _sprint
from ..utils.duration import parse_duration
from ..utils.quantity import Quantity

_I64_MAX = (1 << 63) - 1


def _const_bytes(s: str) -> bytes:
    return s.encode('utf-8')


class _K:
    """Kleene pair of known-true / known-false boolean arrays."""

    __slots__ = ('t', 'f')

    def __init__(self, t, f):
        self.t = t
        self.f = f

    @staticmethod
    def known(v):
        return _K(v, ~v)

    @staticmethod
    def const(shape, value: bool):
        ones = jnp.ones(shape, bool)
        return _K(ones, ~ones) if value else _K(~ones, ones)

    @staticmethod
    def false_const(shape):
        return _K.const(shape, False)

    def negate(self) -> '_K':
        return _K(self.f, self.t)

    def __and__(self, other: '_K') -> '_K':
        return _K(self.t & other.t, self.f | other.f)

    def __or__(self, other: '_K') -> '_K':
        return _K(self.t | other.t, self.f & other.f)

    def unknown(self):
        return ~(self.t | self.f)


def _k_all(parts: List[_K]) -> _K:
    out = parts[0]
    for p in parts[1:]:
        out = out & p
    return out


def _k_any(parts: List[_K]) -> _K:
    out = parts[0]
    for p in parts[1:]:
        out = out | p
    return out


def _cmp_arr(value, operand, cmp: str):
    if cmp == '>':
        return value > operand
    if cmp == '>=':
        return value >= operand
    if cmp == '<':
        return value < operand
    if cmp == '<=':
        return value <= operand
    if cmp == '==':
        return value == operand
    if cmp == '!=':
        return value != operand
    raise ValueError(cmp)


def _frac_thresholds(cmp: str, target: Fraction) -> Tuple[str, int]:
    """Rewrite ``milli cmp target`` (target rational ×1000) as an integer
    comparison on the milli lane (exact for any rational threshold)."""
    import math
    if target.denominator == 1:
        return cmp, int(target)
    if cmp == '>':
        return '>=', math.floor(target) + 1
    if cmp == '>=':
        return '>=', math.ceil(target)
    if cmp == '<':
        return '<=', math.ceil(target) - 1
    if cmp == '<=':
        return '<=', math.floor(target)
    if cmp == '==':
        return '==', None  # never equal — caller handles
    if cmp == '!=':
        return '!=', None  # always unequal
    raise ValueError(cmp)


class _View:
    """Accessor for one lane bundle (slot or gather elements) in the flat
    tensor dict, plus tag predicates shared by all ops."""

    _BYTE_LANES = frozenset({'str_head', 'str_tail'})

    def __init__(self, t: Dict[str, Any], prefix: str, elem: int = None):
        self._t = t
        self._p = prefix
        # gather element index — the LAST gather axis, so the same view
        # works for [R, G] gathers and [R, FE, EG] per-foreach gathers
        self._elem = elem

    def lane(self, name: str):
        arr = self._t[f'{self._p}_{name}']
        if self._elem is not None:
            if name in self._BYTE_LANES:
                arr = arr[..., self._elem, :]
            else:
                arr = arr[..., self._elem]
        return arr

    def has(self, name: str) -> bool:
        return f'{self._p}_{name}' in self._t

    @property
    def tag(self):
        return self.lane('tag')

    def is_tag(self, *tags):
        tag = self.tag
        r = tag == tags[0]
        for x in tags[1:]:
            r = r | (tag == x)
        return r

    @property
    def convertible(self):
        return self.is_tag(TAG_STRING, TAG_INT, TAG_FLOAT, TAG_BOOL)

    @property
    def numish(self):
        return self.is_tag(TAG_INT, TAG_FLOAT)

    @property
    def nullish(self):
        # missing keys validate as nil (anchor.py handle_element default:
        # resource_map.get(key) → None)
        return self.is_tag(TAG_NULL, TAG_MISSING)

    @property
    def arrayish(self):
        return self.tag == TAG_ARRAY

    @property
    def milli(self):
        return self.lane('milli')

    @property
    def milli_ok(self):
        # missing == nil: _number_to_string(None) == '0' → 0 exactly
        return self.lane('milli_ok') | (self.tag == TAG_MISSING)

    @property
    def nanos(self):
        return self.lane('nanos')

    @property
    def nanos_ok(self):
        return self.lane('nanos_ok') | (self.tag == TAG_MISSING)

    @property
    def str_len(self):
        return self.lane('str_len')

    @property
    def is_zero_str(self):
        """The literal string '0' (excluded from operator duration parse,
        reference: pkg/engine/variables/operator/operator.go:80)."""
        return self.lane('lit_zero')

    # duration usable under LEAF semantics (pattern.py _compare_duration:
    # the plain string form parses, '0' included).  The encoder sets
    # nanos_ok for int 0 ('0' parses) and nulls; floats never parse
    # ('0.000000' has no unit).
    @property
    def dur_leaf(self):
        return (((self.tag == TAG_STRING) & self.lane('str_is_dur')) |
                ((self.tag == TAG_INT) & self.lane('nanos_ok')) |
                self.nullish)

    # string equality / prefix / suffix against a constant ---------------

    def eq_const(self, s: str) -> _K:
        b = _const_bytes(s)
        conv = self.convertible
        head = self.lane('str_head')
        w = head.shape[-1]
        if len(b) <= w:
            # value bytes past str_len are zero, so a full-window compare
            # against the zero-padded constant is exact string equality
            const = np.zeros(w, np.uint8)
            const[:len(b)] = np.frombuffer(b, np.uint8)
            hit = (conv & (self.str_len == len(b)) &
                   jnp.all(head == const, axis=-1))
            return _K(hit, ~hit & ~self.arrayish)
        # constant longer than the head window: equal length + matching
        # prefix is undecidable (analysis sizes windows so this is rare)
        maybe = conv & (self.str_len == len(b)) & \
            jnp.all(head == np.frombuffer(b[:w], np.uint8), axis=-1)
        return _K(jnp.zeros_like(maybe), ~maybe & ~self.arrayish)

    def prefix_const(self, s: str) -> _K:
        b = _const_bytes(s)
        conv = self.convertible
        head = self.lane('str_head')
        w = head.shape[-1]
        if len(b) <= w:
            const = np.frombuffer(b, np.uint8)
            hit = conv & (self.str_len >= len(b)) & \
                jnp.all(head[..., :len(b)] == const, axis=-1)
            return _K(hit, ~hit & ~self.arrayish)
        maybe = conv & (self.str_len >= len(b)) & \
            jnp.all(head == np.frombuffer(b[:w], np.uint8), axis=-1)
        return _K(jnp.zeros_like(maybe), ~maybe & ~self.arrayish)

    def suffix_const(self, s: str) -> _K:
        b = _const_bytes(s)
        conv = self.convertible
        tail = self.lane('str_tail')[..., TAIL_LEN - len(b):]
        const = np.frombuffer(b, np.uint8)
        hit = conv & (self.str_len >= len(b)) & jnp.all(tail == const, axis=-1)
        return _K(hit, ~hit & ~self.arrayish)

    def wildcard_const(self, pattern: str) -> _K:
        """Glob ``pattern`` (utils/wildcard.py semantics) vs the value's
        string form; undecidable when the value exceeds the byte window or
        '?' meets non-ASCII bytes (rune vs byte width)."""
        conv = self.convertible
        head = self.lane('str_head')
        w = head.shape[-1]
        vlen = jnp.minimum(self.str_len, w)
        pb = _const_bytes(pattern)
        # dp[j]: pattern consumed so far matches value[:j]
        shape = head.shape[:-1]
        dp = jnp.zeros(shape + (w + 1,), bool)
        dp = dp.at[..., 0].set(True)
        pos_valid = jnp.arange(w) < vlen[..., None]
        for ch in pb:
            if ch == ord('*'):
                dp = jnp.cumsum(dp.astype(jnp.int32), axis=-1) > 0
            elif ch == ord('?'):
                step = dp[..., :-1] & pos_valid
                dp = jnp.concatenate(
                    [jnp.zeros(shape + (1,), bool), step], axis=-1)
            else:
                step = dp[..., :-1] & (head == ch) & pos_valid
                dp = jnp.concatenate(
                    [jnp.zeros(shape + (1,), bool), step], axis=-1)
        matched = jnp.take_along_axis(dp, vlen[..., None], axis=-1)[..., 0]
        in_window = self.str_len <= w
        if b'?' in bytes(pb):
            ascii_ok = jnp.all((head < 0x80) | ~pos_valid, axis=-1)
        else:
            ascii_ok = jnp.ones(shape, bool)
        decid = in_window & ascii_ok
        t = conv & decid & matched
        f = (~self.arrayish) & (~conv | (decid & ~matched))
        return _K(t, f)

    def match_const_pattern(self, s: str) -> _K:
        """wildcard.match(const_pattern, value_string) — classified into
        the cheapest lane comparison (ir.classify_wildcard, shared with
        the compiler and the lane-need analysis)."""
        from ..compiler.ir import classify_wildcard
        kind, parts = classify_wildcard(s)
        if kind == 'eq':
            return self.eq_const(s)
        if kind == 'any':
            return _K(self.convertible, ~self.convertible & ~self.arrayish)
        if kind == 'nonempty':
            t = (self.is_tag(TAG_INT, TAG_FLOAT, TAG_BOOL) |
                 ((self.tag == TAG_STRING) & (self.str_len > 0)))
            return _K(t, ~t & ~self.arrayish)
        if kind == 'prefix':
            return self.prefix_const(parts[0])
        if kind == 'suffix':
            return self.suffix_const(parts[0])
        if kind == 'prefix_suffix':
            min_len = (len(parts[0].encode('utf-8')) +
                       len(parts[1].encode('utf-8')))
            ok = self.convertible & (self.str_len >= min_len)
            conv_len = _K(ok, ~ok & ~self.arrayish)
            return (self.prefix_const(parts[0]) &
                    self.suffix_const(parts[1]) & conv_len)
        return self.wildcard_const(s)


# ---------------------------------------------------------------------------
# leaf (pattern) ops over a view — semantics: kyverno_tpu/engine/pattern.py
# (reference: pkg/engine/pattern/pattern.go)

def leaf_op_tf(v: _View, op: str, operand: Any) -> _K:
    arr = v.arrayish

    if op == 'true':
        return _K.const(v.tag.shape, True)
    if op == 'absent':
        return _K.known(v.tag == TAG_MISSING)
    if op == 'present':
        return _K.known(v.tag != TAG_MISSING)
    if op == 'star':
        # anchor default-key "*": passes on any non-nil value
        return _K.known(~v.nullish)
    if op == 'is_map':
        return _K.known(v.tag == TAG_MAP)
    if op == 'is_array':
        return _K.known(v.tag == TAG_ARRAY)
    if op == 'any_str':
        return _K(v.convertible, ~v.convertible & ~arr)
    if op == 'nonempty':
        t = (v.is_tag(TAG_INT, TAG_FLOAT, TAG_BOOL) |
             ((v.tag == TAG_STRING) & (v.str_len > 0)))
        return _K(t, ~t & ~arr)
    if op == 'convertible':
        return _K(v.convertible, ~v.convertible & ~arr)
    if op == 'eq_bool':
        t = (v.tag == TAG_BOOL) & ((v.milli != 0) == bool(operand))
        return _K(t, ~t & ~arr)
    if op == 'eq_null':
        t = (v.nullish |
             (v.is_tag(TAG_BOOL, TAG_INT, TAG_FLOAT) & v.milli_ok &
              (v.milli == 0)) |
             ((v.tag == TAG_STRING) & (v.str_len == 0)))
        return _K(t, ~t & ~arr)
    if op in ('eq_int', 'eq_float'):
        target = (int(operand) * 1000 if op == 'eq_int'
                  else int(Fraction(str(operand)) * 1000))
        flag = 'str_is_int' if op == 'eq_int' else 'str_is_float'
        cand = v.numish | ((v.tag == TAG_STRING) & v.lane(flag))
        mok = v.lane('milli_ok')
        t = cand & mok & (v.milli == target)
        u = cand & ~mok
        return _K(t, ~t & ~u & ~arr)
    if op == 'cmp_qty':
        cmp, target = operand
        cand = (v.numish | v.nullish |
                ((v.tag == TAG_STRING) & v.lane('str_is_qty')))
        mok = v.milli_ok
        t = cand & mok & _cmp_arr(v.milli, target, cmp)
        u = cand & ~mok
        return _K(t, ~t & ~u & ~arr)
    if op == 'cmp_dur':
        cmp, target = operand
        cand = v.dur_leaf
        t = cand & v.nanos_ok & _cmp_arr(v.nanos, target, cmp)
        # parsed-but-overflowed durations are undecidable
        u = (v.tag == TAG_STRING) & v.lane('str_is_dur') & \
            ~v.lane('nanos_ok')
        return _K(t, ~t & ~u & ~arr)
    if op == 'eq_str':
        return v.eq_const(operand)
    if op == 'prefix':
        return v.prefix_const(operand)
    if op == 'suffix':
        return v.suffix_const(operand)
    if op == 'min_len':
        t = v.convertible & (v.str_len >= int(operand))
        return _K(t, ~t & ~arr)
    if op == 'wildcard':
        return v.wildcard_const(operand)
    if op == 'truthy':
        # Python bool(value): maps/arrays are truthy only when non-empty,
        # which the lanes can't see → unknown
        mok = v.lane('milli_ok')
        num = v.is_tag(TAG_BOOL, TAG_INT, TAG_FLOAT)
        t = (num & ((v.milli != 0) | ~mok)) | \
            ((v.tag == TAG_STRING) & (v.str_len > 0))
        f = v.nullish | (num & mok & (v.milli == 0)) | \
            ((v.tag == TAG_STRING) & (v.str_len == 0))
        return _K(t, f)
    if op == 'is_true':
        # `value is True` — identity, so every non-bool is known-False
        t = (v.tag == TAG_BOOL) & (v.milli != 0)
        return _K(t, ~t)
    if op == 'is_false':
        t = (v.tag == TAG_BOOL) & (v.milli == 0)
        return _K(t, ~t)
    if op == 'is_zero_num':
        # Python ==: 0 == 0.0 == False; strings/maps/arrays never equal 0
        num = v.is_tag(TAG_BOOL, TAG_INT, TAG_FLOAT)
        t = num & v.lane('milli_ok') & (v.milli == 0)
        return _K(t, ~t)
    raise ValueError(f'unknown leaf op {op!r}')


# ---------------------------------------------------------------------------
# string-term evaluation for condition values that are range / pattern
# strings (leaf_pattern.validate semantics over a lane view)

def string_term_tf(v: _View, term: str) -> _K:
    op = leaf_pattern.get_operator_from_string_pattern(term)
    if op == leaf_pattern.OP_IN_RANGE:
        m = leaf_pattern.IN_RANGE_RE.match(term)
        return (string_term_tf(v, f'>= {m.group(1)}') &
                string_term_tf(v, f'<= {m.group(2)}'))
    if op == leaf_pattern.OP_NOT_IN_RANGE:
        m = leaf_pattern.NOT_IN_RANGE_RE.match(term)
        return (string_term_tf(v, f'< {m.group(1)}') |
                string_term_tf(v, f'> {m.group(2)}'))
    operand = term[len(op):].strip(' ') if op else term
    cmp = {leaf_pattern.OP_MORE: '>', leaf_pattern.OP_MORE_EQUAL: '>=',
           leaf_pattern.OP_LESS: '<', leaf_pattern.OP_LESS_EQUAL: '<=',
           leaf_pattern.OP_EQUAL: '==',
           leaf_pattern.OP_NOT_EQUAL: '!='}[op or leaf_pattern.OP_EQUAL]
    alts: List[_K] = []
    try:
        nanos = parse_duration(operand)
        alts.append(leaf_op_tf(v, 'cmp_dur', (cmp, nanos)))
    except (ValueError, TypeError):
        pass
    try:
        q = Quantity.parse(operand)
        m = q.value * 1000
        if m.denominator == 1 and abs(m.numerator) <= _I64_MAX:
            alts.append(leaf_op_tf(v, 'cmp_qty', (cmp, int(m))))
        else:
            cand = (v.numish | v.nullish |
                    ((v.tag == TAG_STRING) & v.lane('str_is_qty')))
            decided = cand & v.milli_ok
            if cmp in ('==', '!='):
                # a milli-exact value can never equal a sub-milli constant
                hit = decided if cmp == '!=' else jnp.zeros_like(decided)
                alts.append(_K(hit, (decided & ~hit) | (~cand & ~v.arrayish)))
            else:
                c2, thr = _frac_thresholds(cmp, m)
                alts.append(leaf_op_tf(v, 'cmp_qty', (c2, thr)))
    except ValueError:
        pass
    if cmp in ('==', '!='):
        s = v.match_const_pattern(operand)
        if cmp == '!=':
            conv = _K(v.convertible, ~v.convertible & ~v.arrayish)
            s = conv & s.negate()
        alts.append(s)
    if not alts:
        return _K.false_const(v.tag.shape)
    return _k_any(alts)


def string_pattern_tf(v: _View, pattern: str) -> _K:
    """leaf_pattern._validate_string_patterns over a view."""
    parts = [v.eq_const(pattern)]  # value == pattern literal short-circuit
    for condition in pattern.split('|'):
        ands = [string_term_tf(v, t.strip(' '))
                for t in condition.strip(' ').split('&')]
        parts.append(_k_all(ands))
    return _k_any(parts)


# ---------------------------------------------------------------------------
# condition (deny / precondition) checks over gathers — semantics:
# kyverno_tpu/engine/operators.py (reference: pkg/engine/variables/operator)

def _scalar_eq_const(sv: _View, value: Any) -> _K:
    """operators._equal(key=<scalar gather>, value=<const>)."""
    shape = sv.tag.shape
    if isinstance(value, bool):
        t = (sv.tag == TAG_BOOL) & ((sv.milli != 0) == value)
        return _K(t, ~t)
    if isinstance(value, (int, float)):
        # key bool→False; key num → exact numeric eq; key str → duration
        # pair only (operators.py:141-162,180-192)
        target = Fraction(str(value)) * 1000
        mok = sv.lane('milli_ok')
        if target.denominator == 1 and abs(target) <= _I64_MAX:
            num_t = sv.numish & mok & (sv.milli == int(target))
        else:
            num_t = jnp.zeros(shape, bool)
        num_u = sv.numish & ~mok
        dur_key = ((sv.tag == TAG_STRING) & sv.lane('str_is_dur') &
                   ~sv.is_zero_str)
        # host truncates via float: _duration_pair does int(value * 1e9)
        # (operators.py:111-117)
        vd = int(value * 1e9)
        if abs(vd) <= _I64_MAX:
            dur_t = dur_key & sv.lane('nanos_ok') & (sv.nanos == vd)
        else:
            dur_t = jnp.zeros(shape, bool)
        dur_u = dur_key & ~sv.lane('nanos_ok')
        t = num_t | dur_t
        u = num_u | dur_u
        return _K(t, ~t & ~u)
    if isinstance(value, str):
        return _scalar_eq_str_const(sv, value)
    if value is None:
        return _K.false_const(shape)  # _equal(key, None) is always False
    if isinstance(value, list):
        return _K.false_const(shape)  # scalar key vs list value → False
    return _K.false_const(shape)


def _scalar_eq_str_const(sv: _View, value: str) -> _K:
    shape = sv.tag.shape
    # key num: float(value) == float(key)  (operators.py:157-177) —
    # replicated as the identical float64 comparison on device
    try:
        fv = float(value)
        mok = sv.lane('milli_ok') & (jnp.abs(sv.milli) <= (1 << 53))
        key_f = sv.milli.astype(jnp.float64) / 1000.0
        num_t = sv.numish & mok & (key_f == jnp.float64(fv))
        num_u = sv.numish & ~mok
    except ValueError:
        num_t = jnp.zeros(shape, bool)
        num_u = jnp.zeros(shape, bool)
    # key str (operators.py:180 _equal_string): duration pair first
    is_str = sv.tag == TAG_STRING
    dur_key = is_str & sv.lane('str_is_dur') & ~sv.is_zero_str
    try:
        vnanos: Optional[int] = (parse_duration(value)
                                 if value != '0' else None)
    except (ValueError, TypeError):
        vnanos = None
    if vnanos is not None:
        dur_t = dur_key & sv.lane('nanos_ok') & (sv.nanos == vnanos)
        dur_decided = dur_key
        dur_u = dur_key & ~sv.lane('nanos_ok')
    else:
        # value not a duration and not numeric → pair=None → quantity next
        dur_t = jnp.zeros(shape, bool)
        dur_decided = jnp.zeros(shape, bool)
        dur_u = jnp.zeros(shape, bool)
    # quantity: key parses as quantity → decided by quantity compare alone
    qty_key = is_str & sv.lane('str_is_qty') & ~dur_decided
    try:
        vq = Quantity.parse(value)
        vm = vq.value * 1000
        if vm.denominator == 1 and abs(vm.numerator) <= _I64_MAX:
            qty_t = qty_key & sv.lane('milli_ok') & (sv.milli == int(vm))
        else:
            qty_t = jnp.zeros(shape, bool)
        qty_u = qty_key & ~sv.lane('milli_ok')
    except ValueError:
        # value not a quantity → quantity-keyed compare is False
        qty_t = jnp.zeros(shape, bool)
        qty_u = jnp.zeros(shape, bool)
    # wildcard string match for plain-string keys
    wild_key = is_str & ~dur_decided & ~qty_key
    wk = sv.match_const_pattern(value)
    wild_t = wild_key & wk.t
    wild_u = wild_key & wk.unknown()
    t = num_t | dur_t | qty_t | wild_t
    u = num_u | dur_u | qty_u | wild_u
    return _K(t, ~t & ~u)


def _list_eq_const(ev: _View, count, overflow, values: Tuple[Any, ...]) -> _K:
    """list key == list const (Python ``==`` semantics, elementwise)."""
    shape = count.shape
    gwidth = ev.lane('tag').shape[-1]
    if len(values) > gwidth:
        # visible lists are shorter → known unequal; overflowed lists have
        # an unknown true length → undecidable
        return _K(jnp.zeros(shape, bool), ~overflow)
    n = len(values)
    mismatch = (count != n) | overflow
    t_all = jnp.ones(shape, bool)
    f_any = jnp.zeros(shape, bool)
    u_any = jnp.zeros(shape, bool)
    for i, cv in enumerate(values):
        el = _View(ev._t, ev._p, i)
        if cv is None:
            ek = _K.known(el.tag == TAG_NULL)
        elif isinstance(cv, (bool, int, float)):
            # Python numeric equality spans bool/int/float: True == 1 == 1.0
            target = Fraction(str(cv if not isinstance(cv, bool)
                                  else (1 if cv else 0))) * 1000
            numish = el.is_tag(TAG_BOOL, TAG_INT, TAG_FLOAT)
            mok = el.lane('milli_ok')
            if target.denominator == 1 and abs(target) <= _I64_MAX:
                et = numish & mok & (el.milli == int(target))
            else:
                et = jnp.zeros(shape, bool)
            ek = _K(et, ~et & ~(numish & ~mok))
        elif isinstance(cv, str):
            is_str = el.tag == TAG_STRING
            e = el.eq_const(cv)
            ek = _K(is_str & e.t, ~is_str | (is_str & e.f))
        else:  # nested list consts are rejected at compile time
            ek = _K(jnp.zeros(shape, bool), jnp.zeros(shape, bool))
        t_all = t_all & ek.t
        f_any = f_any | ek.f
        u_any = u_any | ek.unknown()
    t = ~mismatch & t_all
    f = mismatch | f_any
    return _K(t, f & ~t)


def _both_dir_member(view: _View, values: Tuple[Any, ...]) -> _K:
    """∃ const v: wildcard.match(sprint(v), k) or wildcard.match(k,
    sprint(v)) — the list-value membership of the In family
    (operators.py:228,327-330)."""
    hw = view.lane('has_wild') if view.has('has_wild') else None
    parts: List[_K] = []
    for cv in values:
        vs = cv if isinstance(cv, str) else _sprint(cv)
        m1 = view.match_const_pattern(vs)  # match(vs_as_pattern, key)
        if hw is None:
            parts.append(m1)
            continue
        # match(key_as_pattern, vs): for wildcard-free keys this is plain
        # equality; wildcard keys are undecidable unless m1 already hit
        eqc = view.eq_const(vs) if ('*' in vs or '?' in vs) else m1
        parts.append(_K(m1.t | (eqc.t & ~hw), m1.f & eqc.f & ~hw))
    return _k_any(parts)


def _arr_member(view: _View, value: str) -> _K:
    """k ∈ (json-list(value) or [value]) — plain string-form equality
    (operators.py:339-345)."""
    arr = _try_json_str_list(value)
    if arr is None:
        arr = [value]
    return _k_any([view.eq_const(x) for x in arr])


def _scalar_str_member(view: _View, value: str) -> _K:
    """_key_in_array(k, value_str, allow_range=True) (operators.py:222):
    wildcard match, else range validation, else set membership."""
    m = view.match_const_pattern(value)
    if leaf_pattern.get_operator_from_string_pattern(value) == \
            leaf_pattern.OP_IN_RANGE:
        return m | string_pattern_tf(view, value)
    return m | _arr_member(view, value)


def _try_json_str_list(value: str) -> Optional[List[str]]:
    try:
        arr = _json.loads(value)
    except ValueError:
        return None
    if isinstance(arr, list) and all(isinstance(x, str) for x in arr):
        return arr
    return None


def _quantify(quant: str, em: _K, valid, overflow):
    """Reduce elementwise Kleene membership over a list key.  Returns
    (known-true, known-false) for the quantified statement."""
    if quant == 'any':          # ∃ member
        lt = jnp.any(valid & em.t, axis=-1)
        lf = jnp.all(~valid | em.f, axis=-1) & ~overflow
    elif quant == 'all':        # ∀ member (vacuously true when empty)
        lt = jnp.all(~valid | em.t, axis=-1) & ~overflow
        lf = jnp.any(valid & em.f, axis=-1)
    elif quant == 'any_not':    # ∃ non-member
        lt = jnp.any(valid & em.f, axis=-1)
        lf = jnp.all(~valid | em.t, axis=-1) & ~overflow
    elif quant == 'all_not':    # ∀ non-member
        lt = jnp.all(~valid | em.f, axis=-1) & ~overflow
        lf = jnp.any(valid & em.t, axis=-1)
    else:
        raise ValueError(quant)
    return lt, lf


def _in_family_tf(t: Dict[str, Any], prefix: str, check: CondCheck) -> _K:
    """AnyIn / AllIn and their negations (operators.py:299-395).  The
    deprecated In/NotIn are host-only (rejected at compile time)."""
    op = check.op
    kind = t[f'{prefix}_kind']
    count = t[f'{prefix}_count']
    overflow = t[f'{prefix}_overflow']
    shape = kind.shape
    negate = op in ('anynotin', 'allnotin')

    if not check.list_value and not isinstance(check.values[0], str):
        # invalid value type: every host path returns False
        return _K(jnp.zeros(shape, bool), jnp.ones(shape, bool))

    sv = _View(t, prefix, 0)
    ev = _View(t, prefix)

    # ---- scalar key (str or num; bool/map/null → False) ----
    scalar = kind == 1
    scalar_ok = sv.is_tag(TAG_STRING, TAG_INT, TAG_FLOAT)
    if check.list_value:
        member = _both_dir_member(sv, check.values)
    else:
        member = _scalar_str_member(sv, check.values[0])
    if negate:
        member = member.negate()
    scal_t = scalar & scalar_ok & member.t
    scal_f = scalar & (~scalar_ok | member.f)

    # ---- list key: per-element membership, then quantify ----
    gwidth = t[f'{prefix}_tag'].shape[-1]
    elem_valid = jnp.arange(gwidth) < count[..., None]
    shortcut = None
    if check.list_value:
        em = _both_dir_member(ev, check.values)
        quant = {'anyin': 'any', 'allin': 'all',
                 'anynotin': 'any_not', 'allnotin': 'all_not'}[op]
    else:
        value = check.values[0]
        is_range = leaf_pattern.get_operator_from_string_pattern(value) == \
            leaf_pattern.OP_IN_RANGE
        # single-element lists equal to the literal value string hit the
        # keys[0]==value shortcut before range/JSON handling
        # (operators.py:332-345,383-394)
        eq0 = _View(t, prefix, 0).eq_const(value)
        shortcut = (count == 1) & eq0.t
        if is_range:
            if op == 'anynotin':
                em = string_pattern_tf(ev, value.replace('-', '!-', 1))
                quant = 'any'
            elif op == 'allnotin':
                em = string_pattern_tf(ev, value)
                quant = 'all_not'
            else:
                em = string_pattern_tf(ev, value)
                quant = {'anyin': 'any', 'allin': 'all'}[op]
        else:
            # JSON-list / plain string values run the same bidirectional
            # wildcard membership as list values (anyin.go:168-183
            # isAnyIn/isAnyNotIn over the parsed array)
            arr = _try_json_str_list(value)
            em = _both_dir_member(ev, tuple(arr if arr is not None
                                            else [value]))
            quant = {'anyin': 'any', 'allin': 'all',
                     'anynotin': 'any_not', 'allnotin': 'all_not'}[op]
    lt, lf = _quantify(quant, em, elem_valid, overflow)
    if shortcut is not None:
        if negate:
            lt, lf = lt & ~shortcut, lf | shortcut
        else:
            lt, lf = lt | shortcut, lf & ~shortcut
    lst = kind == 2
    list_t = lst & lt
    list_f = lst & lf

    null_f = kind == 0
    t_out = scal_t | list_t
    f_out = (scal_f | list_f | null_f) & ~t_out
    return _K(t_out, f_out)


def _numeric_tf(t: Dict[str, Any], prefix: str, check: CondCheck) -> _K:
    """GreaterThan / LessThan family (operators.py:413 _numeric).

    The host compares through float64 (``_cmp(op, float(key),
    float(value))``, duration pairs via ``int(x * 1e9)`` then ``/ 1e9``);
    the device replicates those float64 computations bit-for-bit (IEEE
    semantics are identical), guarded to the ranges where the lanes
    reconstruct the host's floats exactly.
    """
    op = check.op
    kind = t[f'{prefix}_kind']
    shape = kind.shape
    sv = _View(t, prefix, 0)
    value = check.values[0]
    cmp = {'greaterthan': '>', 'greaterthanorequals': '>=',
           'lessthan': '<', 'lessthanorequals': '<='}[op]
    zeros = jnp.zeros(shape, bool)
    scalar = kind == 1

    # f64(milli)/1000 == the host's float(key) whenever milli is exact and
    # within 2^53 (single correctly-rounded division; see encode milli)
    f53 = 1 << 53
    mok = sv.lane('milli_ok') & (jnp.abs(sv.milli) <= f53)
    key_f = sv.milli.astype(jnp.float64) / 1000.0

    def cmp_float(valid, ok, target_f):
        """valid & host-float comparison against a float64 constant."""
        return (valid & ok & _cmp_arr(key_f, jnp.float64(target_f), cmp),
                valid & ~ok)

    def cmp_duration_pair(valid, ok, vd: int):
        """_duration_pair semantics: int(key*1e9)/1e9 cmp vd/1e9."""
        kd = jnp.trunc(key_f * 1e9)
        return (valid & ok & _cmp_arr(kd / 1e9, jnp.float64(vd / 1e9), cmp),
                valid & ~ok)

    # value-side constants, computed exactly as the host does
    vd: Optional[int] = None        # duration nanos (int(value * 1e9))
    vf: Optional[float] = None      # float(value)
    vq = None                       # Quantity
    if isinstance(value, (int, float)) and not isinstance(value, bool):
        vf = float(value)
    if isinstance(value, str):
        vd = _op_duration(value)
        try:
            vq = Quantity.parse(value)
        except ValueError:
            vq = None
        if vd is None:
            try:
                vf = float(value)
            except ValueError:
                vf = None

    # ---- numeric key (operators.py:442 _numeric_num_key) ----
    num_key = sv.numish
    if isinstance(value, bool):
        num_t, num_u = zeros, zeros
    elif isinstance(value, (int, float)):
        num_t, num_u = cmp_float(num_key, mok, vf)
    elif isinstance(value, str) and vd is not None:
        num_t, num_u = cmp_duration_pair(num_key, mok, vd)
    elif isinstance(value, str) and vf is not None:
        num_t, num_u = cmp_float(num_key, mok, vf)
    else:
        num_t, num_u = zeros, zeros

    # ---- string key (operators.py:418-437) ----
    is_str = sv.tag == TAG_STRING
    dur_key = is_str & sv.lane('str_is_dur') & ~sv.is_zero_str
    # duration pair: needs a duration/numeric value; kd is the parsed
    # nanos (exact int) pushed through the host's / 1e9
    if isinstance(value, str):
        pair_vd = vd
    elif isinstance(value, (int, float)) and not isinstance(value, bool):
        pair_vd = int(value * 1e9)
    else:
        pair_vd = None
    if pair_vd is not None:
        nok = sv.lane('nanos_ok') & (jnp.abs(sv.nanos) <= f53)
        kd_f = sv.nanos.astype(jnp.float64) / 1e9
        dur_t = dur_key & nok & _cmp_arr(kd_f, jnp.float64(pair_vd / 1e9),
                                         cmp)
        dur_u = dur_key & ~nok
        dur_decided = dur_key
    else:
        dur_t, dur_u = zeros, zeros
        dur_decided = zeros
    # quantity stage: exact rational compare (Quantity.cmp) via milli
    qty_key = is_str & sv.lane('str_is_qty') & ~dur_decided
    if isinstance(value, str) and vq is not None:
        c2, thr = _frac_thresholds(cmp, vq.value * 1000)
        qty_t = qty_key & sv.lane('milli_ok') & _cmp_arr(sv.milli, thr, c2)
        qty_u = qty_key & ~sv.lane('milli_ok')
        qty_decided = qty_key
    else:
        qty_t, qty_u = zeros, zeros
        qty_decided = zeros
    # float(key) fallback: _numeric_num_key with the parsed float
    float_key = (is_str & sv.lane('str_is_float') & ~dur_decided &
                 ~qty_decided)
    if isinstance(value, bool):
        f_t, f_u = zeros, zeros
    elif isinstance(value, (int, float)):
        f_t, f_u = cmp_float(float_key, mok, float(value))
    elif isinstance(value, str) and vd is not None:
        f_t, f_u = cmp_duration_pair(float_key, mok, vd)
    elif isinstance(value, str) and vf is not None:
        f_t, f_u = cmp_float(float_key, mok, vf)
    else:
        f_t, f_u = zeros, zeros
    # semver stage: undecidable on device when the const side is semver
    semver_const = isinstance(value, str) and _is_semverish(value)
    rest = is_str & ~dur_decided & ~qty_decided & ~float_key
    semver_u = rest if semver_const else zeros

    t_true = scalar & (num_t | dur_t | qty_t | f_t)
    u = scalar & (num_u | dur_u | qty_u | f_u | semver_u)
    return _K(t_true, ~t_true & ~u)


def _op_duration(v: str) -> Optional[int]:
    """operators._try_duration: duration strings except literal '0'."""
    if isinstance(v, str) and v != '0':
        try:
            return parse_duration(v)
        except (ValueError, TypeError):
            return None
    return None


def _is_op_num(v: Any) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool)


def _is_semverish(v: str) -> bool:
    from ..engine.operators import _try_semver
    return _try_semver(v) is not None


def _suspicious_scalar(view: _View) -> Any:
    """Scalar string values that might trigger the host's runtime range
    or JSON handling (contains '-', leads with '[' after optional
    whitespace — json.loads tolerates leading whitespace — has wildcards,
    or exceeds the head window): undecidable beyond plain equality."""
    head = view.lane('str_head')
    w = head.shape[-1]
    pos_valid = jnp.arange(w) < jnp.minimum(view.str_len, w)[..., None]
    has_dash = jnp.any((head == ord('-')) & pos_valid, axis=-1)
    is_space = (head == ord(' ')) | (head == ord('\t')) | \
        (head == ord('\n')) | (head == ord('\r'))
    # all-whitespace prefix up to (exclusive) each position
    space_prefix = jnp.cumprod(is_space.astype(jnp.int32), axis=-1) > 0
    before_ok = jnp.concatenate(
        [jnp.ones(head.shape[:-1] + (1,), bool), space_prefix[..., :-1]],
        axis=-1)
    leads_bracket = jnp.any(
        before_ok & (head == ord('[')) & pos_valid, axis=-1)
    hw = view.lane('has_wild') if view.has('has_wild') else \
        jnp.zeros(view.tag.shape, bool)
    return has_dash | leads_bracket | hw | (view.str_len > w)


def _cond_b_tf(t: Dict[str, Any], prefix: str, check: CondCheck) -> _K:
    """Mode-B checks: constant key vs gathered value (foreach conditions
    like ``key: ALL, value: {{element...drop[]}}``; operators.py with the
    runtime side on the right)."""
    op = check.op
    key = check.key_const
    kind = t[f'{prefix}_kind']
    count = t[f'{prefix}_count']
    overflow = t[f'{prefix}_overflow']
    notfound = t[f'{prefix}_notfound']
    shape = kind.shape
    sv = _View(t, prefix, 0)
    ev = _View(t, prefix)
    zeros = jnp.zeros(shape, bool)

    if op in ('equal', 'equals', 'notequal', 'notequals'):
        res = _b_equals(t, prefix, key, sv, kind, count, overflow)
        if op in ('notequal', 'notequals'):
            res = res.negate()
    else:  # anyin / allin / anynotin / allnotin with a scalar const key
        negate = op in ('anynotin', 'allnotin')
        if key is None or isinstance(key, bool):
            # host: key not str/num/list → False for every variant
            res = _K(zeros, jnp.ones(shape, bool))
        else:
            ks = key if isinstance(key, str) else _sprint(key)
            # value list: ∃ element matching either direction
            # (_key_in_array(K, value) — the key is scalar, so every op
            # reduces to one membership test; operators.py:299-369)
            m_eq = ev.eq_const(ks)
            m_pat = ev.match_const_pattern(ks)
            hw = ev.lane('has_wild') if ev.has('has_wild') else None
            et = m_eq.t | m_pat.t
            ef = m_eq.f & m_pat.f
            if hw is not None:
                ef = ef & ~hw  # wildcard elements may match as patterns
            gw = ev.lane('tag').shape[-1]
            valid = jnp.arange(gw) < count[..., None]
            lt = jnp.any(valid & et, axis=-1)
            lf = jnp.all(~valid | ef, axis=-1) & ~overflow
            # value scalar string: match(value, K) → equality unless the
            # value could be a wildcard/range/JSON form at runtime
            s_eq = sv.eq_const(ks)
            s_susp = _suspicious_scalar(sv)
            st_ = (sv.tag == TAG_STRING) & s_eq.t
            sf_ = (sv.tag == TAG_STRING) & s_eq.f & ~s_susp
            scalar_str = (kind == 1) & (sv.tag == TAG_STRING)
            scalar_other = (kind == 1) & (sv.tag != TAG_STRING)
            r_t = ((kind == 2) & lt) | (scalar_str & st_)
            r_f = ((kind == 2) & lf) | (scalar_str & sf_) | \
                scalar_other | (kind == 0)
            res = _K(r_t, r_f & ~r_t)
            if negate:
                # r=None (invalid value types) stays False, not True
                inv = scalar_other | (kind == 0)
                res = _K(res.f & ~inv, (res.t | inv) & ~(res.f & ~inv))
    bad = notfound | ((kind == 0) & overflow)
    return _K(res.t & ~bad, res.f & ~bad)


def _b_equals(t, prefix: str, key, sv: _View, kind, count, overflow) -> _K:
    """operators._equal(const_key, gathered_value)."""
    shape = kind.shape
    zeros = jnp.zeros(shape, bool)
    scalar = kind == 1
    if isinstance(key, bool):
        tv = scalar & (sv.tag == TAG_BOOL) & ((sv.milli != 0) == key)
        return _K(tv, ~tv)
    if isinstance(key, (int, float)):
        # value num → exact numeric equality; value str → float compare
        kf = Fraction(str(key)) * 1000
        if kf.denominator == 1 and abs(kf) <= _I64_MAX:
            num_t = sv.numish & sv.lane('milli_ok') & (sv.milli == int(kf))
        else:
            num_t = zeros  # out of the milli lane → never equal exactly
        mok53 = sv.lane('milli_ok') & (jnp.abs(sv.milli) <= (1 << 53))
        key_f = sv.milli.astype(jnp.float64) / 1000.0
        str_t = (sv.tag == TAG_STRING) & sv.lane('str_is_float') & mok53 & \
            (key_f == jnp.float64(float(key)))
        str_u = (sv.tag == TAG_STRING) & sv.lane('str_is_float') & ~mok53
        num_u = sv.numish & ~sv.lane('milli_ok')
        tv = scalar & (num_t | str_t)
        uv = scalar & (num_u | str_u)
        return _K(tv, ~tv & ~uv)
    if isinstance(key, str):
        is_str = sv.tag == TAG_STRING
        try:
            kd = parse_duration(key) if key != '0' else None
        except (ValueError, TypeError):
            kd = None
        if kd is not None:
            # duration pair: value duration-string or numeric
            v_dur = is_str & sv.lane('str_is_dur') & ~sv.is_zero_str
            if abs(kd) <= _I64_MAX:
                dur_t = v_dur & sv.lane('nanos_ok') & (sv.nanos == kd)
                dur_u = v_dur & ~sv.lane('nanos_ok')
                mok53 = sv.lane('milli_ok') & \
                    (jnp.abs(sv.milli) <= (1 << 53))
                key_f = sv.milli.astype(jnp.float64) / 1000.0
                vd = jnp.trunc(key_f * 1e9)
                num_t = sv.numish & mok53 & (vd == jnp.float64(kd))
                num_u = sv.numish & ~mok53
            else:
                # constant beyond the nanos lane: duration-pair outcomes
                # are undecidable on device
                dur_t = num_t = zeros
                dur_u = v_dur
                num_u = sv.numish
            decided = v_dur | sv.numish
            rest = is_str & ~v_dur
        else:
            dur_t = dur_u = num_t = num_u = zeros
            decided = zeros
            rest = is_str
        try:
            kq = Quantity.parse(key)
        except ValueError:
            kq = None
        if kq is not None:
            m = kq.value * 1000
            if m.denominator == 1 and abs(m.numerator) <= _I64_MAX:
                qty_t = rest & sv.lane('str_is_qty') & \
                    sv.lane('milli_ok') & (sv.milli == int(m))
            else:
                qty_t = zeros
            qty_u = rest & sv.lane('str_is_qty') & ~sv.lane('milli_ok')
            # a quantity-keyed compare is decided for every string value
            qty_f_zone = rest
            wild_zone = zeros
        else:
            qty_t = qty_u = zeros
            qty_f_zone = zeros
            wild_zone = rest
        # wildcard: match(value_as_pattern, K) — equality unless wild
        w_eq = sv.eq_const(key)
        hw = sv.lane('has_wild') if sv.has('has_wild') else zeros
        wild_t = wild_zone & w_eq.t
        wild_u = wild_zone & ~w_eq.t & hw
        tv = scalar & (dur_t | num_t | qty_t | wild_t)
        uv = scalar & (dur_u | num_u | qty_u | wild_u)
        return _K(tv, ~tv & ~uv)
    # None / list / dict const keys: _equal returns False for gathered
    # scalars; list-vs-list is not compiled in mode B
    return _K(zeros, jnp.ones(shape, bool))


def cond_tf(t: Dict[str, Any], prefix: str, check: CondCheck) -> _K:
    op = check.op
    kind = t[f'{prefix}_kind']
    overflow = t[f'{prefix}_overflow']
    shape = kind.shape
    if op in ('equal', 'equals', 'notequal', 'notequals'):
        sv = _View(t, prefix, 0)
        scalar = kind == 1
        if check.list_value:
            eq_scal = _K.false_const(shape)  # scalar key vs list → False
        else:
            eq_scal = _scalar_eq_const(sv, check.values[0])
        count = t[f'{prefix}_count']
        if check.list_value:
            eq_list = _list_eq_const(_View(t, prefix), count, overflow,
                                     check.values)
        else:
            eq_list = _K.false_const(shape)  # list key vs scalar → False
        eq_t = (scalar & eq_scal.t) | ((kind == 2) & eq_list.t)
        eq_u = (scalar & eq_scal.unknown()) | ((kind == 2) & eq_list.unknown())
        res = _K(eq_t, ~eq_t & ~eq_u)
        if op in ('notequal', 'notequals'):
            res = res.negate()
        # raised queries (overflow on kind 0) and unresolvable paths
        # (notfound → STATUS_VAR_ERR preempts at the precond/deny node)
        # are undecidable at the condition level
        raised = ((kind == 0) & overflow) | t[f'{prefix}_notfound']
        return _K(res.t & ~raised, res.f & ~raised)
    raised = ((kind == 0) & overflow) | t[f'{prefix}_notfound']
    if op in ('in', 'anyin', 'allin', 'notin', 'anynotin', 'allnotin'):
        res = _in_family_tf(t, prefix, check)
        return _K(res.t & ~raised, res.f & ~raised)
    if op in ('greaterthan', 'greaterthanorequals', 'lessthan',
              'lessthanorequals'):
        res = _numeric_tf(t, prefix, check)
        return _K(res.t & ~raised, res.f & ~raised)
    raise ValueError(f'condition op {op!r} not supported on device')


# ---------------------------------------------------------------------------
# evaluator assembly.  Compiled-executable persistence lives in the
# aotcache subsystem (kyverno_tpu/aotcache + compiler/aot.py): every
# jit site below consults the disk store before paying a fresh trace +
# XLA compile, and stores what it compiled for the next process.  The
# cache-key helpers are re-exported here because this module
# historically owned them (and the evaluator is their main consumer).

from ..aotcache.keys import (enable_persistent_compilation_cache,  # noqa: E402,F401
                             policy_set_fingerprint)


#: per-row admission lane names (compiler/admission.py contract); the
#: lanes ride every non-mesh dispatch of a policy set with at least one
#: admission-dependent eligible rule, zero-filled when the scan carries
#: no admission data, so they add inputs — never executables
ADM_LANES = ('__admres__', '__adm_user__', '__adm_groups__',
             '__adm_roles__', '__adm_croles__', '__adm_hasinfo__',
             '__adm_excluded__')


def _adm_member2(lanes2d, ids):
    """∃ lane value ∈ ids over a [R, W] id lane (ids are static interned
    operand ids ≥ 0; -1 marks absent/out-of-vocabulary lane slots)."""
    ops = jnp.asarray(list(ids), dtype=jnp.int32)
    return jnp.any(lanes2d[:, :, None] == ops[None, None, :], axis=(1, 2))


def _adm_member1(lane1d, ids):
    ops = jnp.asarray(list(ids), dtype=jnp.int32)
    return jnp.any(lane1d[:, None] == ops[None, :], axis=1)


def _adm_match_graph(table, lanes):
    """[R, n_elig] bool: the jitted half of matches_resource_description
    for admission-eligible programs — the static filter tree
    (compiler/admission.py AdmProgram) over host-computed resource-shape
    atoms (``__admres__``) and the per-row user-info id lanes.  Exactly
    mirrors engine/match.py's _check_filter / _check_user_info /
    check_subjects semantics for the lowered vocabulary."""
    atoms = lanes['__admres__'] != 0
    user = lanes['__adm_user__']
    groups = lanes['__adm_groups__']
    roles = lanes['__adm_roles__']
    croles = lanes['__adm_croles__']
    hasinfo = lanes['__adm_hasinfo__'] != 0
    excluded = lanes['__adm_excluded__'] != 0
    false = jnp.zeros(user.shape, bool)

    def ui_ok(f):
        # excluded users skip role gates entirely, and ride the
        # exclude-group-roles Group subjects the host matcher appends
        ok = None
        if f.has_roles:
            hit = _adm_member2(roles, f.roles) if f.roles else false
            ok = excluded | hit
        if f.has_croles:
            hit = _adm_member2(croles, f.cluster_roles) \
                if f.cluster_roles else false
            ok = (excluded | hit) if ok is None else ok & (excluded | hit)
        if f.has_subjects:
            hit = false
            if f.subjects_ug:
                # User/Group names match any of groups ∪ {username}
                hit = hit | _adm_member2(groups, f.subjects_ug) | \
                    _adm_member1(user, f.subjects_ug)
            if f.subjects_sa:
                hit = hit | _adm_member1(user, f.subjects_sa)
            sub = hit | excluded
            ok = sub if ok is None else ok & sub
        return ok if ok is not None else ~false

    def filter_ok(f, mode):
        res_ok = atoms[:, f.atom]
        if mode == 'match':
            # without admission info the matcher drops user info: a
            # filter reduced to nothing is 'match cannot be empty'
            if not f.has_ui:
                return res_ok if f.has_res else false
            with_ui = res_ok & ui_ok(f)
            without = res_ok if f.has_res else false
            return jnp.where(hasinfo, with_ui, without)
        # exclude mode: user info always applies; an empty filter
        # never excludes (folded to 'none' at compile time)
        if not f.has_ui and not f.has_res:
            return false
        ok = res_ok
        if f.has_ui:
            ok = ok & ui_ok(f)
        return ok

    def combine(kind, oks):
        if kind == 'none' or not oks:
            return false
        acc = oks[0]
        for o in oks[1:]:
            acc = (acc & o) if kind == 'all' else (acc | o)
        return acc

    cols = []
    for p in table.programs:
        m = combine(p.match_kind,
                    [filter_ok(f, 'match') for f in p.match_filters])
        e = combine(p.exclude_kind,
                    [filter_ok(f, 'exclude') for f in p.exclude_filters])
        cols.append(m & ~e)
    return jnp.stack(cols, axis=1)


def build_evaluator(cps: CompiledPolicySet):
    enable_persistent_compilation_cache()
    from ..compiler.admission import compile_admission
    # frozen NamedTuple-of-tuples: trace-static by construction, so the
    # jitted closure below can never drift under a cached executable
    adm_table = compile_admission(cps)
    slot_prefix = {slot: f's{i}' for i, slot in enumerate(cps.slots)}
    gather_prefix = {g: f'g{k}' for k, g in enumerate(cps.gathers)}
    elem_prefix = {g: f'e{k}' for k, g in enumerate(cps.elem_gathers)}
    _, _, _, array_paths = _needs_cached(cps)
    array_prefix = {path: f'a{j}' for j, path in enumerate(array_paths)}

    def check_prefix(check: CondCheck) -> str:
        if check.value_gather is not None:
            return elem_prefix[check.value_gather]
        return elem_prefix.get(check.gather) or gather_prefix[check.gather]

    dims: Dict[str, int] = {}

    def broadcast(arr, depth: int):
        """Append trailing element axes so arr has depth element dims."""
        # ktpu: noqa[KTPU203] -- deliberate: rank pads to the element
        # depth baked into this executable (one trace per depth)
        while arr.ndim < depth + 1:
            arr = arr[..., None]
        tgt = (arr.shape[0],) + (dims['E'],) * depth
        return jnp.broadcast_to(arr, tgt)

    leaf_cache: Dict[Tuple[Leaf, int], _K] = {}
    cond_cache: Dict[CondCheck, _K] = {}
    # per-trace accumulator of anyPattern child fail channels; the static
    # column map (program index → (aux base, n children)) is derived from
    # the programs so callers can index the fdet output past the P main
    # columns without waiting for a trace
    aux_acc: List[Any] = []
    any_meta: Dict[int, Tuple[int, int]] = {}
    _aux_cols = 0
    for _j, _prog in enumerate(cps.programs):
        _units = _prog.status.children if _prog.status.kind == 'seq' \
            else (_prog.status,)
        for _u in _units:
            if _u.kind == 'any':
                any_meta[_j] = (_aux_cols, len(_u.children))
                _aux_cols += len(_u.children)

    def eval_leaf(t, leaf: Leaf, depth: int) -> _K:
        key = (leaf, depth)
        if key in leaf_cache:
            return leaf_cache[key]
        if leaf.op == 'true':
            n = t[next(iter(t))].shape[0]
            shape = (n,) + (dims['E'],) * depth
            out = _K.const(shape, True)
        else:
            view = _View(t, slot_prefix[leaf.slot])
            out = leaf_op_tf(view, leaf.op, leaf.operand)
            sd = leaf.slot.depth
            if sd < depth:
                out = _K(broadcast(out.t, depth), broadcast(out.f, depth))
            elif sd > depth:
                # reduce ALL over valid elements (trackfail guards): true
                # iff every element satisfies; overflow blocks known-true
                tt, ff = out.t, out.f
                path = leaf.slot.path
                for lvl in range(sd, depth, -1):
                    prefix_path = _nth_star_prefix(path, lvl)
                    ap = array_prefix.get(prefix_path)
                    if ap is None:
                        # container not tracked: cannot reduce exactly
                        shape = tt.shape[:-1]
                        tt = jnp.zeros(shape, bool)
                        ff = jnp.zeros(shape, bool)
                        continue
                    count = t[f'{ap}_count']
                    ovf = t[f'{ap}_overflow']
                    valid = jnp.arange(tt.shape[-1]) < count[..., None]
                    tt = jnp.all(tt | ~valid, axis=-1) & ~ovf
                    ff = jnp.any(ff & valid, axis=-1)
                out = _K(tt, ff)
        leaf_cache[key] = out
        return out

    def _nth_star_prefix(path: Tuple[str, ...], lvl: int) -> Tuple[str, ...]:
        seen = 0
        for i, p in enumerate(path):
            if p == '*':
                seen += 1
                if seen == lvl:
                    return path[:i]
        raise AssertionError('bad star level')

    def eval_expr(t, expr: BoolExpr, depth: int) -> _K:
        if expr.kind == 'leaf':
            return eval_leaf(t, expr.leaf, depth)
        if expr.kind == 'cond':
            check = expr.cond
            if check in cond_cache:
                out = cond_cache[check]
            else:
                if check.value_gather is not None:
                    out = _cond_b_tf(t, check_prefix(check), check)
                else:
                    out = cond_tf(t, check_prefix(check), check)
                cond_cache[check] = out
            # ktpu: noqa[KTPU203] -- deliberate rank specialization:
            # const-folded conditions broadcast to the element depth
            if depth > 0 and out.t.ndim == 1:
                out = _K(broadcast(out.t, depth), broadcast(out.f, depth))
            return out
        if expr.kind in ('any_elem', 'all_elem'):
            sub = eval_expr(t, expr.children[0], depth + 1)
            ap = array_prefix[expr.slot.path]
            arr_tag = t[f'{ap}_tag']
            count = t[f'{ap}_count']
            ovf = t[f'{ap}_overflow']
            valid = jnp.arange(sub.t.shape[-1]) < count[..., None]
            # missing/null arrays walk as [] (pss/checks.py `or []`);
            # map/scalar values would crash the host walk → undecidable
            known_arr = (arr_tag == TAG_ARRAY) | (arr_tag == TAG_MISSING) | \
                (arr_tag == TAG_NULL)
            if expr.kind == 'any_elem':
                tt = jnp.any(valid & sub.t, axis=-1)
                ff = jnp.all(~valid | sub.f, axis=-1) & ~ovf
            else:
                tt = jnp.all(~valid | sub.t, axis=-1) & ~ovf
                ff = jnp.any(valid & sub.f, axis=-1)
            return _K(known_arr & tt, known_arr & ff)
        parts = [eval_expr(t, c, depth) for c in expr.children]
        nd = max(p.t.ndim for p in parts)
        # ktpu: noqa[KTPU203] -- deliberate rank specialization: scalar
        # parts broadcast against element-scoped parts per trace
        if any(p.t.ndim != nd for p in parts):
            # scalar parts (const-folded conditions) broadcast against
            # element-scoped [R, FE] parts via trailing axes
            parts = [p if p.t.ndim == nd else
                     _K(p.t.reshape(p.t.shape + (1,) * (nd - p.t.ndim)),
                        p.f.reshape(p.f.shape + (1,) * (nd - p.f.ndim)))
                     for p in parts]
        if expr.kind == 'and':
            return _k_all(parts)
        if expr.kind == 'or':
            return _k_any(parts)
        if expr.kind == 'not':
            return parts[0].negate()
        raise ValueError(expr.kind)

    PASS, FAIL, SKIP = STATUS_PASS, STATUS_FAIL, STATUS_SKIP
    HOST, SKIPP = STATUS_HOST, STATUS_SKIP_PRECOND

    def from_k(k: _K, true_code: int, false_code: int):
        return jnp.where(k.t, jnp.int8(true_code),
                         jnp.where(k.f, jnp.int8(false_code),
                                   jnp.int8(HOST))).astype(jnp.int8)

    def site_fd(node: StatusExpr, ref):
        """Constant fail-detail plane for a node with a static fail site
        (site id in the high bits, element bytes zeroed)."""
        if node.fail_site is None:
            return jnp.full(ref.shape, -1, jnp.int32)
        return jnp.full(ref.shape, node.fail_site << 16, jnp.int32)

    def eval_status(t, node: StatusExpr, depth: int):
        """Returns (status int8, detail int8, fdet int32), each
        [R]+[E]*depth.  ``fdet`` identifies, for FAIL statuses, the walk
        position the host would report: site id in bits 16+, the
        outer/inner element indices in bytes 0/1; -1 = a FAIL here has no
        synthesizable message (host re-run)."""
        def zd(ref):
            return jnp.zeros(ref.shape, jnp.int8)

        def nofd(ref):
            return jnp.full(ref.shape, -1, jnp.int32)

        kind = node.kind
        if kind == 'const':
            n = t[next(iter(t))].shape[0]
            shape = (n,) + (dims['E'],) * depth
            s = jnp.full(shape, node.operand, jnp.int8)
            return s, jnp.zeros(shape, jnp.int8), nofd(s)
        if kind == 'leaf':
            s = from_k(eval_expr(t, node.expr, depth), PASS, FAIL)
            return s, zd(s), site_fd(node, s)
        if kind in ('precond', 'deny'):
            if kind == 'precond':
                s = from_k(eval_expr(t, node.expr, depth), PASS, SKIPP)
            else:
                s = from_k(eval_expr(t, node.expr, depth), FAIL, PASS)
            d = zd(s)
            # unresolvable condition variables preempt evaluation with the
            # host's substitution-error ERROR; the first missing variable
            # in traversal order picks the message (engine.py:388,431)
            for gather, msg_idx in (node.operand or ()):
                nf = t[f'{gather_prefix[gather]}_notfound']
                hit = nf & (s != STATUS_VAR_ERR)
                s = jnp.where(hit, jnp.int8(STATUS_VAR_ERR), s)
                d = jnp.where(hit, jnp.int8(msg_idx), d)
            # deny FAILs carry a static message (site-free): fdet 0 marks
            # 'synthesizable'; preconditions never FAIL
            fd = jnp.zeros(s.shape, jnp.int32) if kind == 'deny' else nofd(s)
            return s, d, fd
        if kind == 'failguard':
            # fdet-only guard: sub status unchanged; the fail path/message
            # is synthesizable only while every tracked anchor key is
            # present (else the host reports the empty-path message form)
            sub_s, sub_d, sub_fd = eval_status(t, node.sub, depth)
            g = eval_expr(t, node.expr, depth)
            return sub_s, sub_d, jnp.where(g.t, sub_fd, jnp.int32(-1))
        if kind == 'seq':
            s, d, fd = eval_status(t, node.children[0], depth)
            for c in node.children[1:]:
                cs, cd, cfd = eval_status(t, c, depth)
                take = s == PASS
                s = jnp.where(take, cs, s)
                d = jnp.where(take, cd, d)
                fd = jnp.where(take, cfd, fd)
            return s, d, fd
        if kind == 'any':
            evals = [eval_status(t, c, depth) for c in node.children]
            stats = [e[0] for e in evals]
            ref = stats[0]
            taken = jnp.zeros(ref.shape, bool)
            pending_host = jnp.zeros(ref.shape, bool)
            all_skip = jnp.ones(ref.shape, bool)
            detail = jnp.zeros(ref.shape, jnp.int8)
            for i, s_i in enumerate(stats):
                this = (s_i == PASS) & ~taken & ~pending_host
                detail = jnp.where(this, jnp.int8(i), detail)
                taken = taken | this
                pending_host = pending_host | (s_i == HOST)
                all_skip = all_skip & (s_i == SKIP)
            out = jnp.where(
                taken, jnp.int8(PASS),
                jnp.where(pending_host, jnp.int8(HOST),
                          jnp.where(all_skip, jnp.int8(SKIP),
                                    jnp.int8(FAIL)))).astype(jnp.int8)
            # per-child fail channels for anyPattern message synthesis:
            # on an overall FAIL every child is FAIL or SKIP; -2 marks a
            # skipped child (omitted from the message), -1 an
            # unsynthesizable child failure
            for s_i, _, fd_i in evals:
                aux_acc.append(jnp.where(
                    s_i == SKIP, jnp.int32(-2),
                    jnp.where(s_i == FAIL, fd_i, jnp.int32(-1))))
            return out, detail, nofd(out)
        if kind in ('cond', 'global', 'equality', 'negation'):
            view = _View(t, slot_prefix[node.slot])
            present = view.tag != TAG_MISSING
            # ktpu: noqa[KTPU203] -- deliberate: slot rank vs node depth
            # is a compile-time program property, not a batch shape
            if view.tag.ndim - 1 < depth:
                present = broadcast(present, depth)
            if kind == 'negation':
                s = jnp.where(present, jnp.int8(FAIL),
                              jnp.int8(PASS)).astype(jnp.int8)
                return s, zd(s), site_fd(node, s)
            sub_s, sub_d, sub_fd = eval_status(t, node.sub, depth)
            if kind == 'equality':
                s = jnp.where(present, sub_s, jnp.int8(PASS)).astype(jnp.int8)
                return s, sub_d, sub_fd
            # cond: absent→SKIP; sub FAIL/SKIP→SKIP; HOST→HOST
            # global: absent→PASS; sub FAIL/SKIP→SKIP; HOST→HOST
            absent_code = SKIP if kind == 'cond' else PASS
            nonpass = jnp.where(sub_s == HOST, jnp.int8(HOST),
                                jnp.int8(SKIP))
            s = jnp.where(
                ~present, jnp.int8(absent_code),
                jnp.where(sub_s == PASS, jnp.int8(PASS),
                          nonpass)).astype(jnp.int8)
            return s, zd(s), nofd(s)
        if kind in ('forall', 'exists', 'scalars'):
            ap = array_prefix[node.slot.path]
            arr_tag = t[f'{ap}_tag']
            count = t[f'{ap}_count']
            ovf = t[f'{ap}_overflow']
            valid = jnp.arange(dims['E']) < count[..., None]
            if kind == 'scalars':
                # scalar-vs-array failures report the ARRAY's path
                # (validate_pattern.py:61-66), so fdet needs no element
                k = eval_expr(t, node.expr, depth + 1)
                any_fail = jnp.any(valid & k.f, axis=-1)
                any_unk = jnp.any(valid & k.unknown(), axis=-1) | ovf
                s = jnp.where(
                    arr_tag != TAG_ARRAY, jnp.int8(FAIL),
                    jnp.where(any_fail, jnp.int8(FAIL),
                              jnp.where(any_unk, jnp.int8(HOST),
                                        jnp.int8(PASS)))).astype(jnp.int8)
                return s, zd(s), site_fd(node, s)
            sub_s, _, sub_fd = eval_status(t, node.sub, depth + 1)
            if kind == 'exists':
                # reference: pkg/engine/anchor/handlers.go:228 — missing
                # key passes, non-list fails, ≥1 element must validate;
                # both failure modes report the anchored key's path
                satisfied = jnp.any(valid & (sub_s == PASS), axis=-1)
                maybe = jnp.any(valid & (sub_s == HOST), axis=-1) | ovf
                s = jnp.where(
                    arr_tag == TAG_MISSING, jnp.int8(PASS),
                    jnp.where(arr_tag != TAG_ARRAY, jnp.int8(FAIL),
                              jnp.where(satisfied, jnp.int8(PASS),
                                        jnp.where(maybe, jnp.int8(HOST),
                                                  jnp.int8(FAIL)))))
                return s.astype(jnp.int8), zd(s), site_fd(node, s)
            # forall (validateArrayOfMaps, validate.go:218)
            fail_at = valid & (sub_s == FAIL)
            any_fail = jnp.any(fail_at, axis=-1)
            any_host = jnp.any(valid & (sub_s == HOST), axis=-1) | ovf
            any_skip = jnp.any(valid & (sub_s == SKIP), axis=-1)
            any_pass = jnp.any(valid & (sub_s == PASS), axis=-1)
            s = jnp.where(
                arr_tag != TAG_ARRAY, jnp.int8(FAIL),
                jnp.where(any_fail, jnp.int8(FAIL),
                          jnp.where(any_host, jnp.int8(HOST),
                                    jnp.where(any_skip & ~any_pass,
                                              jnp.int8(SKIP),
                                              jnp.int8(PASS)))))
            # the host raises on the FIRST failing element in index order
            # (validate_pattern.py:136); an undecidable element BEFORE it
            # could itself be the true first failure → path ambiguous
            idx = jnp.argmax(fail_at, axis=-1)
            before = jnp.arange(dims['E']) < idx[..., None]
            ambiguous = jnp.any(before & valid & (sub_s == HOST), axis=-1)
            sel = jnp.take_along_axis(
                sub_fd, idx[..., None].astype(jnp.int32), axis=-1)[..., 0]
            elem_fd = jnp.where(
                ambiguous | (sel < 0), jnp.int32(-1),
                sel | (idx.astype(jnp.int32) << (8 * depth)))
            fd = jnp.where(arr_tag != TAG_ARRAY, site_fd(node, s), elem_fd)
            return s.astype(jnp.int8), zd(s), fd
        if kind == 'foreach':
            # engine.py:611 _validate_foreach: entries in order; the
            # first non-pass element outcome decides; zero applied
            # elements overall → 'rule skipped'
            n = t[next(iter(t))].shape[0]
            nonpass = jnp.zeros(n, bool)
            unknown = jnp.zeros(n, bool)
            apply_any = jnp.zeros(n, bool)
            # fd_ok: the FIRST entry with any non-pass/unknown outcome
            # decided by a deny-condition element fail — its message is the
            # static 'validation failure: …'; a last-index ERROR element or
            # an earlier undecidable entry makes the outcome/message
            # ambiguous (engine.py:663 error-continue semantics)
            fd_ok = jnp.zeros(n, bool)
            for entry in node.operand:
                lp = gather_prefix[entry.list_gather]
                lkind = t[f'{lp}_kind']
                lcount = t[f'{lp}_count']
                lovf = t[f'{lp}_overflow']
                # list query failures (NotFound / interpreter errors) skip
                # the entry silently (engine.py:615-618) → kind 0
                active = lkind != 0
                lview = _View(t, lp)
                gw = lview.tag.shape[-1]
                valid = (jnp.arange(gw) < lcount[:, None]) & \
                    (lview.tag != TAG_NULL)  # null elements are skipped
                # element variable errors (first missing var → ERROR elem)
                elem_err = jnp.zeros((n, gw), bool)
                for eg in entry.err_gathers:
                    elem_err = elem_err | t[f'{elem_prefix[eg]}_notfound']
                def at_elem(k: _K) -> _K:
                    # ktpu: noqa[KTPU203] -- deliberate rank
                    # specialization for const-folded conditions
                    if k.t.ndim == 1:
                        return _K(k.t[:, None], k.f[:, None])
                    return k
                if entry.precond is not None:
                    pre = at_elem(eval_expr(t, entry.precond, 0))
                else:
                    pre = _K.const((n, gw), True)
                deny = at_elem(eval_expr(t, entry.deny, 0))
                e_fail = ~elem_err & pre.t & deny.t
                e_pass = ~elem_err & pre.t & deny.f
                e_unknown = ~elem_err & (pre.unknown() |
                                         (pre.t & deny.unknown()))
                any_fail = jnp.any(valid & e_fail, axis=-1)
                # an ERROR element returns only at the true last index
                # (engine.py:663-665); overflow hides the true length
                last_err = jnp.take_along_axis(
                    elem_err & valid,
                    jnp.maximum(lcount - 1, 0)[:, None],
                    axis=-1)[..., 0] & ~lovf
                entry_nonpass = active & (any_fail | last_err)
                entry_unknown = active & (
                    jnp.any(valid & e_unknown, axis=-1) | lovf) & \
                    ~entry_nonpass
                entry_apply = active & jnp.any(valid & e_pass, axis=-1)
                fd_ok = fd_ok | (~(nonpass | unknown) & active & any_fail)
                nonpass = nonpass | entry_nonpass
                unknown = unknown | entry_unknown
                apply_any = apply_any | entry_apply
            s = jnp.where(
                nonpass, jnp.int8(FAIL),
                jnp.where(unknown, jnp.int8(HOST),
                          jnp.where(apply_any, jnp.int8(PASS),
                                    jnp.int8(SKIP)))).astype(jnp.int8)
            fd = jnp.where(fd_ok, jnp.int32(0), jnp.int32(-1))
            return s, jnp.zeros(n, jnp.int8), fd
        if kind == 'trackfail':
            sub_s, sub_d, sub_fd = eval_status(t, node.sub, depth)
            guard = eval_expr(t, node.expr, depth)
            s = jnp.where(sub_s == FAIL,
                          jnp.where(guard.t, jnp.int8(FAIL),
                                    jnp.int8(HOST)),
                          sub_s).astype(jnp.int8)
            return s, sub_d, sub_fd
        raise ValueError(f'unknown status kind {kind!r}')

    # whole-program dedup, computed STATICALLY: replicated/near-duplicate
    # policies (the common case in large real policy sets — and the
    # 1k-policy admission benchmark) compile identical status trees.
    # Each unique tree is traced ONCE; the compact (match-carrying) path
    # keeps the whole device graph AND the d2h readback in unique space
    # — duplicate columns are expanded on the host with one numpy
    # gather, so a 1000-policy replicated set compiles and ships like
    # its ~30 unique rules.
    uniq_idx_list: List[int] = []
    uniq_trees: List[Any] = []
    _memo: Dict[Any, int] = {}
    for _prog in cps.programs:
        try:
            _u = _memo.get(_prog.status)
            _memo_key = _prog.status
        except TypeError:  # unhashable operand somewhere in the tree
            _u = None
            _memo_key = None
        if _u is None:
            _u = len(uniq_trees)
            uniq_trees.append(_prog.status)
            if _memo_key is not None:
                _memo[_memo_key] = _u
        uniq_idx_list.append(_u)
    n_uniq = len(uniq_trees)
    uniq_idx_np = np.asarray(uniq_idx_list, np.int64) if uniq_idx_list \
        else np.zeros(0, np.int64)
    # aux channels per unique tree (anyPattern child fail channels; at
    # most one 'any' unit per program — a rule has one validate form)
    uniq_aux_base: List[int] = []
    uniq_any: List[Tuple[int, int]] = []  # (unique idx, n children)
    _aux_u_total = 0
    for _u, _tree in enumerate(uniq_trees):
        uniq_aux_base.append(_aux_u_total)
        _units = _tree.children if _tree.kind == 'seq' else (_tree,)
        for _unit in _units:
            if _unit.kind == 'any':
                uniq_any.append((_u, len(_unit.children)))
                _aux_u_total += len(_unit.children)
    # frozen before any trace closes over it: a tuple can never drift
    # under a cached executable (ktpu-lint KTPU201)
    uniq_any = tuple(uniq_any)
    n_cols = len(cps.programs) + _aux_cols
    n_cols_u = n_uniq + _aux_u_total
    # program-space column -> unique-space column, for host expansion
    expand_idx_np = np.zeros(n_cols, np.int64)
    expand_idx_np[:len(cps.programs)] = uniq_idx_np
    for _j in sorted(any_meta, key=lambda jj: any_meta[jj][0]):
        _base, _cnt = any_meta[_j]
        _ub = uniq_aux_base[uniq_idx_list[_j]]
        for _c in range(_cnt):
            expand_idx_np[len(cps.programs) + _base + _c] = \
                n_uniq + _ub + _c
    expand_identity = bool(
        n_cols == n_cols_u and
        np.array_equal(expand_idx_np, np.arange(n_cols)))
    # program columns sharing one unique tree, for host match folding
    uniq_groups: List[np.ndarray] = [
        np.flatnonzero(uniq_idx_np == u) for u in range(n_uniq)]

    def evaluate_unique(t: Dict[str, jnp.ndarray]):
        """Trace the unique status trees only; returns unique-space
        (s_u, d_u, fdet_u) with aux channels appended past n_uniq."""
        leaf_cache.clear()
        cond_cache.clear()
        aux_acc.clear()
        # element width of this batch (dynamic; see encode._measure_elems)
        # — probed from slot ('sN_') or array ('aN_') tags, not gathers
        dims['E'] = next(
            (arr.shape[1] for name, arr in sorted(t.items())
             if name.endswith('_tag') and arr.ndim >= 2
             and name[0] in 'sa'), 0)
        cols, dets, fds = [], [], []
        for tree in uniq_trees:
            s, d, fd = eval_status(t, tree, 0)
            cols.append(s)
            dets.append(d)
            fds.append(fd)
        if not cols:
            n = t[next(iter(t))].shape[0] if t else 0
            z = jnp.zeros((n, 0), jnp.int8)
            return z, z, jnp.zeros((n, 0), jnp.int32)
        s_u = jnp.stack(cols, axis=1)
        d_u = jnp.stack(dets, axis=1)
        fd_u = jnp.stack(fds, axis=1)
        if aux_acc:
            fd_u = jnp.concatenate(
                [fd_u, jnp.stack(list(aux_acc), axis=1)], axis=1)
        return s_u, d_u, fd_u

    def evaluate(t: Dict[str, jnp.ndarray]):
        """Program-space evaluation (mesh path / raw consumers): unique
        results expanded by a device-side column gather."""
        s_u, d_u, fdet_u = evaluate_unique(t)
        if n_uniq == 0:
            return s_u, d_u, fdet_u
        if expand_identity:
            return s_u, d_u, fdet_u
        pid = uniq_idx_np
        statuses = s_u[:, pid]
        details = d_u[:, pid]
        fdet = fdet_u[:, expand_idx_np]
        return statuses, details, fdet

    layout_holder: Dict[str, Any] = {'layout': None}

    #: fixed per-row budget of fail-detail cells shipped back to the
    #: host.  fdet is ~75% of the chunk's device→host bytes and d2h is
    #: the scarce direction over a remote-TPU tunnel; only (matched,
    #: FAIL) cells are ever read, so the device compacts them to the
    #: first K relevant columns.  Overflow rows keep exactness: their
    #: missing cells read -1 → host materialization.
    fdet_k = int(os.environ.get('KTPU_FDET_K', '32'))

    def evaluate_packed(packed: Dict[str, jnp.ndarray]):
        # ktpu: noqa[KTPU201] -- layout is trace-static by contract:
        # compile_lock serializes every trace, and the AOT cache key
        # bakes the batch layout into the executable's identity
        t = unpack_batch(packed, layout_holder['layout'])
        # ragged batches: rows past the live row count are canonical-
        # capacity padding.  Per-row outputs for them are sliced off on
        # the host; everything that selects or reduces ACROSS rows in
        # the graph masks them here so one compiled capacity serves
        # every occupancy with bit-identical output.
        rowvalid = t.pop('__rowvalid__', None)
        match = t.pop('__match__', None)
        adm_in = {name: t.pop(name) for name in ADM_LANES if name in t}
        if not t and rowvalid is not None:
            # slot-free policy sets (e.g. pure deny-by-subject rules —
            # exactly the admission-lane vocabulary) still need one
            # reference array for constant-tree row shapes
            t = {'__rowref__': rowvalid}
        if match is None:
            return evaluate(t)
        # compact form, all in UNIQUE space (match arrives pre-folded to
        # [R, n_uniq]): ship (statuses|details) as one int8 buffer and
        # the (matched & FAIL) fail-detail cells as [cols | fds]; the
        # host expands duplicates with one gather (expand_compact)
        s_u, d_u, fdet_u = evaluate_unique(t)
        rel_main = (s_u == FAIL) & (match != 0)
        if rowvalid is not None:
            rel_main = rel_main & (rowvalid != 0)[:, None]
        parts = [rel_main]
        for u, cnt in uniq_any:
            parts.append(jnp.broadcast_to(rel_main[:, u:u + 1],
                                          (s_u.shape[0], cnt)))
        rel = parts[0] if len(parts) == 1 else jnp.concatenate(parts, axis=1)
        c = fdet_u.shape[1]
        # fixed budget: d2h bytes over a remote-TPU tunnel are the
        # scan's scarcest resource, and rows overflowing the budget
        # degrade to exact host materialization, never wrong answers
        k = min(fdet_k, c)
        col_idx = jnp.arange(c, dtype=jnp.int32)
        keys = jnp.where(rel, col_idx, jnp.int32(c))
        order = jnp.sort(keys, axis=1)[:, :k]
        fds = jnp.take_along_axis(
            fdet_u, jnp.minimum(order, c - 1).astype(jnp.int32), axis=1)
        out32 = jnp.concatenate([order, fds.astype(jnp.int32)], axis=1)
        out8 = jnp.concatenate([s_u, d_u], axis=1)
        if adm_table is not None and len(adm_in) == len(ADM_LANES):
            # per-row admission match for eligible programs, decided
            # in-graph and shipped back as extra int8 columns (the host
            # replaces its conservative match upper bound with these
            # before assembly; rows the encoder marked non-valid are
            # ignored there)
            adm = _adm_match_graph(adm_table, adm_in).astype(jnp.int8)
            out8 = jnp.concatenate([out8, adm], axis=1)
        return out8, out32

    jitted = jax.jit(evaluate_packed)
    # compile/AOT keys derive from the fingerprint of the policies THIS
    # evaluator compiles — the whole set in monolithic mode, one
    # partition's members under KTPU_PARTITIONS (partition/keys.py is
    # the sanctioned source; ktpu-lint KTPU508 keeps whole-set
    # fingerprints out of executable cache keys elsewhere)
    from ..partition.keys import compile_fingerprint
    fingerprint = compile_fingerprint(cps)
    exec_cache: Dict[str, Any] = {}
    # id(compiled) -> ledger key: dispatch-site attribution for the
    # executable lifecycle ledger without re-deriving the cache key per
    # call (entries live exactly as long as exec_cache holds them)
    exec_keys: Dict[int, str] = {}
    # input signatures the jitted fallback has already traced — mirrors
    # jax.jit's own cache key well enough for hit/miss telemetry on the
    # paths where the AOT executable cache is unavailable (mesh, >1
    # local device)
    jit_seen: set = set()
    # one lock covers exec_cache AND every trace of evaluate_packed:
    # the trace reads layout_holder, so an unsynchronized concurrent
    # call could bake another batch shape's layout into the executable
    # (and the AOT store would persist the poisoned artifact to disk)
    compile_lock = __import__('threading').RLock()

    def _compiled_for(packed, layout) -> Optional[Any]:
        """Executable for this input signature: memory → AOT disk →
        trace+compile (and populate both).  None → mesh-sharded inputs
        or AOT disabled; caller falls back to the jitted path."""
        import time as _time
        from ..compiler import aot
        from ..observability import device as devtel
        from ..observability import executables as exectel
        key = aot.executable_cache_key(fingerprint, packed,
                                       extra=(str(fdet_k),))
        if key is None:
            return None
        with compile_lock:
            hit = exec_cache.get(key)
            if hit is not None:
                devtel.record_cache('hit')
                return hit
        # the packed buffers all lead with the resource axis, so any
        # buffer's first dim is the canonical row capacity (ledger
        # attribute; pack_batch coalesces per dtype, capacity-invariant)
        capacity = next((int(v.shape[0]) for v in packed.values()
                         if getattr(v, 'ndim', 0) >= 1), 0) \
            if exectel.enabled() else 0
        # the disk deserialize runs OUTSIDE the compile lock: it never
        # touches layout_holder, and the shape warmer loads the
        # canonical capacities on a thread pool — serializing the
        # (tens-of-seconds) deserializes here would make warm-up a sum
        # instead of a max.  Two racers on ONE key at worst both
        # deserialize; setdefault keeps a single winner.
        with devtel.stage('compile') as st:
            t0 = _time.monotonic()
            loaded = aot.load_executable(key)
            if loaded is not None:
                devtel.record_cache('aot_load')
                st.set_attribute('cache', 'aot_load')
                with compile_lock:
                    winner = exec_cache.setdefault(key, loaded)
                    if winner is loaded and exectel.enabled():
                        exec_keys[id(winner)] = key
                        exectel.record_build(
                            key, fingerprint=fingerprint,
                            capacity=capacity, source='aot_load',
                            build_s=_time.monotonic() - t0,
                            compiled=winner)
                    return winner
            with compile_lock:
                hit = exec_cache.get(key)
                if hit is not None:
                    devtel.record_cache('hit')
                    return hit
                layout_holder['layout'] = layout
                t0 = _time.monotonic()
                loaded = jitted.lower(packed).compile()
                devtel.record_cache('miss')
                st.set_attribute('cache', 'miss')
                if exectel.enabled():
                    exec_keys[id(loaded)] = key
                    exectel.record_build(
                        key, fingerprint=fingerprint, capacity=capacity,
                        source='fresh_compile',
                        build_s=_time.monotonic() - t0, compiled=loaded)
                aot.store_executable_async(key, loaded)
                devtel.record_cache('aot_store')
                exec_cache[key] = loaded
                return loaded

    def _evict_aot(packed) -> None:
        """Drop a poisoned AOT entry (memory + disk) so the next call
        recompiles instead of re-failing."""
        from ..compiler import aot
        key = aot.executable_cache_key(fingerprint, packed,
                                       extra=(str(fdet_k),))
        if key is None:
            return
        with compile_lock:
            dropped = exec_cache.pop(key, None)
            if dropped is not None:
                exec_keys.pop(id(dropped), None)
        aot.evict_executable(key, reason='execute_failed')

    def call(packed: Dict[str, Any],
             layout: Dict[str, Tuple[str, int, int, Tuple[int, ...]]]):
        # i64 lanes are required: quantity milli-values span past 2^31.
        # Scope x64 to this call instead of flipping the process-global
        # flag at import time.
        import time as _time
        from ..observability import device as devtel
        from ..observability import executables as exectel
        with enable_x64():
            try:
                compiled = _compiled_for(packed, layout)
            except Exception:  # noqa: BLE001 - AOT is an optimization
                compiled = None
            if compiled is not None:
                try:
                    with devtel.stage('device_eval') as st:
                        _stamp_coverage(st)
                        if exectel.enabled():
                            t0 = _time.monotonic()
                            out = compiled(packed)
                            exectel.record_dispatch(
                                exec_keys.get(id(compiled), ''),
                                _time.monotonic() - t0)
                            return out
                        return compiled(packed)
                except Exception:  # noqa: BLE001 - a deserialized
                    # executable can fail at EXECUTE time (e.g. machine-
                    # feature mismatch); evict it and fall through to a
                    # fresh trace+compile rather than surfacing a device
                    # failure to the circuit breaker
                    _evict_aot(packed)
            with compile_lock:
                layout_holder['layout'] = layout
                exec_on = exectel.enabled()
                pkey = ''
                if devtel.enabled() or exec_on:
                    sig = tuple(
                        (k, str(v.dtype), tuple(v.shape))
                        for k, v in sorted(packed.items()))
                    if exec_on:
                        # no AOT cache key on this path (mesh / AOT
                        # off): a process-local pseudo-key names the
                        # jit-backed executable in the ledger
                        pkey = f'jit:{fingerprint[:12]}:' \
                               f'{abs(hash(sig)):x}'
                    if sig not in jit_seen:
                        # first call at this signature pays jit trace +
                        # XLA compile inside the dispatch — time it as
                        # the compile stage (jit caches internally, so
                        # a separate lower().compile() would double-pay)
                        jit_seen.add(sig)
                        devtel.record_cache('miss')
                        with devtel.stage('compile') as st:
                            st.set_attribute('cache', 'miss')
                            t0 = _time.monotonic()
                            out = jitted(packed)
                            if exec_on:
                                exectel.record_build(
                                    pkey, fingerprint=fingerprint,
                                    capacity=next(
                                        (int(v.shape[0])
                                         for v in packed.values()
                                         if getattr(v, 'ndim', 0) >= 1),
                                        0),
                                    source='persistent_xla',
                                    build_s=_time.monotonic() - t0)
                            return out
                    devtel.record_cache('hit')
                with devtel.stage('device_eval') as st:
                    _stamp_coverage(st)
                    if pkey:
                        t0 = _time.monotonic()
                        out = jitted(packed)
                        exectel.record_dispatch(
                            pkey, _time.monotonic() - t0)
                        return out
                    return jitted(packed)

    call.jitted = jitted
    call.raw = evaluate
    call.layout_holder = layout_holder
    call.compile_lock = compile_lock
    call.any_meta = any_meta
    call.fingerprint = fingerprint
    call.n_cols = n_cols
    call.n_programs = len(cps.programs)
    call.n_uniq = n_uniq
    call.n_cols_u = n_cols_u
    call.uniq_idx = uniq_idx_np
    call.expand_idx = expand_idx_np
    call.expand_identity = expand_identity
    call.uniq_groups = uniq_groups
    call.adm_table = adm_table
    call.n_adm = len(adm_table.programs) if adm_table is not None else 0
    call.adm_cols = adm_table.program_cols() if adm_table is not None \
        else np.zeros(0, np.int64)
    return call


def _stamp_coverage(st) -> None:
    """Attribute the device-coverage ratio of the most recently
    completed scan onto a device_eval stage span (the assembly that
    decides THIS dispatch's ratio runs after it; the ledger's last
    ratio is the freshest attributable value)."""
    from ..observability import coverage
    ratio = coverage.last_ratio()
    if ratio is not None:
        st.set_attribute('device_coverage_ratio', round(ratio, 4))


def fold_match_unique(mm: np.ndarray, evaluator) -> np.ndarray:
    """Fold a program-space [R, P] match mask to unique-program space
    [R, U] (OR over duplicate columns) for the compact device path."""
    if evaluator.n_uniq == len(evaluator.uniq_idx) or mm.shape[1] == 0:
        return mm
    out = np.zeros((mm.shape[0], evaluator.n_uniq), mm.dtype)
    for u, cols in enumerate(evaluator.uniq_groups):
        if cols.size == 1:
            out[:, u] = mm[:, cols[0]]
        else:
            out[:, u] = mm[:, cols].max(axis=1)
    return out


def expand_compact(out8: np.ndarray, out32: np.ndarray, evaluator):
    """Reconstruct program-space (statuses, details, dense fdet,
    admission-match) from the unique-space compact device outputs.
    Cells beyond the per-row budget stay -1, which downstream message
    synthesis treats as 'materialize on host' — exactness is never
    lost.  The trailing admission columns (None when the policy set has
    no admission-eligible rules) are the in-graph per-row match
    decisions for ``evaluator.adm_cols``."""
    n_adm = getattr(evaluator, 'n_adm', 0)
    width = out8.shape[1] - n_adm
    n_uniq = width // 2
    s_u = out8[:, :n_uniq]
    d_u = out8[:, n_uniq:n_uniq * 2]
    adm = out8[:, width:] if n_adm else None
    k = out32.shape[1] // 2
    cols = out32[:, :k]
    fds = out32[:, k:]
    dense_u = np.full((out8.shape[0], evaluator.n_cols_u), -1, np.int32)
    rr, kk = np.nonzero(cols < evaluator.n_cols_u)
    dense_u[rr, cols[rr, kk]] = fds[rr, kk]
    if evaluator.expand_identity:
        return s_u, d_u, dense_u, adm
    pid = evaluator.uniq_idx
    return (s_u[:, pid], d_u[:, pid], dense_u[:, evaluator.expand_idx],
            adm)


def enable_x64():
    # jax 0.4.37 dropped the (never-public) jax.enable_x64 alias; the
    # supported spelling is jax.experimental.enable_x64
    from jax.experimental import enable_x64 as _enable_x64
    return _enable_x64()


#: pack plans memoized by lane signature — admission serves thousands of
#: identical-signature single-request packs, and rebuilding the grouping
#: (dtype stringification, offset bookkeeping over ~900 lanes) per call
#: costs more than the actual concatenation
_PACK_PLANS: Dict[Tuple, Tuple] = {}


def pack_batch(tensors: Dict[str, np.ndarray]):
    """Coalesce all lanes into ONE flat [R, W] buffer per dtype.

    The encoder produces hundreds of small per-lane arrays; transferring
    each individually costs one host→device round trip apiece (dominant
    over a remote-TPU tunnel, where per-transfer latency — not
    bandwidth — bounds the pipeline).  Every lane has the resource axis
    leading, so each is viewed as [R, prod(rest)] and concatenated per
    dtype; the evaluator unpacks with static slices + reshapes that XLA
    folds away.  Five dtypes → five host→device transfers per chunk.
    """
    sig = tuple((name, arr.dtype.num, arr.shape)
                for name, arr in sorted(tensors.items()))
    plan = _PACK_PLANS.get(sig)
    if plan is None:
        groups: Dict[str, List[Tuple[str, np.ndarray]]] = {}
        for name, arr in sorted(tensors.items()):
            groups.setdefault(str(arr.dtype), []).append((name, arr))
        layout: Dict[str, Tuple[str, int, int, Tuple[int, ...]]] = {}
        group_names: List[Tuple[str, List[str]]] = []
        for dt, members in sorted(groups.items()):
            r = members[0][1].shape[0]
            off = 0
            names: List[str] = []
            for name, arr in members:
                w = int(np.prod(arr.shape[1:], dtype=np.int64)) \
                    if arr.ndim > 1 else 1
                layout[name] = (f'pk_{dt}', off, w, arr.shape[1:])
                names.append(name)
                off += w
            group_names.append((f'pk_{dt}', names))
        plan = (layout, group_names)
        if len(_PACK_PLANS) > 256:
            _PACK_PLANS.clear()
        _PACK_PLANS[sig] = plan
    layout, group_names = plan
    packed: Dict[str, np.ndarray] = {}
    for buf_name, names in group_names:
        r = tensors[names[0]].shape[0]
        parts = [tensors[n].reshape(r, -1) for n in names]
        packed[buf_name] = parts[0] if len(parts) == 1 \
            else np.concatenate(parts, axis=1)
    return packed, layout


def unpack_batch(packed: Dict[str, Any],
                 layout: Dict[str, Tuple[str, int, int, Tuple[int, ...]]]
                 ) -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    for name, (g, off, width, tail) in layout.items():
        buf = packed[g]
        sl = buf[:, off:off + width]
        out[name] = sl.reshape((buf.shape[0],) + tuple(tail))
    return out


def shard_batch(tensors: Dict[str, np.ndarray], mesh=None,
                axis: str = 'data', device=None) -> Dict[str, Any]:
    """Pack + place batch tensors, optionally sharded over a 1-D mesh
    (the resource axis of packed buffers is axis 0) or pinned to an
    explicit single device (small-batch CPU path).  int64 inputs are
    transferred inside an x64 scope so they are not downcast.  Returns
    (packed_device_dict, layout)."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    from ..observability import device as devtel
    with devtel.stage('pack'):
        packed, layout = pack_batch(tensors)
    with enable_x64(), devtel.stage('h2d') as st:
        st.set_attribute('bytes', sum(v.nbytes for v in packed.values()))
        if mesh is None:
            if device is not None:
                return ({k: jax.device_put(v, device)
                         for k, v in packed.items()}, layout)
            return {k: jnp.asarray(v) for k, v in packed.items()}, layout
        out = {}
        for k, v in packed.items():
            spec = P(axis, *([None] * (v.ndim - 1)))
            out[k] = jax.device_put(v, NamedSharding(mesh, spec))
        return out, layout
