"""The device mutate evaluator: lanes → (status, edit bitmask, reason).

One jitted straight-line program per lowered policy set, batched over
resources and edit sites.  Per (resource, site) it decides whether the
edit applies — leaf missing → apply; add-only anchors skip present
leaves; otherwise apply iff the encoded value differs from the patch
constant (Python equality semantics: bool/int/float compare through the
exact milli lane, strings through length + byte window; cross-kind
never equal except the numeric tower) — then reduces sites to per-rule
outputs:

  status  i8 [R, NR]   0 = SKIP (no edits), 1 = PASS (edit list
                       non-empty), 2 = FALLBACK (host applies)
  edits   i64 [R, NR]  bitmask over the rule's sites (bit k = site k
                       applies); the host decodes it into a (slot,
                       value) edit list and patches the JSON
  reason  i8 [R, NR]   first-fault attribution for FALLBACK rows, in
                       the host fast path's check order: 1 = a
                       json6902 replace path is missing, 2 = a non-map
                       intermediate, 3 = equality undecidable in the
                       encoded lanes

The kernel is intentionally tiny (a few element-wise ops and one
matmul-shaped reduction per output) — it is not AOT-persisted; XLA
compiles it once per canonical batch capacity (compiler/shapes.py).
Rows past the live row count (the ``valid`` lane) are capacity
padding: their statuses, edit bitmasks, and reasons are forced to
SKIP/0 inside the jitted program so no cross-row consumer can ever
observe them.
"""

from __future__ import annotations

from typing import Dict, Tuple

import numpy as np

from ..compiler.ir import TAG_BOOL, TAG_FLOAT, TAG_INT, TAG_MISSING, \
    TAG_STRING
from .encode import exact_milli, string_window
from .plan import MutateSetProgram

#: per-(resource, rule) device statuses
MUT_SKIP = 0
MUT_PASS = 1
MUT_FALLBACK = 2

#: FALLBACK reason codes (decoded to taxonomy slugs in scanner.py)
RC_NONE = 0
RC_REPLACE_MISSING = 1
RC_NON_DICT = 2
RC_UNDECIDABLE = 3


class MutateKernel:
    """Compile-time constants + the jitted evaluator for one program."""

    def __init__(self, program: MutateSetProgram):
        sites = [(ri, k, site)
                 for ri, prog in enumerate(program.programs)
                 for k, site in enumerate(prog.sites)]
        self.n_rules = len(program.programs)
        self.n_sites = len(sites)
        self.width = string_window(program)
        s, w = self.n_sites, self.width
        self._t_is_num = np.zeros(s, bool)
        self._t_milli = np.zeros(s, np.int64)
        self._t_len = np.zeros(s, np.int32)
        self._t_bytes = np.zeros((s, w), np.uint8)
        self._add_only = np.zeros(s, bool)
        self._replace = np.zeros(s, bool)
        # site → rule selector and the site's bit weight in its rule's
        # edit mask; both feed the matmul-shaped per-rule reductions
        self._onehot = np.zeros((s, self.n_rules), np.int64)
        self._bit_w = np.zeros(s, np.int64)
        for idx, (ri, k, site) in enumerate(sites):
            v = site.value
            if isinstance(v, str) and not isinstance(v, bool):
                b = v.encode('utf-8')
                self._t_len[idx] = len(b)
                self._t_bytes[idx, :min(len(b), w)] = \
                    np.frombuffer(b[:w], np.uint8)
            else:
                self._t_is_num[idx] = True
                m = exact_milli(v)
                # lowering guarantees representable constants
                self._t_milli[idx] = 0 if m is None else m
            self._add_only[idx] = site.add_only
            self._replace[idx] = site.replace
            self._onehot[idx, ri] = 1
            self._bit_w[idx] = np.int64(1) << np.int64(k)
        self._jitted = None

    def _eval(self, lanes):
        import jax.numpy as jnp
        tag = lanes['tag']
        istate = lanes['istate']
        milli = lanes['milli']
        milli_ok = lanes['milli_ok']
        slen = lanes['slen']
        sbytes = lanes['sbytes']
        missing = tag == TAG_MISSING
        bad = istate == 2
        present = (~missing) & (~bad)
        num_tag = (tag == TAG_BOOL) | (tag == TAG_INT) | \
            (tag == TAG_FLOAT)
        eq_num = self._t_is_num & present & num_tag & milli_ok & \
            (milli == self._t_milli)
        undec = self._t_is_num & present & num_tag & (~milli_ok) & \
            (~self._add_only)
        eq_str = (~self._t_is_num) & present & (tag == TAG_STRING) & \
            (slen == self._t_len) & \
            jnp.all(sbytes == self._t_bytes, axis=-1)
        eq = eq_num | eq_str
        edit = jnp.where(missing & ~bad, True,
                         jnp.where(self._add_only, False,
                                   present & ~eq))
        rep_bad = self._replace & ((istate != 0) | missing)

        def per_rule(flag):
            return (flag.astype(jnp.int64) @ self._onehot) > 0

        edits = (edit.astype(jnp.int64) * self._bit_w) @ self._onehot
        rep_any = per_rule(rep_bad)
        bad_any = per_rule(bad)
        undec_any = per_rule(undec)
        fb = rep_any | bad_any | undec_any
        status = jnp.where(
            fb, MUT_FALLBACK,
            jnp.where(edits != 0, MUT_PASS, MUT_SKIP)).astype(jnp.int8)
        # first-fault reason in the host fast path's check order:
        # replace guard, then the non-dict walk, then equality
        reason = jnp.where(
            rep_any, RC_REPLACE_MISSING,
            jnp.where(bad_any, RC_NON_DICT,
                      jnp.where(undec_any, RC_UNDECIDABLE,
                                RC_NONE))).astype(jnp.int8)
        # ragged batches: capacity-padding rows (all-MISSING leaves
        # would otherwise read as "every edit applies") are masked to
        # SKIP / empty-bitmask / no-reason inside the program
        valid = lanes.get('valid')
        if valid is not None:
            vcol = valid[:, None]
            status = jnp.where(vcol, status, MUT_SKIP).astype(jnp.int8)
            edits = jnp.where(vcol, edits, 0)
            reason = jnp.where(vcol, reason, RC_NONE).astype(jnp.int8)
        return status, edits, reason

    def __call__(self, lanes: Dict[str, np.ndarray]
                 ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        n = lanes['tag'].shape[0]
        if self.n_sites == 0:
            return (np.zeros((n, self.n_rules), np.int8),
                    np.zeros((n, self.n_rules), np.int64),
                    np.zeros((n, self.n_rules), np.int8))
        from ..ops.eval import enable_x64
        with enable_x64():
            if self._jitted is None:
                import jax
                self._jitted = jax.jit(self._eval)
            out = self._jitted(lanes)
            return tuple(np.asarray(o) for o in out)
