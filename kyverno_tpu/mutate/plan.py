"""Lowering: mutate rules → fixed device edit-site programs.

A mutate rule lowers when its whole patch is expressible as a fixed set
of **edit sites** — (slot path, static scalar value) pairs with an
optional add-if-absent anchor or json6902 ``replace`` existence guard —
over the same wildcard-free slot-path vocabulary the validate encoder
resolves at encode time (``compiler/encode.py``).  The device program
then decides, per (resource, site), whether the edit applies, and emits
a compact per-rule edit bitmask the host decodes back into patched JSON
(``scanner.py``).  Anything outside that vocabulary — foreach, contexts,
preconditions, variables, anchors needing live lookups, list patches,
null values (RFC-7386 deletes), non-scalar values — does NOT lower and
keeps the host engine, attributed on the coverage ledger.

Set-level coupling: the admission mutate chain is CUMULATIVE (policy
k+1 sees policy k's patched output — handlers.py Mutate loop), while
the device decides every rule against the ORIGINAL document.  The two
agree exactly when (a) every lowered rule's match block is simple
(kinds/namespaces/operations — unaffected by scalar edits that cannot
touch identity fields) and (b) no two rules' edit sites overlap in the
prefix-or-equal sense.  ``compile_mutate_set`` enforces both; a set
that violates them places every mutate rule on the host with reason
``edit_site_conflict`` / ``policy_coupling``.
"""

from __future__ import annotations

from typing import Any, List, NamedTuple, Optional, Tuple

from ..api.policy import Policy, Rule
from ..observability import coverage
from ..compiler.mutate_compile import _compile_overlay, parse_json6902_sets

#: edit bitmask budget: one i32 lane per (resource, rule)
MAX_SITES = 32

#: resource-identity paths no lowered edit may write: match/exclude and
#: namespace gating read them, so a rule that mutates them could change
#: a later rule's match decision mid-chain
_IDENTITY_PATHS = (('kind',), ('apiVersion',), ('metadata', 'name'),
                   ('metadata', 'namespace'))


class LowerError(Exception):
    """A mutate rule cannot lower; carries its taxonomy reason."""

    def __init__(self, reason: str, detail: str):
        super().__init__(detail)
        self.reason = reason
        self.detail = detail


class EditSite(NamedTuple):
    path: Tuple[str, ...]   # slot path of the written leaf
    add_only: bool          # ``+(key)`` anchor: write only when absent
    value: Any              # static scalar (str | bool | int | float)
    replace: bool           # json6902 replace: whole path must exist


class RuleMutateProgram:
    """One lowered mutate rule: its edit sites + response metadata.

    ``path``/``pss`` satisfy the coverage ledger's program duck type
    (``ScanTally`` reads both), so mutate rows land on the ledger as
    ``path="mutate"`` next to validate/pss rows.
    """

    pss = None
    path = 'mutate'

    __slots__ = ('policy_name', 'rule_name', 'rule', 'kind', 'sites',
                 'policy_index', 'rule_index')

    def __init__(self, policy_name: str, rule_name: str, rule: Rule,
                 kind: str, sites: Tuple[EditSite, ...]):
        self.policy_name = policy_name
        self.rule_name = rule_name
        self.rule = rule
        self.kind = kind              # 'strategic' | 'json6902'
        self.sites = sites
        self.policy_index = -1        # filled by compile_mutate_set
        self.rule_index = -1


def _identity_site(path: Tuple[str, ...]) -> bool:
    return any(path[:len(idp)] == idp for idp in _IDENTITY_PATHS)


def _check_sites(sites: List[EditSite]) -> Tuple[EditSite, ...]:
    if len(sites) > MAX_SITES:
        raise LowerError(
            coverage.REASON_UNSUPPORTED_OPERATOR,
            f'{len(sites)} edit sites exceed the {MAX_SITES}-bit '
            f'per-rule edit bitmask')
    for site in sites:
        if site.value is None:
            raise LowerError(
                coverage.REASON_UNSUPPORTED_OPERATOR,
                'null patch values delete keys under RFC-7386 — '
                'outside the device edit vocabulary')
        if not isinstance(site.value, (str, bool, int, float)):
            raise LowerError(
                coverage.REASON_UNSUPPORTED_OPERATOR,
                f'non-scalar patch value at {"/".join(site.path)}')
        if _identity_site(site.path):
            raise LowerError(
                coverage.REASON_UNSUPPORTED_OPERATOR,
                f'edit writes the identity field {"/".join(site.path)} '
                f'— later rules\' match decisions could change '
                f'mid-chain')
    return tuple(sites)


def lower_mutate_rule(rule: Rule, policy_name: str) -> RuleMutateProgram:
    """Lower one mutate rule or raise :class:`LowerError` with the
    taxonomy reason the placement record carries."""
    raw = rule.raw
    if raw.get('context'):
        raise LowerError(coverage.REASON_API_CALL,
                         'rule context entries need live loads')
    if raw.get('preconditions') is not None:
        raise LowerError(coverage.REASON_UNSUPPORTED_OPERATOR,
                         'preconditions keep the engine path')
    mutation = raw.get('mutate') or {}
    if mutation.get('targets'):
        raise LowerError(coverage.REASON_HOST_CLOSURE,
                         'mutate-existing rides the UpdateRequest '
                         'pipeline')
    if mutation.get('foreach') is not None:
        raise LowerError(coverage.REASON_UNSUPPORTED_OPERATOR,
                         'foreach mutation keeps the host fast path')
    from ..compiler.scan import _rule_match_is_simple
    if not _rule_match_is_simple(raw):
        raise LowerError(
            coverage.REASON_UNSUPPORTED_OPERATOR,
            'non-simple match: the cumulative chain re-matches per '
            'policy, so only kind/namespace/operation matches are '
            'stable under device edits')
    overlay = mutation.get('patchStrategicMerge')
    json6902 = mutation.get('patchesJson6902')
    if overlay is not None and not json6902:
        sets = _compile_overlay(overlay)
        if sets is None:
            raise LowerError(
                coverage.REASON_UNSUPPORTED_OPERATOR,
                'overlay outside the static scalar vocabulary '
                '(anchors needing live lookups, lists, or variables)')
        sites = _check_sites([EditSite(path, add_only, value, False)
                              for path, add_only, value in sets])
        return RuleMutateProgram(policy_name, str(raw.get('name', '')),
                                 rule, 'strategic', sites)
    if json6902 and overlay is None:
        parsed = parse_json6902_sets(json6902)
        if parsed is None:
            raise LowerError(
                coverage.REASON_UNSUPPORTED_OPERATOR,
                'json6902 patch outside the static add/replace '
                'object-path vocabulary')
        sets, replace_paths = parsed
        rset = set(replace_paths)
        sites = _check_sites([EditSite(path, False, value, path in rset)
                              for path, _ao, value in sets])
        return RuleMutateProgram(policy_name, str(raw.get('name', '')),
                                 rule, 'json6902', sites)
    raise LowerError(coverage.REASON_UNSUPPORTED_OPERATOR,
                     'empty or mixed patch document')


def _paths_conflict(a: Tuple[str, ...], b: Tuple[str, ...]) -> bool:
    n = min(len(a), len(b))
    return a[:n] == b[:n]


class MutateSetProgram:
    """A whole mutate policy set lowered (or not) for device serving.

    ``device_ok`` is all-or-nothing: the cumulative admission chain
    means one unlowered or conflicting rule invalidates original-
    document device decisions for everything after it, so a set either
    serves entirely on device (with per-row host fallback) or entirely
    on the host engine.
    """

    def __init__(self, policies: List[Policy]):
        self.policies = list(policies)
        self.programs: List[RuleMutateProgram] = []
        self.per_policy: List[List[RuleMutateProgram]] = []
        self.placements: List[coverage.RulePlacement] = []
        self.device_ok = True
        failures: List[Tuple[int, Rule, LowerError]] = []
        lowered: List[Tuple[int, RuleMutateProgram]] = []
        for pi, policy in enumerate(self.policies):
            mutate_rules = [r for r in policy.rules if r.has_mutate()]
            if (policy.apply_rules or 'All') == 'One' and \
                    len(mutate_rules) > 1:
                self.device_ok = False
                for r in mutate_rules:
                    failures.append((pi, r, LowerError(
                        coverage.REASON_POLICY_COUPLING,
                        'applyRules=One early-exits between rules')))
                continue
            for r in mutate_rules:
                try:
                    prog = lower_mutate_rule(r, policy.name)
                except LowerError as e:
                    self.device_ok = False
                    failures.append((pi, r, e))
                    continue
                prog.policy_index = pi
                lowered.append((pi, prog))
        # cross-rule edit-site conflicts: prefix-or-equal overlap makes
        # original-document decisions order-dependent
        conflicted: set = set()
        for i in range(len(lowered)):
            for j in range(i + 1, len(lowered)):
                pa, a = lowered[i]
                pb, b = lowered[j]
                if a is b:
                    continue
                for sa in a.sites:
                    for sb in b.sites:
                        if _paths_conflict(sa.path, sb.path):
                            conflicted.add(id(a))
                            conflicted.add(id(b))
        if conflicted:
            self.device_ok = False
        # placements: device across the board, or host with the most
        # specific reason each rule earned
        for pi, policy in enumerate(self.policies):
            progs = [prog for ppi, prog in lowered if ppi == pi]
            self.per_policy.append(progs if self.device_ok else [])
            for prog in progs:
                if self.device_ok:
                    prog.rule_index = len(self.programs)
                    self.programs.append(prog)
                    self.placements.append(coverage.RulePlacement(
                        policy.name, prog.rule_name, 'mutate',
                        coverage.PLACEMENT_DEVICE, None, '', pi))
                elif id(prog) in conflicted:
                    self.placements.append(coverage.RulePlacement(
                        policy.name, prog.rule_name, 'mutate',
                        coverage.PLACEMENT_HOST,
                        coverage.REASON_SITE_CONFLICT,
                        'edit sites overlap another lowered rule — '
                        'cumulative ordering leaves the device '
                        'vocabulary', pi))
                else:
                    self.placements.append(coverage.RulePlacement(
                        policy.name, prog.rule_name, 'mutate',
                        coverage.PLACEMENT_HOST,
                        coverage.REASON_POLICY_COUPLING,
                        'rule lowered but a sibling mutate rule keeps '
                        'the set on the host engine', pi))
        for pi, r, e in failures:
            self.placements.append(coverage.RulePlacement(
                self.policies[pi].name, str(r.raw.get('name', '')),
                'mutate', coverage.PLACEMENT_HOST, e.reason, e.detail,
                pi))

    @property
    def n_sites(self) -> int:
        return sum(len(p.sites) for p in self.programs)


def compile_mutate_set(policies: List[Policy]) -> MutateSetProgram:
    return MutateSetProgram(policies)
