"""Device-side mutate: compiled patch kernels over edit-site lanes.

``plan`` lowers strategic-merge / json6902 mutate rules into fixed
edit-site programs, ``encode`` projects resources onto their lanes,
``kernel`` is the jitted per-(resource, rule) decision program, and
``scanner.MutateScanner`` ties them into the admission serving path
with the host engine as the bit-identity oracle.
"""

from .plan import (EditSite, LowerError, MutateSetProgram,
                   RuleMutateProgram, compile_mutate_set,
                   lower_mutate_rule)
from .scanner import MutateScanner

__all__ = ['EditSite', 'LowerError', 'MutateSetProgram',
           'RuleMutateProgram', 'compile_mutate_set',
           'lower_mutate_rule', 'MutateScanner']
