"""MutateScanner: batched device-side mutate for admission serving.

Compiles a mutate policy set once (``plan.compile_mutate_set``) and
evaluates admission batches as one device dispatch:

1. host match sieve per (resource, rule) — the same
   ``matches_resource_description`` call the engine loop makes, against
   the ORIGINAL document (sound because lowered rules are simple-match
   and edits cannot touch identity fields; see plan.py)
2. encode the edit-site lanes, run the jitted kernel → per-(resource,
   rule) status + edit bitmask + fallback reason (the *patch emit*
   stage, read back like fail details)
3. decode on the host: set bits → (slot, value) edit list →
   ``apply_edit_list`` copy-on-write patch → ``generate_patches`` diff
   → the exact ``EngineResponse`` the handler's engine loop would have
   produced (PASS message via ``_success_message``, SKIP as
   ``no patches applied``)

FALLBACK rows re-run the faulting policy on the host engine with the
row's cumulative ``PolicyContext`` — and every *later* policy of that
row also rides the engine, because an engine rerun may reshape the
document outside the device's original-document model.  Responses are
byte-identical to the host loop by construction either way; fallbacks
are attributed per rule on the coverage ledger (``path="mutate"``).

``scan`` accepts the same signature the admission batcher dispatches
(``resources/contexts/admission/pctx_factory/operations/
old_resources``), so mutate tickets ride the same queue and coalescing
loop as validate tickets.
"""

from __future__ import annotations

import time
from typing import Any, List, Optional, Tuple

import numpy as np

from ..api.policy import Policy
from ..api.unstructured import Resource
from ..engine.api import (EngineResponse, PolicyContext, RuleResponse,
                          RuleStatus, RuleType)
from ..engine.engine import Engine
from ..engine.match import matches_resource_description
from ..engine.mutate.jsonpatch import generate_patches
from ..engine.mutate.mutate import _success_message
from ..compiler.mutate_compile import apply_edit_list
from ..observability import coverage, tracing
from ..observability.metrics import global_registry
from .encode import encode_mutate_batch, string_window
from .kernel import (MUT_FALLBACK, MUT_PASS, MUT_SKIP, RC_NON_DICT,
                     RC_REPLACE_MISSING, RC_UNDECIDABLE, MutateKernel)
from .plan import MutateSetProgram, compile_mutate_set

MUTATE_PATCH_EMIT = 'kyverno_tpu_mutate_patch_emit_seconds'
MUTATE_DECODE = 'kyverno_tpu_mutate_decode_seconds'
MUTATE_EDITS = 'kyverno_tpu_mutate_device_edits_total'

_RC_REASON = {
    RC_REPLACE_MISSING: coverage.REASON_REPLACE_PATH_MISSING,
    RC_NON_DICT: coverage.REASON_NON_DICT,
    RC_UNDECIDABLE: coverage.REASON_PATCH_UNDECIDABLE,
}


class MutateScanner:
    """One compiled mutate policy set, served batch-at-a-time.

    ``ok`` is False when the set does not lower (see plan.py) — callers
    keep the host engine loop and the placement records already name
    why, per rule.
    """

    def __init__(self, policies: List[Policy],
                 engine: Optional[Engine] = None):
        self.policies = list(policies)
        self.engine = engine or Engine()
        self.program: MutateSetProgram = compile_mutate_set(self.policies)
        self.ok = self.program.device_ok and bool(self.program.programs)
        # serving coalesces on the scanner serial alone: the match sieve
        # below runs per row with that row's own admission tuple, so
        # mixed-user/mixed-verb mutate bursts share a dispatch
        from ..compiler.scan import next_scanner_serial
        self.serial = next_scanner_serial()
        self.supports_row_admissions = True
        if coverage.enabled():
            coverage.record_placements(self.program.placements)
        from ..aotcache.keys import policy_set_fingerprint
        self.fingerprint = policy_set_fingerprint(self.policies)
        self._kernel = MutateKernel(self.program) if self.ok else None
        self._width = string_window(self.program) if self.ok else 0

    def warmup(self) -> float:
        """Compile the admission-shape kernel bucket before traffic."""
        if not self.ok:
            return 0.0
        from ..compiler.scan import WARM_POD
        import copy
        t0 = time.monotonic()
        self.scan([copy.deepcopy(WARM_POD)])
        return time.monotonic() - t0

    # -- match ------------------------------------------------------------

    def _match_row(self, doc: dict, admission: Optional[tuple]):
        """Per-program match bits for one resource — the engine mutate
        loop's exact call (mutate.py:167), against the original doc."""
        info, roles, ns_labels = (admission or (None, [], {}))[:3]
        res = Resource(doc)
        out = np.zeros(len(self.program.programs), bool)
        for j, prog in enumerate(self.program.programs):
            policy = self.policies[prog.policy_index]
            out[j] = matches_resource_description(
                res, prog.rule, info, roles, ns_labels,
                policy.namespace) is None
        return out

    # -- scan -------------------------------------------------------------

    def scan(self, resources: List[dict],
             contexts: Optional[List[dict]] = None,
             admission: Optional[tuple] = None,
             pctx_factory=None,
             operations: Optional[List[str]] = None,
             old_resources: Optional[List[Optional[dict]]] = None,
             admissions: Optional[List[Optional[tuple]]] = None):
        """Per resource: ``(steps, patched)`` where ``steps`` is the
        ordered ``[(policy, EngineResponse), ...]`` chain the handler's
        host loop would produce (stopping after the first unsuccessful
        policy) and ``patched`` the cumulative document.  ``admissions``
        carries one admission tuple per row (heterogeneous batches);
        the match sieve runs each row against its own.  ``contexts``/
        ``operations``/``old_resources`` are accepted for batcher
        signature compatibility; mutation evaluates the new object."""
        if not self.ok:
            raise RuntimeError('mutate set is not device-lowered')
        n = len(resources)
        if n == 0:
            return []
        adm_rows = admissions if admissions is not None \
            else [admission] * n
        match = np.stack([self._match_row(doc, adm_rows[i])
                          for i, doc in enumerate(resources)])
        registry = global_registry()
        t0 = time.monotonic()
        with tracing.start_span('kyverno/mutate/patch_emit',
                                {'rows': n,
                                 'sites': self.program.n_sites}):
            # canonical capacity (compiler/shapes.py): the kernel masks
            # padding rows via the `valid` lane, so one compiled shape
            # serves every admission occupancy
            from ..compiler.shapes import canonical_capacity
            lanes = encode_mutate_batch(resources, self.program,
                                        padded_n=canonical_capacity(n),
                                        width=self._width)
            status, edits, reason = self._kernel(lanes)
        if registry is not None:
            registry.observe(MUTATE_PATCH_EMIT, time.monotonic() - t0)
        t1 = time.monotonic()
        with tracing.start_span('kyverno/mutate/decode', {'rows': n}):
            rows = [self._decode_row(resources[i], match[i], status[i],
                                     edits[i], reason[i], pctx_factory)
                    for i in range(n)]
        if registry is not None:
            registry.observe(MUTATE_DECODE, time.monotonic() - t1)
        return rows

    # -- decode -----------------------------------------------------------

    def _decode_row(self, doc: dict, match, status, edits, reason,
                    pctx_factory) -> Tuple[list, dict]:
        tally = coverage.scan_tally()
        if pctx_factory is not None:
            pctx = pctx_factory(doc)
        else:
            pctx = PolicyContext(None, new_resource=doc)
        steps: List[Tuple[Policy, EngineResponse]] = []
        host_rest = False
        for pi, policy in enumerate(self.policies):
            progs = self.program.per_policy[pi]
            if not any(r.has_mutate() for r in policy.rules):
                continue
            matched = [(prog.rule_index, prog) for prog in progs
                       if match[prog.rule_index]]
            pol_fb = any(int(status[j]) == MUT_FALLBACK
                         for j, _ in matched)
            ctx = pctx.copy()
            ctx.policy = policy
            if host_rest or pol_fb:
                er = self.engine.mutate(ctx)
                self._tally_host(tally, matched, reason,
                                 fallback=pol_fb and not host_rest)
                host_rest = True
            else:
                er = self._device_policy(policy, matched, status, edits,
                                         ctx, tally)
            steps.append((policy, er))
            if not er.is_successful():
                break
            # cumulative chain: the patched output re-enters the
            # context for the next policy (handlers.py Mutate loop)
            pctx = pctx.copy()
            pctx.new_resource = er.patched_resource or pctx.new_resource
            pctx.json_context.add_resource(pctx.new_resource)
        if tally is not None:
            tally.finish()
        return steps, pctx.new_resource

    def _tally_host(self, tally, matched, reason, fallback: bool) -> None:
        """Attribute one policy's engine rerun: the faulting rules keep
        their device-reported reason, siblings ride with the policy."""
        if tally is None:
            return
        for j, prog in matched:
            if fallback and int(reason[j]):
                tally.host_rule(prog.policy_name, prog.rule_name,
                                _RC_REASON.get(int(reason[j]),
                                               coverage.REASON_NON_DICT),
                                path='mutate')
            else:
                tally.host_rule(prog.policy_name, prog.rule_name,
                                coverage.REASON_POLICY_COUPLING,
                                path='mutate')

    def _device_policy(self, policy: Policy, matched, status, edits,
                       ctx: PolicyContext, tally) -> EngineResponse:
        """Materialize one policy's EngineResponse from device cells —
        field-for-field what the engine mutate loop builds for this
        vocabulary (statuses, messages, patches, patched doc)."""
        start = time.time()
        resp = EngineResponse(policy)
        cum = ctx.new_resource
        registry = global_registry()
        for j, prog in matched:
            if tally is not None:
                tally.total_rows += 1
            st = int(status[j])
            rule_start = time.time()
            if st == MUT_SKIP:
                rr = RuleResponse(prog.rule_name, RuleType.MUTATION,
                                  'no patches applied', RuleStatus.SKIP,
                                  patches=None)
            else:  # MUT_PASS
                mask = int(edits[j])
                changes = [(site.path, site.value)
                           for k, site in enumerate(prog.sites)
                           if mask & (1 << k)]
                patched = apply_edit_list(cum, changes)
                if patched is None:
                    # cannot happen for conflict-free site sets; keep
                    # the exactness contract via the engine anyway
                    raise RuntimeError('edit list failed to apply')
                patches = generate_patches(cum, patched)
                rr = RuleResponse(prog.rule_name, RuleType.MUTATION,
                                  _success_message(patched),
                                  RuleStatus.PASS, patches=patches)
                cum = patched
                if registry is not None:
                    registry.inc(MUTATE_EDITS, float(len(changes)))
            rr.processing_time = time.time() - rule_start
            resp.policy_response.rules.append(rr)
            resp.policy_response.rules_applied_count += 1
            if tally is not None:
                tally.device(prog)
        resp.patched_resource = cum
        self.engine._build_response(ctx, resp, start)
        return resp
