"""Mutate-batch encoder: resources → per-(resource, edit-site) lanes.

Projects each resource onto the lowered edit-site table exactly the way
``compiler/encode.py`` projects onto the validate slot table: the
document itself never reaches the device — only the lanes the kernel's
comparisons read.  Per (resource, site):

  tag      i8   type tag of the leaf value (compiler.ir TAG_*)
  istate   i8   path-intermediate state: 0 = every intermediate is a
                map (leaf parent reached), 1 = a missing/null
                intermediate (the merge creates the path), 2 = a
                non-map intermediate (host fallback)
  milli    i64  leaf numeric value ×1000 (bool/int/float), exact only
  milli_ok bool
  slen     i32  utf-8 byte length of a string leaf
  sbytes   u8[W] first bytes of a string leaf (W sized to the longest
                string patch constant in the program)

Plus one per-resource lane:

  valid    bool row is a live resource (False = canonical-capacity
                padding; the kernel masks padding rows so their edit
                bitmasks and statuses are identically empty)

The walk mirrors ``mutate_compile._apply_sets``' decision loop byte for
byte — non-map intermediates, null-as-creatable intermediates, and the
leaf-parent map check — so a device verdict can only ever differ from
the host fast path by being *more* conservative (FALLBACK).
"""

from __future__ import annotations

import math
from fractions import Fraction
from typing import Any, Dict, List, Tuple

import numpy as np

from ..compiler.ir import (TAG_ARRAY, TAG_BOOL, TAG_FLOAT, TAG_INT,
                           TAG_MAP, TAG_MISSING, TAG_NULL, TAG_STRING)
from .plan import EditSite, MutateSetProgram

_INT64_MAX = (1 << 63) - 1

#: cap on the string-constant byte window (and so on sbytes memory)
MAX_STR_WINDOW = 256

_MISSING = object()


def exact_milli(value: Any):
    """``value * 1000`` as an exact int, or None when the value leaves
    the exact milli window (the device then cannot decide equality)."""
    if isinstance(value, bool):
        return 1000 if value else 0
    if isinstance(value, int):
        return value * 1000 if abs(value) <= _INT64_MAX // 1000 else None
    if isinstance(value, float):
        if not math.isfinite(value):
            return None
        frac = Fraction(str(value)) * 1000
        if frac.denominator == 1 and abs(frac.numerator) <= _INT64_MAX:
            return int(frac)
        return None
    return None


def string_window(program: MutateSetProgram) -> int:
    """Byte width of the shared string-constant lane, 8-aligned."""
    longest = 1
    for prog in program.programs:
        for site in prog.sites:
            if isinstance(site.value, str) and \
                    not isinstance(site.value, bool):
                longest = max(longest, len(site.value.encode('utf-8')))
    return min(MAX_STR_WINDOW, (longest + 7) & ~7)


def _walk_site(doc: dict, path: Tuple[str, ...]):
    """(istate, leaf_value) for one site path — the `_apply_sets`
    decision walk: isinstance check before descent, ``None``
    intermediates creatable, leaf parent must be a map."""
    cur: Any = doc
    for part in path[:-1]:
        if not isinstance(cur, dict):
            return 2, _MISSING
        cur = cur.get(part)
        if cur is None:
            return 1, _MISSING
    if not isinstance(cur, dict):
        return 2, _MISSING
    leaf = path[-1]
    if leaf not in cur:
        return 0, _MISSING
    return 0, cur[leaf]


def _leaf_tag(value: Any) -> int:
    if value is _MISSING:
        return TAG_MISSING
    if value is None:
        return TAG_NULL
    if isinstance(value, bool):
        return TAG_BOOL
    if isinstance(value, int):
        return TAG_INT
    if isinstance(value, float):
        return TAG_FLOAT
    if isinstance(value, str):
        return TAG_STRING
    if isinstance(value, dict):
        return TAG_MAP
    if isinstance(value, list):
        return TAG_ARRAY
    return TAG_MISSING


def encode_mutate_batch(resources: List[dict],
                        program: MutateSetProgram,
                        padded_n: int = 0,
                        width: int = 0) -> Dict[str, np.ndarray]:
    """Lane tensors for ``resources`` over the program's edit sites.
    ``padded_n`` is a canonical capacity (``compiler/shapes.py``):
    padding rows encode as all-MISSING and carry ``valid=False``, so
    the kernel's edit bitmasks ignore them entirely."""
    sites: List[EditSite] = [s for prog in program.programs
                             for s in prog.sites]
    n = max(len(resources), padded_n)
    s = len(sites)
    w = width or string_window(program)
    lanes = {
        'tag': np.zeros((n, s), np.int8),
        'istate': np.zeros((n, s), np.int8),
        'milli': np.zeros((n, s), np.int64),
        'milli_ok': np.zeros((n, s), bool),
        'slen': np.zeros((n, s), np.int32),
        'sbytes': np.zeros((n, s, w), np.uint8),
        'valid': np.arange(n) < len(resources),
    }
    for r, doc in enumerate(resources):
        for k, site in enumerate(sites):
            istate, value = _walk_site(doc, site.path)
            lanes['istate'][r, k] = istate
            tag = _leaf_tag(value)
            lanes['tag'][r, k] = tag
            if tag in (TAG_BOOL, TAG_INT, TAG_FLOAT):
                m = exact_milli(value)
                if m is not None:
                    lanes['milli'][r, k] = m
                    lanes['milli_ok'][r, k] = True
            elif tag == TAG_STRING:
                b = value.encode('utf-8')
                lanes['slen'][r, k] = len(b)
                head = b[:w]
                if head:
                    lanes['sbytes'][r, k, :len(head)] = \
                        np.frombuffer(head, np.uint8)
    return lanes
