"""Deterministic fault-injection harness for the serving hot paths.

The ``KTPU_FAULTS`` spec arms named injection sites threaded through
the layers that carry admission traffic — encode, h2d, device_eval,
d2h, the AOT executable load, the verdict-cache snapshot read, the
batcher dispatch, and the webhook handler.  Each armed clause raises a
configured error class at its site so the degradation machinery
(poison-batch quarantine, breaker lifecycle, pipeline retries, host
fallback) is exercised by REAL exceptions on the REAL code paths, not
by test doubles.

Spec grammar (clauses separated by ``;``, fields by ``,``)::

    site=<name>[,p=<prob>][,nth=<call>][,marker=<label>]
        [,error=<class>][,seed=<int>][,exhaust=1][,delay_ms=<ms>]

* ``p``      — fire with probability ``p`` per check, drawn
  deterministically from ``seed`` and the site's call counter (the
  same spec always fires on the same calls, so chaos runs replay).
* ``nth``    — fire on exactly the Nth check of that site (1-based),
  once.  Multiple ``nth`` clauses schedule a bounded, fully
  deterministic burst of device errors.
* ``marker`` — row-targeted poison: fires when any row passed to
  :func:`check_rows` carries ``metadata.labels.chaos == <label>``.
  This is how the chaos schedule plants poison rows that fail
  *deterministically per row* (so quarantine bisection can isolate
  them) instead of per call.
* ``error``  — error class name (:data:`ERROR_CLASSES`); default
  ``RuntimeError``.  Injected errors carry ``ktpu_injected = True``.
* ``delay_ms`` — fire as a *stall* instead of an error: the check
  sleeps ``delay_ms`` milliseconds and returns.  This is how a chaos
  schedule plants a deterministic straggler (a slow shard/stage is a
  different failure mode than a dead one — the ``mesh_shard`` site
  uses it to inflate exactly one shard's device-eval wall so the
  fleet skew analyzer can be exercised end to end).
* ``exhaust`` — mark the injected error retry-exhausted
  (``ktpu_retry_exhausted = True``), the shape a pipeline stage
  reports after burning its ``KTPU_STAGE_RETRIES`` budget.  The
  quarantine treats such failures as *wholesale* (infrastructure)
  evidence rather than row-attributed poison, so this is how a chaos
  schedule trips the circuit breaker on purpose.

Contract: with ``KTPU_FAULTS`` unset (or after :func:`disable`) every
check is a no-op behind a single ``is None`` test — scan output is
bit-identical to a build without this module, and nothing is imported,
counted, or drawn.  Every fired fault counts on
``kyverno_tpu_faults_injected_total{site}``.
"""

from __future__ import annotations

import os
import random
import threading
from typing import Dict, List, Optional, Sequence

FAULTS_INJECTED = 'kyverno_tpu_faults_injected_total'

#: injection sites, in hot-path order
SITE_ENCODE = 'encode'
SITE_H2D = 'h2d'
SITE_DEVICE_EVAL = 'device_eval'
SITE_D2H = 'd2h'
SITE_AOT_LOAD = 'aot_load'
SITE_VERDICT_SNAPSHOT = 'verdict_snapshot_read'
SITE_BATCHER_DISPATCH = 'batcher_dispatch'
SITE_WEBHOOK_HANDLER = 'webhook_handler'
#: checked once per shard inside the mesh per-shard readback-timing
#: loop (parallel/mesh.py) — the Nth check is shard (N-1) % mesh_size
#: of step (N-1) // mesh_size, so an nth+delay_ms clause stalls one
#: specific shard of one specific step, deterministically
SITE_MESH_SHARD = 'mesh_shard'

SITES = (SITE_ENCODE, SITE_H2D, SITE_DEVICE_EVAL, SITE_D2H,
         SITE_AOT_LOAD, SITE_VERDICT_SNAPSHOT, SITE_BATCHER_DISPATCH,
         SITE_WEBHOOK_HANDLER, SITE_MESH_SHARD)

#: the label key :func:`check_rows` inspects for ``marker`` clauses
MARKER_LABEL = 'chaos'

#: legal ``error=`` classes — the shapes real backends fail with
ERROR_CLASSES = {
    'RuntimeError': RuntimeError,
    'ValueError': ValueError,
    'OSError': OSError,
    'TimeoutError': TimeoutError,
    'MemoryError': MemoryError,
    'ConnectionError': ConnectionError,
}


class FaultSpecError(ValueError):
    """KTPU_FAULTS could not be parsed (bad site / field / value)."""


class _Clause:
    __slots__ = ('site', 'p', 'nth', 'marker', 'error', 'seed',
                 'exhaust', 'delay_ms', 'fired')

    def __init__(self, site: str, p: Optional[float], nth: Optional[int],
                 marker: Optional[str], error: type, seed: int,
                 exhaust: bool = False,
                 delay_ms: Optional[float] = None):
        self.site = site
        self.p = p
        self.nth = nth
        self.marker = marker
        self.error = error
        self.seed = seed
        self.exhaust = exhaust
        self.delay_ms = delay_ms
        self.fired = 0


def parse(spec: str) -> List[_Clause]:
    """Parse a ``KTPU_FAULTS`` spec string into clauses (see module
    docstring for the grammar); raises :class:`FaultSpecError` so a
    typo'd spec fails loudly at arm time, never silently no-ops."""
    clauses: List[_Clause] = []
    for part in spec.split(';'):
        part = part.strip()
        if not part:
            continue
        fields: Dict[str, str] = {}
        for kv in part.split(','):
            kv = kv.strip()
            if '=' not in kv:
                raise FaultSpecError(
                    f'fault clause field {kv!r} is not key=value '
                    f'(clause {part!r})')
            k, _, v = kv.partition('=')
            fields[k.strip()] = v.strip()
        site = fields.pop('site', None)
        if site not in SITES:
            raise FaultSpecError(
                f'unknown fault site {site!r} (clause {part!r}); '
                f'sites: {", ".join(SITES)}')
        try:
            p = float(fields.pop('p')) if 'p' in fields else None
            nth = int(fields.pop('nth')) if 'nth' in fields else None
            seed = int(fields.pop('seed', '0'))
            exhaust = bool(int(fields.pop('exhaust', '0')))
            delay_ms = float(fields.pop('delay_ms')) \
                if 'delay_ms' in fields else None
        except ValueError as e:
            raise FaultSpecError(
                f'bad numeric field in fault clause {part!r}: {e}')
        marker = fields.pop('marker', None)
        err_name = fields.pop('error', 'RuntimeError')
        error = ERROR_CLASSES.get(err_name)
        if error is None:
            raise FaultSpecError(
                f'unknown error class {err_name!r} (clause {part!r}); '
                f'classes: {", ".join(sorted(ERROR_CLASSES))}')
        if fields:
            raise FaultSpecError(
                f'unknown fault clause fields {sorted(fields)} '
                f'(clause {part!r})')
        if p is None and nth is None and marker is None:
            raise FaultSpecError(
                f'fault clause {part!r} needs one of p=, nth=, marker=')
        if p is not None and not (0.0 <= p <= 1.0):
            raise FaultSpecError(f'p={p} outside [0, 1] in {part!r}')
        if delay_ms is not None and delay_ms < 0:
            raise FaultSpecError(f'delay_ms={delay_ms} negative in '
                                 f'{part!r}')
        clauses.append(_Clause(site, p, nth, marker, error, seed,
                               exhaust, delay_ms))
    return clauses


class Injector:
    """Armed fault clauses plus per-site call counters.

    Thread-safe; the draw for a ``p`` clause is a pure function of
    (seed, site call index), so a given spec fires on the same calls
    in every run regardless of thread interleaving of OTHER sites.
    """

    def __init__(self, clauses: Sequence[_Clause]):
        self._clauses = list(clauses)
        self._calls: Dict[str, int] = {}
        self._fired: Dict[str, int] = {}
        self._lock = threading.Lock()

    def _raise(self, clause: _Clause, detail: str):
        with self._lock:
            clause.fired += 1
            self._fired[clause.site] = self._fired.get(clause.site, 0) + 1
        registry = _registry()
        if registry is not None:
            registry.inc(FAULTS_INJECTED, site=clause.site)
        if clause.delay_ms is not None:
            # stall semantics: the injected failure is slowness, not an
            # error — the caller proceeds after the sleep
            import time
            time.sleep(clause.delay_ms / 1000.0)
            return
        err = clause.error(
            f'injected fault at {clause.site} ({detail})')
        err.ktpu_injected = True
        if clause.exhaust:
            err.ktpu_retry_exhausted = True
        raise err

    def check(self, site: str) -> None:
        """Raise if an armed call-indexed clause fires at ``site``."""
        with self._lock:
            n = self._calls.get(site, 0) + 1
            self._calls[site] = n
        for clause in self._clauses:
            if clause.site != site:
                continue
            if clause.nth is not None:
                if n == clause.nth:
                    self._raise(clause, f'nth={clause.nth}')
                continue
            if clause.p is not None:
                draw = random.Random((clause.seed << 32) ^ n).random()
                if draw < clause.p:
                    self._raise(clause, f'p={clause.p} call={n}')

    def check_rows(self, site: str, rows: Sequence[dict]) -> None:
        """:meth:`check`, then fire any ``marker`` clause whose label
        appears on a row — the row-deterministic poison path."""
        self.check(site)
        for clause in self._clauses:
            if clause.site != site or clause.marker is None:
                continue
            for row in rows:
                if not isinstance(row, dict):
                    continue
                labels = (row.get('metadata') or {}).get('labels') or {}
                if labels.get(MARKER_LABEL) == clause.marker:
                    self._raise(clause, f'marker={clause.marker}')

    def marked(self, rows: Sequence[dict]) -> int:
        """How many rows an armed marker clause would poison (test and
        bench bookkeeping, no side effects)."""
        markers = {c.marker for c in self._clauses if c.marker is not None}
        if not markers:
            return 0
        n = 0
        for row in rows:
            if isinstance(row, dict):
                labels = (row.get('metadata') or {}).get('labels') or {}
                if labels.get(MARKER_LABEL) in markers:
                    n += 1
        return n

    def counts(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._fired)


def _registry():
    from ..observability.metrics import global_registry
    return global_registry()


_injector: Optional[Injector] = None


def configure(spec: Optional[str]) -> Optional[Injector]:
    """Arm the process-wide injector from a spec string (None/'' →
    disarm).  Returns the installed injector so tests and the chaos
    bench can read its fire counts."""
    global _injector
    _injector = Injector(parse(spec)) if spec else None
    return _injector


def disable() -> None:
    global _injector
    _injector = None


def active() -> Optional[Injector]:
    return _injector


def check(site: str) -> None:
    """Hot-path hook: no-op behind one ``is None`` test when unarmed."""
    inj = _injector
    if inj is not None:
        inj.check(site)


def check_rows(site: str, rows: Sequence[dict]) -> None:
    """Hot-path hook for sites that see a batch of row documents."""
    inj = _injector
    if inj is not None:
        inj.check_rows(site, rows)


# arm from the environment once at import: the hot paths pay only the
# module-global None test afterwards (bit-identity when unset)
_env_spec = os.environ.get('KTPU_FAULTS', '')
if _env_spec.strip():
    configure(_env_spec)
