"""Anchor grammar and map-level anchor handlers for pattern validation.

Anchor forms (reference: pkg/engine/anchor/anchor.go:10-19):
  ``(key)``   condition        — if key exists, its pattern must match, else the
                                 whole rule is *skipped* for this resource
  ``<(key)``  global condition — like condition but a failure skips the rule
                                 from anywhere in the tree
  ``^(key)``  existence        — at least one element of the resource list must
                                 match the pattern
  ``=(key)``  equality         — if key exists it must match (no skip)
  ``X(key)``  negation         — key must NOT exist; presence fails the rule
  ``+(key)``  add-if-not-present (mutation overlays only)

The handlers mirror pkg/engine/anchor/handlers.go and the anchor bookkeeping
mirrors pkg/engine/anchor/anchormap.go.
"""

from __future__ import annotations

import re
from typing import Any, Callable, Optional, Tuple

CONDITION = ''
GLOBAL = '<'
NEGATION = 'X'
ADD_IF_NOT_PRESENT = '+'
EQUALITY = '='
EXISTENCE = '^'

_ANCHOR_RE = re.compile(r'^(?P<modifier>[+<=X^])?\((?P<key>.+)\)$')


class Anchor:
    __slots__ = ('modifier', 'key')

    def __init__(self, modifier: str, key: str):
        self.modifier = modifier
        self.key = key

    def __str__(self):
        return f'{self.modifier}({self.key})'


def parse(s: str) -> Optional[Anchor]:
    m = _ANCHOR_RE.match(s.strip())
    if not m:
        return None
    return Anchor(m.group('modifier') or '', m.group('key'))


def is_condition(a: Optional[Anchor]) -> bool:
    return a is not None and a.modifier == CONDITION


def is_global(a: Optional[Anchor]) -> bool:
    return a is not None and a.modifier == GLOBAL


def is_negation(a: Optional[Anchor]) -> bool:
    return a is not None and a.modifier == NEGATION


def is_equality(a: Optional[Anchor]) -> bool:
    return a is not None and a.modifier == EQUALITY


def is_existence(a: Optional[Anchor]) -> bool:
    return a is not None and a.modifier == EXISTENCE


def is_add_if_not_present(a: Optional[Anchor]) -> bool:
    return a is not None and a.modifier == ADD_IF_NOT_PRESENT


def contains_condition(a: Optional[Anchor]) -> bool:
    return a is not None and a.modifier in (CONDITION, GLOBAL)


def remove_anchor(key: str) -> Tuple[str, str]:
    """Return (bare key, modifier) for a possibly-anchored key."""
    a = parse(key)
    if a is None:
        return key, ''
    return a.key, a.modifier


# ---------------------------------------------------------------------------
# Errors used to steer the validate walk (skip vs fail semantics,
# reference: pkg/engine/validate/validate.go:58-66)

class ValidateError(Exception):
    """Plain validation failure."""

    def __init__(self, msg: str, path: str = ''):
        super().__init__(msg)
        self.path = path


class ConditionalAnchorError(ValidateError):
    """Condition anchor did not apply → rule is skipped."""


class GlobalAnchorError(ValidateError):
    """Global anchor did not apply → rule is skipped."""


class NegationAnchorError(ValidateError):
    """Negation anchor matched → rule fails."""


def is_skip_error(e: Exception) -> bool:
    return isinstance(e, (ConditionalAnchorError, GlobalAnchorError))


def is_fail_error(e: Exception) -> bool:
    return isinstance(e, NegationAnchorError)


class AnchorMap:
    """Tracks whether condition/existence/negation anchor keys appear in the
    resource (reference: pkg/engine/anchor/anchormap.go)."""

    def __init__(self):
        self.anchor_map: dict[str, bool] = {}
        self.anchor_error: Optional[ValidateError] = None

    def keys_are_missing(self) -> bool:
        return any(not v for v in self.anchor_map.values())

    def check_anchor_in_resource(self, pattern: dict, resource: Any):
        for key in pattern:
            a = parse(key)
            if is_condition(a) or is_existence(a) or is_negation(a):
                if self.anchor_map.get(key):
                    continue
                self.anchor_map.setdefault(key, False)
                if isinstance(resource, dict) and resource.get(a.key) is not None:
                    self.anchor_map[key] = True


def get_anchors_resources_from_map(pattern_map: dict) -> Tuple[dict, dict]:
    """Split a pattern map into {anchored keys} and {plain keys}.
    Condition/existence/equality/negation are 'anchors' for phase 1; global
    (and add-if-not-present) anchors are processed with the plain keys in
    phase 2, where globals are pushed to the front
    (reference: pkg/engine/anchor/utils.go:9 GetAnchorsResourcesFromMap)."""
    anchors, resources = {}, {}
    for key, value in pattern_map.items():
        a = parse(key)
        if is_condition(a) or is_existence(a) or is_equality(a) or is_negation(a):
            anchors[key] = value
        else:
            resources[key] = value
    return anchors, resources


# Handler type: fn(resource_element, pattern_element, origin_pattern, path, ac)
# raising ValidateError subclasses on mismatch.
ElementHandler = Callable[[Any, Any, Any, str, AnchorMap], None]


def handle_element(element_key: str, pattern: Any, path: str,
                   handler: ElementHandler, resource_map: dict,
                   origin_pattern: Any, ac: AnchorMap) -> None:
    """Dispatch one pattern-map entry against the resource map, applying the
    anchor semantics for its key (reference: pkg/engine/anchor/handlers.go:31)."""
    a = parse(element_key)
    if is_condition(a):
        current_path = path + a.key + '/'
        if a.key in resource_map:
            try:
                handler(resource_map[a.key], pattern, origin_pattern, current_path, ac)
            except ValidateError as e:
                err = ConditionalAnchorError(str(e), getattr(e, 'path', current_path))
                ac.anchor_error = err
                raise err from e
        else:
            raise ConditionalAnchorError(
                "conditional anchor key doesn't exist in the resource", current_path)
        return
    if is_global(a):
        current_path = path + a.key + '/'
        if a.key in resource_map:
            try:
                handler(resource_map[a.key], pattern, origin_pattern, current_path, ac)
            except ValidateError as e:
                err = GlobalAnchorError(str(e), getattr(e, 'path', current_path))
                ac.anchor_error = err
                raise err from e
        return
    if is_existence(a):
        _handle_existence(a, pattern, path, handler, resource_map, origin_pattern, ac)
        return
    if is_equality(a):
        current_path = path + a.key + '/'
        if a.key in resource_map:
            handler(resource_map[a.key], pattern, origin_pattern, current_path, ac)
        return
    if is_negation(a):
        current_path = path + a.key + '/'
        if a.key in resource_map:
            err = NegationAnchorError(f'{current_path} is not allowed', current_path)
            ac.anchor_error = err
            raise err
        return
    if is_add_if_not_present(a):
        return  # mutation-only anchor: no-op during validation
    # default (non-anchored) key
    current_path = path + element_key + '/'
    value = resource_map.get(element_key)
    if pattern == '*' and value is not None:
        return
    if pattern == '*' and value is None:
        raise ValidateError(f'{path}/{element_key} not found', path)
    handler(value, pattern, origin_pattern, current_path, ac)


def _handle_existence(a: Anchor, pattern: Any, path: str,
                      handler: ElementHandler, resource_map: dict,
                      origin_pattern: Any, ac: AnchorMap) -> None:
    # reference: pkg/engine/anchor/handlers.go:228
    current_path = path + a.key + '/'
    if a.key not in resource_map:
        return
    value = resource_map[a.key]
    if not isinstance(value, list):
        raise ValidateError(
            f'invalid resource type {type(value).__name__}: existence anchor '
            f'can only be used on list/array type resource', current_path)
    if not isinstance(pattern, list):
        raise ValidateError(
            'invalid pattern type: existence anchor pattern must be a list',
            current_path)
    for pattern_map in pattern:
        if not isinstance(pattern_map, dict):
            raise ValidateError(
                'invalid pattern type: existence anchor pattern elements must '
                'be maps', current_path)
        # at least one element of the resource list must satisfy the pattern
        satisfied = False
        for i, elem in enumerate(value):
            try:
                handler(elem, pattern_map, origin_pattern,
                        current_path + str(i) + '/', ac)
                satisfied = True
                break
            except ValidateError:
                continue
        if not satisfied:
            raise ValidateError(
                f'existence anchor validation failed at path {current_path}',
                current_path)
