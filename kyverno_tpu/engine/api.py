"""Engine API types: PolicyContext, EngineResponse, RuleResponse, RuleStatus.

Mirrors the reference engine API (reference: pkg/engine/api/policycontext.go:24,
engineresponse.go:13, ruleresponse.go:23, rulestatus.go).
"""

from __future__ import annotations

import copy
import time
from typing import Any, Dict, List, Optional

from ..api.policy import Policy, Rule
from ..api.unstructured import Resource
from .context import Context


class RuleStatus:
    PASS = 'pass'
    FAIL = 'fail'
    SKIP = 'skip'
    ERROR = 'error'
    WARN = 'warn'


class RuleType:
    VALIDATION = 'Validation'
    MUTATION = 'Mutation'
    GENERATION = 'Generation'
    IMAGE_VERIFY = 'ImageVerify'


class RuleResponse:
    def __init__(self, name: str, rule_type: str, message: str, status: str,
                 patches: Optional[List[dict]] = None,
                 generated_resource: Optional[dict] = None,
                 patched_target: Optional[dict] = None,
                 pod_security_checks: Optional[dict] = None):
        self.name = name
        self.rule_type = rule_type
        self.message = message
        self.status = status
        self.patches = patches or []
        self.generated_resource = generated_resource
        self.patched_target = patched_target
        self.pod_security_checks = pod_security_checks
        self.processing_time: float = 0.0
        self.timestamp: int = 0

    def __repr__(self):
        return (f'RuleResponse(name={self.name!r}, status={self.status!r}, '
                f'message={self.message!r})')

    def to_dict(self) -> dict:
        out = {
            'name': self.name,
            'ruleType': self.rule_type,
            'message': self.message,
            'status': self.status,
        }
        if self.patches:
            out['patches'] = self.patches
        if self.generated_resource:
            out['generatedResource'] = self.generated_resource
        if self.pod_security_checks:
            out['podSecurityChecks'] = self.pod_security_checks
        return out


class PolicyResponse:
    def __init__(self):
        self.rules: List[RuleResponse] = []
        self.rules_applied_count = 0
        self.rules_error_count = 0
        self.processing_time: float = 0.0
        self.timestamp: int = 0
        self.validation_failure_action = 'Audit'
        self.validation_failure_action_overrides: List[dict] = []
        self.policy_name = ''
        self.policy_namespace = ''
        self.resource_name = ''
        self.resource_namespace = ''
        self.resource_kind = ''
        self.resource_api_version = ''


class EngineResponse:
    def __init__(self, policy: Optional[Policy] = None,
                 patched_resource: Optional[dict] = None):
        self.policy = policy
        self.patched_resource = patched_resource
        self.policy_response = PolicyResponse()
        self.namespace_labels: Dict[str, str] = {}

    def is_successful(self) -> bool:
        return not any(r.status in (RuleStatus.FAIL, RuleStatus.ERROR)
                       for r in self.policy_response.rules)

    def is_failed(self) -> bool:
        return any(r.status == RuleStatus.FAIL
                   for r in self.policy_response.rules)

    def is_error(self) -> bool:
        return any(r.status == RuleStatus.ERROR
                   for r in self.policy_response.rules)

    def is_empty(self) -> bool:
        return len(self.policy_response.rules) == 0

    def get_failed_rules(self) -> List[str]:
        return [r.name for r in self.policy_response.rules
                if r.status in (RuleStatus.FAIL, RuleStatus.ERROR)]

    def get_successful_rules(self) -> List[str]:
        return [r.name for r in self.policy_response.rules
                if r.status == RuleStatus.PASS]

    def get_validation_failure_action(self) -> str:
        """Resolve enforce/audit with namespace overrides
        (reference: pkg/engine/api/engineresponse.go:107)."""
        from ..utils import wildcard
        from .match import check_selector
        for override in self.policy_response.validation_failure_action_overrides:
            action = override.get('action', '')
            if action.lower() not in ('enforce', 'audit'):
                continue
            ns_selector = override.get('namespaceSelector')
            if ns_selector is not None:
                try:
                    if not check_selector(ns_selector, self.namespace_labels):
                        continue
                except Exception:
                    continue
                if not override.get('namespaces'):
                    return action
            for ns in override.get('namespaces') or []:
                if wildcard.match(ns, self.policy_response.resource_namespace):
                    return action
        return self.policy_response.validation_failure_action


class PolicyContext:
    """Everything the engine needs for one (policy, resource) evaluation
    (reference: pkg/engine/policyContext.go)."""

    def __init__(self, policy: Policy,
                 new_resource: Optional[dict] = None,
                 old_resource: Optional[dict] = None,
                 admission_info: Optional[dict] = None,
                 namespace_labels: Optional[Dict[str, str]] = None,
                 exclude_group_roles: Optional[List[str]] = None,
                 json_context: Optional[Context] = None,
                 exceptions: Optional[List[dict]] = None,
                 admission_operation: str = '',
                 subresource: str = '',
                 element: Optional[dict] = None,
                 subresources_in_policy: Optional[List[dict]] = None):
        self.policy = policy
        self.new_resource = new_resource or {}
        self.old_resource = old_resource or {}
        self.admission_info = admission_info or {}
        self.namespace_labels = namespace_labels or {}
        self.exclude_group_roles = exclude_group_roles or []
        self.exceptions = exceptions or []
        self.admission_operation = admission_operation
        self.subresource = subresource
        self.element = element
        # CLI-only: subresource declarations from the values file
        # (reference: pkg/engine/policyContext.go WithSubresourcesInPolicy)
        self.subresources_in_policy = subresources_in_policy or []
        if json_context is None:
            json_context = Context()
            if self.new_resource:
                json_context.add_resource(self.new_resource)
            if self.old_resource:
                json_context.add_old_resource(self.old_resource)
            if admission_operation:
                json_context.add_operation(admission_operation)
        self.json_context = json_context

    def copy(self) -> 'PolicyContext':
        c = PolicyContext.__new__(PolicyContext)
        c.policy = self.policy
        c.new_resource = self.new_resource
        c.old_resource = self.old_resource
        c.admission_info = self.admission_info
        c.namespace_labels = self.namespace_labels
        c.exclude_group_roles = self.exclude_group_roles
        c.exceptions = self.exceptions
        c.admission_operation = self.admission_operation
        c.subresource = self.subresource
        c.element = self.element
        c.subresources_in_policy = self.subresources_in_policy
        c.json_context = self.json_context
        return c

    def set_element(self, element: dict) -> None:
        self.element = element

    def new_resource_obj(self) -> Resource:
        return Resource(self.new_resource)

    def old_resource_obj(self) -> Resource:
        return Resource(self.old_resource)

    def find_exceptions(self, rule_name: str) -> List[dict]:
        """Return PolicyException candidates for (policy, rule)
        (reference: pkg/engine/policyContext.go FindExceptions)."""
        out = []
        policy_key = self.policy.get_kind_and_name()
        for ex in self.exceptions:
            for match_ex in (ex.get('spec') or {}).get('exceptions') or []:
                if match_ex.get('policyName') == policy_key and \
                        rule_name in (match_ex.get('ruleNames') or []):
                    out.append(ex)
                    break
        return out
