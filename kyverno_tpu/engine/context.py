"""Per-request JSON variable context with a checkpoint stack.

Re-implements the reference's engine context
(reference: pkg/engine/context/context.go, evaluate.go):

* a single JSON document built by RFC-7386 merge-patch semantics (null
  deletes, objects merge recursively, everything else replaces)
* well-known paths: request.object / request.oldObject / request.operation /
  request.userInfo / request.namespace, images, element / elementIndex
  (with `elementN` nesting for nested foreach)
* Checkpoint / Restore / Reset stack used for rule and foreach isolation
* Query() evaluates a JMESPath expression over the document
"""

from __future__ import annotations

import copy
import json
from typing import Any, Dict, List, Optional

from . import jmespath as jp


class ContextError(Exception):
    pass


class InvalidVariableError(ContextError):
    pass


class VariableNotFoundError(ContextError):
    """Query resolved to a missing field (maps the fork's NotFoundError)."""


def merge_patch(target: Any, patch: Any) -> Any:
    """RFC 7386 JSON merge patch (reference merges via
    jsonpatch.MergeMergePatches, pkg/engine/context/context.go:123).

    Non-dict patch values are shared by reference, not copied: the engine
    treats context documents as immutable (queries only read; substitution
    builds new objects), which also makes checkpoints O(1)."""
    if not isinstance(patch, dict):
        return patch
    if not isinstance(target, dict):
        target = {}
    else:
        target = dict(target)
    for k, v in patch.items():
        if v is None:
            target.pop(k, None)
        elif k not in target:
            # absent key: share the patch subtree by reference — context
            # documents are immutable (see above), and every writer
            # copies the target spine, so sharing is never observable.
            # RFC 7386 still requires nested nulls to be STRIPPED, so
            # dicts only short-circuit when verifiably null-free.
            if isinstance(v, dict) and not _null_free(v):
                target[k] = merge_patch(None, v)
            else:
                target[k] = v
        else:
            target[k] = merge_patch(target[k], v)
    return target


#: null-free memo, id-pinned: the same resource dict is merged into many
#: contexts (one per policy/element), so the scan amortizes
_NULL_FREE: dict = {}


def _null_free(node: Any) -> bool:
    if isinstance(node, dict):
        key = id(node)
        hit = _NULL_FREE.get(key)
        if hit is not None and hit[0] is node:
            return hit[1]
        ok = all(v is not None and _null_free(v) for v in node.values())
        if len(_NULL_FREE) > 16384:
            _NULL_FREE.clear()
        _NULL_FREE[key] = (node, ok)
        return ok
    if isinstance(node, list):
        return all(_null_free(v) for v in node)
    return True


class Context:
    """The engine's per-request variable store."""

    def __init__(self, data: Optional[dict] = None):
        self._data: dict = data if data is not None else {}
        self._checkpoints: List[dict] = []

    # -- raw document --------------------------------------------------------

    @property
    def data(self) -> dict:
        return self._data

    def add_json(self, patch: Any) -> None:
        self._data = merge_patch(self._data, patch)

    # -- well-known paths ----------------------------------------------------

    def add_request(self, request: dict) -> None:
        self.add_json({'request': request})

    def add_resource(self, resource: dict) -> None:
        self.add_json({'request': {'object': resource}})

    def add_old_resource(self, resource: dict) -> None:
        self.add_json({'request': {'oldObject': resource}})

    def add_target_resource(self, resource: dict) -> None:
        self.add_json({'target': resource})

    def add_operation(self, op: str) -> None:
        self.add_json({'request': {'operation': op}})

    def add_user_info(self, user_info: dict) -> None:
        self.add_json({'request': user_info})

    def add_namespace(self, namespace: str) -> None:
        self.add_json({'request': {'namespace': namespace}})

    def add_variable(self, key: str, value: Any) -> None:
        patch: Any = value
        for part in reversed(key.split('.')):
            patch = {part: patch}
        self.add_json(patch)

    def add_context_entry(self, name: str, value: Any) -> None:
        self.add_json({name: value})

    def replace_context_entry(self, name: str, value: Any) -> None:
        self.add_json({name: None})
        self.add_json({name: value})

    def add_element(self, data: Any, index: int, nesting: int = 0) -> None:
        # reference: pkg/engine/context/context.go:244 AddElement
        self.add_json({
            'element': data,
            f'element{nesting}': data,
            'elementIndex': index,
            f'elementIndex{nesting}': index,
        })

    def add_service_account(self, username: str) -> None:
        # reference: pkg/engine/context/context.go:193 AddServiceAccount
        sa_prefix = 'system:serviceaccount:'
        sa = username[len(sa_prefix):] if len(username) > len(sa_prefix) else ''
        name, namespace = '', ''
        groups = sa.split(':')
        if len(groups) >= 2:
            namespace, name = groups[0], groups[1]
        self.add_json({'serviceAccountName': name})
        self.add_json({'serviceAccountNamespace': namespace})

    def add_image_infos(self, images: dict) -> None:
        self.add_json({'images': images})

    # -- checkpoint stack ----------------------------------------------------
    # O(1) snapshots: every mutation goes through add_json → merge_patch,
    # which is copy-on-write (builds new dicts along patched paths, never
    # mutates in place), so a checkpoint is just a reference
    # (the reference deep-copies raw bytes instead,
    # pkg/engine/context/context.go:301)

    def checkpoint(self) -> None:
        self._checkpoints.append(self._data)

    def restore(self) -> None:
        if self._checkpoints:
            self._data = self._checkpoints.pop()

    def reset(self) -> None:
        if self._checkpoints:
            self._data = self._checkpoints[-1]

    # -- querying ------------------------------------------------------------

    def query(self, query: str) -> Any:
        query = query.strip()
        if not query:
            raise InvalidVariableError('invalid query (nil)')
        try:
            compiled = jp.compile(query)
        except jp.JMESPathError as e:
            raise InvalidVariableError(f'incorrect query {query}: {e}') from e
        try:
            return compiled.search(self._data)
        except jp.NotFoundError as e:
            raise VariableNotFoundError(str(e)) from e
        except jp.JMESPathError as e:
            raise ContextError(f'JMESPath query failed: {e}') from e

    def has_changed(self, expr: str) -> bool:
        obj = self.query('request.object.' + expr)
        if obj is None:
            raise ContextError(f'request.object.{expr} not found')
        old = self.query('request.oldObject.' + expr)
        if old is None:
            raise ContextError(f'request.oldObject.{expr} not found')
        return obj != old


class MockContext(Context):
    """Context that only allows an allow-listed set of query roots, for the
    CLI / tests (reference: pkg/engine/context/mock_context.go)."""

    def __init__(self, allowed: List[str], data: Optional[dict] = None):
        super().__init__(data)
        self._allowed = list(allowed)

    def query(self, query: str) -> Any:
        from ..utils import wildcard
        q = query.strip()
        if not any(wildcard.match(pat, q) or q.startswith(pat.rstrip('*').rstrip('.'))
                   for pat in self._allowed):
            raise InvalidVariableError(f'variable {q} not allowed')
        return super().query(query)
