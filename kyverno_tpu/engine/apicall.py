"""APICall / ServiceCall context-entry execution.

Mirrors the reference's apicall package (reference:
pkg/engine/apicall/apiCall.go:31-160): ``urlPath`` entries GET the K8s
API server through the dynamic client's raw path; ``service`` entries
issue GET/POST HTTP requests (bearer token from the projected service
account token, optional CA bundle), and results are JMESPath-transformed
before landing in the JSON context.

Transports are injectable so policies relying on API calls stay
hermetically testable; the defaults use urllib against live endpoints.
"""

from __future__ import annotations

import json
import ssl
import tempfile
from typing import Any, Callable, Optional

from . import variables as vars_mod
from .context import Context, ContextError
from .jmespath import compile as jp_compile

TOKEN_PATH = '/var/run/secrets/tokens/api-token'


def default_http_transport(method: str, url: str, headers: dict,
                           body: Optional[bytes],
                           ca_bundle: str = '') -> bytes:
    """reference: apiCall.go:83-126 executeServiceCall"""
    import urllib.request
    req = urllib.request.Request(url, data=body, method=method)
    for k, v in headers.items():
        req.add_header(k, v)
    ctx = None
    if ca_bundle:
        ctx = ssl.create_default_context()
        with tempfile.NamedTemporaryFile('w', suffix='.pem') as f:
            f.write(ca_bundle)
            f.flush()
            ctx.load_verify_locations(f.name)
    with urllib.request.urlopen(req, context=ctx, timeout=30) as resp:
        if not (200 <= resp.status < 300):
            raise ContextError(f'HTTP {resp.status}: {resp.reason}')
        return resp.read()


def default_token_reader() -> str:
    try:
        with open(TOKEN_PATH) as f:
            return f.read().strip()
    except OSError:
        return ''


class APICallExecutor:
    """Executes one ``apiCall`` context entry
    (reference: apiCall.go:45 Execute)."""

    def __init__(self, raw_abs_path: Optional[Callable[[str], bytes]] = None,
                 http_transport: Callable = default_http_transport,
                 token_reader: Callable[[], str] = default_token_reader):
        self.raw_abs_path = raw_abs_path
        self.http_transport = http_transport
        self.token_reader = token_reader

    def __call__(self, entry: dict, ctx: Context) -> Any:
        name = entry.get('name', '')
        call = vars_mod.substitute_all(ctx, entry.get('apiCall') or {})
        data = self._execute(name, call)
        return self._transform(name, call, ctx, data)

    def _execute(self, name: str, call: dict) -> bytes:
        url_path = call.get('urlPath', '')
        if url_path:
            # reference: apiCall.go:72 executeK8sAPICall (RawAbsPath)
            if self.raw_abs_path is None:
                raise ContextError(
                    f'failed to load context entry {name}: no cluster '
                    f'client for urlPath {url_path}')
            try:
                return self.raw_abs_path(url_path)
            except Exception as e:  # noqa: BLE001
                raise ContextError(
                    f'failed to get resource with raw url\n: {url_path}: '
                    f'{e}')
        service = call.get('service')
        if not service:
            raise ContextError(f'missing service for APICall {name}')
        method = service.get('method', 'GET') or 'GET'
        headers = {}
        token = self.token_reader()
        if token:
            headers['Authorization'] = f'Bearer {token}'
        body = None
        if method == 'POST':
            data_map = {d.get('key'): d.get('value')
                        for d in call.get('data') or []}
            body = json.dumps(data_map).encode('utf-8')
            headers['Content-Type'] = 'application/json'
        elif method != 'GET':
            raise ContextError(
                f'invalid request type {method} for APICall {name}')
        try:
            return self.http_transport(method, service.get('url', ''),
                                       headers, body,
                                       service.get('caBundle', ''))
        except ContextError:
            raise
        except Exception as e:  # noqa: BLE001
            raise ContextError(
                f'failed to execute HTTP request for APICall {name}: {e}')

    def _transform(self, name: str, call: dict, ctx: Context,
                   data: bytes) -> Any:
        """reference: apiCall.go:186 transformAndStore"""
        try:
            parsed = json.loads(data)
        except ValueError as e:
            raise ContextError(
                f'failed to parse JSON response for APICall {name}: {e}')
        # the whole apiCall dict was already variable-substituted in
        # __call__; the path is final here
        path = call.get('jmesPath', '')
        if not path:
            return parsed
        try:
            result = jp_compile(str(path)).search(parsed)
        except Exception as e:  # noqa: BLE001
            raise ContextError(
                f'failed to apply JMESPath {path} for APICall {name}: {e}')
        return result


def make_context_loader(dclient=None, registry_client=None,
                        http_transport: Callable = default_http_transport,
                        token_reader: Callable[[], str] =
                        default_token_reader,
                        cm_resolver: Optional[Callable] = None):
    """Build a fully-wired engine ContextLoader: ConfigMap resolution via
    the dynamic client, APICall/ServiceCall via the HTTP transport,
    imageRegistry via the registry client
    (reference: pkg/engine/jsonContext.go:23 ContextLoaderFactory)."""
    from .engine import ContextLoader
    raw = None
    if dclient is not None and hasattr(dclient, 'raw_abs_path'):
        raw = dclient.raw_abs_path
    api_call = APICallExecutor(raw_abs_path=raw,
                               http_transport=http_transport,
                               token_reader=token_reader)
    if cm_resolver is None and dclient is not None:
        def cm_resolver(name, namespace):  # noqa: F811
            return dclient.get_resource('v1', 'ConfigMap', namespace, name)
    image_data = None
    if registry_client is not None:
        def image_data(entry, ctx):  # noqa: F811
            return fetch_image_data(entry, ctx, registry_client)
    return ContextLoader(configmap_resolver=cm_resolver,
                         api_call=api_call,
                         image_data=image_data)


def fetch_image_data(entry: dict, ctx: Context, rclient) -> Any:
    """``imageRegistry`` context entries: fetch image metadata from the
    registry client (reference: pkg/engine/jsonContext.go:189-283
    fetchImageData / fetchImageDataMap)."""
    from ..utils.image import get_image_info
    spec = entry.get('imageRegistry') or {}
    ref = vars_mod.substitute_all(ctx, spec.get('reference', ''))
    if not isinstance(ref, str):
        raise ContextError(
            f'invalid image reference {ref}, image reference must be '
            f'a string')
    path = vars_mod.substitute_all(ctx, spec.get('jmesPath', '') or '')
    try:
        desc = rclient.fetch_image_descriptor(ref)
    except Exception as e:  # noqa: BLE001 - registry failure → rule error
        raise ContextError(
            f'failed to fetch image descriptor for {ref}: {e}')
    try:
        info = get_image_info(ref)
    except ValueError as e:
        raise ContextError(str(e))
    manifest = {}
    config_data = {}
    try:
        if hasattr(rclient, 'get_manifest'):
            manifest = rclient.get_manifest(ref)
        if hasattr(rclient, 'get_config'):
            config_data = rclient.get_config(ref)
    except Exception as e:  # noqa: BLE001
        raise ContextError(
            f'failed to fetch image metadata for {ref}: {e}')
    repo_name = f'{info.registry}/{info.path}' if info.registry \
        else info.path
    data = {
        'image': ref,
        'resolvedImage': f'{repo_name}@{desc.digest}',
        'registry': info.registry,
        'repository': info.path,
        'identifier': info.digest or info.tag,
        'manifest': manifest,
        'configData': config_data,
    }
    if path:
        try:
            return jp_compile(str(path)).search(data)
        except Exception as e:  # noqa: BLE001
            raise ContextError(
                f'failed to apply JMESPath ({path}) results to context '
                f'entry {entry.get("name", "")}, error: {e}')
    return data
