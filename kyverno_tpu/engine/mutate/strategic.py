"""Strategic merge patch with Kyverno anchor preprocessing.

Two stages, mirroring the reference:

1. **Preprocessing** (reference: pkg/engine/mutate/patch/strategicPreprocessing.go)
   resolves mutate-overlay anchors against the resource: conditional anchors
   ``(key)``/``<(key)`` gate whether (parts of) the patch apply,
   ``+(key)`` adds only when absent, and anchored list-of-map elements are
   expanded per matching resource element (carrying the resource's ``name``
   so associative merge can target it).

2. **Merge** (reference: pkg/engine/mutate/patch/strategicMergePatch.go via
   kustomize kyaml merge2): maps merge recursively, ``null`` deletes,
   ``$patch: delete|replace`` directives honored, and lists of maps merge
   associatively when their elements share one of kyaml's associative keys
   (mountPath, devicePath, ip, type, topologyKey, name, containerPort);
   other lists are replaced.
"""

from __future__ import annotations

from typing import Any, List, Optional, Tuple

from .. import anchor
from ..validate_pattern import PatternError, match_pattern


class ConditionError(Exception):
    """A conditional anchor did not match → skip this part/rule."""


class GlobalConditionError(Exception):
    """A global anchor did not match → skip the whole rule."""


# kyaml's associative sequence keys (kustomize kyaml/yaml/types.go)
ASSOCIATIVE_KEYS = ('mountPath', 'devicePath', 'ip', 'type', 'topologyKey',
                    'name', 'containerPort')


def apply_strategic_merge_patch(base: Any, overlay: Any) -> Any:
    """Preprocess the overlay against base, then merge. Returns the patched
    document; on a failed condition returns base unchanged.  Neither
    input is mutated: preprocessing rebuilds containers as it walks and
    the merge is copy-on-write, so the output may structurally SHARE
    unpatched subtrees with both inputs (the ``substitute_all`` aliasing
    contract — treat outputs read-only or copy before mutating)."""
    try:
        overlay = preprocess_pattern(overlay, base)
    except (ConditionError, GlobalConditionError):
        return base
    return strategic_merge(base, overlay)


# ---------------------------------------------------------------------------
# Stage 1: preprocessing
#
# The whole walk is NON-MUTATING toward ``pattern``: every map level is
# rebuilt before being written to (`_handle_add_if_not_present` /
# `_delete_anchors_in_map` return fresh dicts; `_validate_conditions`
# only ever writes into the fresh dict `_walk_map` just made), so
# callers apply rule-constant overlays per resource WITHOUT a deepcopy
# — the per-(resource, element) deepcopy used to dominate bulk mutate
# profiles the same way `calculate_resource_hash`'s did (PR 6).
# tests/test_mutate.py pins both the no-mutation property and output
# identity against a deepcopy-based reference.

def preprocess_pattern(pattern: Any, resource: Any) -> Any:
    pattern = _preprocess_recursive(pattern, resource)
    return _delete_condition_elements(pattern)


def _preprocess_recursive(pattern: Any, resource: Any) -> Any:
    if isinstance(pattern, dict):
        return _walk_map(pattern, resource)
    if isinstance(pattern, list):
        return _walk_list(pattern, resource)
    return pattern


def _walk_map(pattern: dict, resource: Any) -> dict:
    pattern = _handle_add_if_not_present(pattern, resource)
    _validate_conditions(pattern, resource)
    out = {}
    for key, value in pattern.items():
        a = anchor.parse(key)
        if a is not None and (anchor.contains_condition(a) or
                              anchor.is_add_if_not_present(a)):
            out[key] = value
            continue
        resource_value = None
        if isinstance(resource, dict):
            resource_value = resource.get(a.key if a else key)
        out[key] = _preprocess_recursive(value, resource_value)
    return out


def _walk_list(pattern: list, resource: Any) -> list:
    if not pattern:
        return pattern
    if isinstance(pattern[0], dict):
        return _process_list_of_maps(pattern, resource)
    return pattern


def _process_list_of_maps(pattern: list, resource: Any) -> list:
    # reference: strategicPreprocessing.go:119 processListOfMaps
    resource_elements = resource if isinstance(resource, list) else []
    out = list(pattern)
    for pattern_element in pattern:
        has_any_anchor = _has_anchors(pattern_element)
        has_global = _has_anchors(pattern_element, global_only=True)
        if not has_any_anchor:
            continue
        any_global_passed = False
        last_global_error: Optional[GlobalConditionError] = None
        # the recursive walk never mutates its pattern argument (module
        # note above), so one shared pattern_element serves every
        # resource element — no per-(resource, element) deepcopy
        for resource_element in resource_elements:
            try:
                processed = _preprocess_recursive(pattern_element,
                                                  resource_element)
            except ConditionError:
                continue
            except GlobalConditionError as e:
                last_global_error = e
                continue
            if has_global:
                any_global_passed = True
            else:
                new_elem = _pattern_with_name(processed, resource_element)
                if new_elem is not None:
                    out.append(new_elem)
        if not resource_elements:
            try:
                _preprocess_recursive(pattern_element, None)
                if has_global:
                    any_global_passed = True
            except ConditionError:
                continue
            except GlobalConditionError as e:
                last_global_error = e
        if not any_global_passed and last_global_error is not None:
            raise last_global_error
    return out


def _pattern_with_name(pattern_element: dict, resource_element: Any) -> Optional[dict]:
    # reference: strategicPreprocessing.go:186 handlePatternName
    if not isinstance(resource_element, dict):
        return None
    name = resource_element.get('name')
    if not name:
        return None
    new_node, empty = _delete_anchors(pattern_element,
                                      delete_scalar=True,
                                      traverse_mapping=False)
    if empty or not isinstance(new_node, dict):
        return None
    new_node['name'] = name
    return new_node


def _validate_conditions(pattern: dict, resource: Any) -> None:
    # reference: strategicPreprocessing.go:236 validateConditions
    for filter_fn, err_cls in ((anchor.is_global, GlobalConditionError),
                               (anchor.is_condition, ConditionError)):
        for key in list(pattern.keys()):
            a = anchor.parse(key)
            if a is None or not filter_fn(a):
                continue
            if not isinstance(resource, dict) or a.key not in resource:
                raise err_cls(
                    f'could not found "{a.key}" key in the resource')
            pattern_value = pattern[key]
            resource_value = resource[a.key]
            if isinstance(pattern_value, dict):
                processed = _handle_add_if_not_present(pattern_value,
                                                       resource_value)
                if processed != pattern_value:
                    pattern[key] = processed
                    continue
                had_add = any(anchor.is_add_if_not_present(anchor.parse(k))
                              for k in pattern_value)
                if had_add:
                    pattern[key] = processed
                    continue
            try:
                match_pattern(resource_value, _strip_all_anchors(pattern_value))
            except PatternError as e:
                raise err_cls(str(e)) from e


def _strip_all_anchors(pattern: Any) -> Any:
    if isinstance(pattern, dict):
        out = {}
        for k, v in pattern.items():
            a = anchor.parse(k)
            key = a.key if a is not None and anchor.contains_condition(a) else k
            out[key] = _strip_all_anchors(v)
        return out
    if isinstance(pattern, list):
        return [_strip_all_anchors(v) for v in pattern]
    return pattern


def _handle_add_if_not_present(pattern: dict, resource: Any) -> dict:
    # reference: strategicPreprocessing.go:253 handleAddIfNotPresentAnchor
    out = {}
    for key, value in pattern.items():
        a = anchor.parse(key)
        if a is not None and anchor.is_add_if_not_present(a):
            if isinstance(resource, dict) and a.key in resource:
                continue  # field exists → drop the +() entry
            out[a.key] = value  # strip the anchor wrapping
        else:
            out[key] = value
    return out


def _has_anchors(pattern: Any, global_only: bool = False) -> bool:
    def check(a) -> bool:
        if a is None:
            return False
        if global_only:
            return anchor.is_global(a)
        return anchor.contains_condition(a) or anchor.is_add_if_not_present(a)

    if isinstance(pattern, dict):
        for key, value in pattern.items():
            if check(anchor.parse(key)):
                return True
            if _has_anchors(value, global_only):
                return True
        return False
    if isinstance(pattern, list):
        return any(_has_anchors(e, global_only) for e in pattern)
    if isinstance(pattern, str):
        return check(anchor.parse(pattern))
    return False


def _delete_condition_elements(pattern: Any) -> Any:
    # reference: strategicPreprocessing.go:399 deleteConditionElements
    if not isinstance(pattern, dict):
        return pattern
    out = {}
    for key, value in pattern.items():
        delete_scalar = anchor.contains_condition(anchor.parse(key))
        new_value, can_delete = _delete_anchors(value, delete_scalar, False)
        if not can_delete:
            out[key] = new_value
    return out


def _delete_anchors(node: Any, delete_scalar: bool,
                    traverse_mapping: bool) -> Tuple[Any, bool]:
    # reference: strategicPreprocessing.go:432 deleteAnchors
    if isinstance(node, dict):
        return _delete_anchors_in_map(node, traverse_mapping)
    if isinstance(node, list):
        return _delete_anchors_in_list(node, traverse_mapping)
    return node, delete_scalar


def _delete_anchors_in_map(node: dict, traverse_mapping: bool) -> Tuple[dict, bool]:
    node = dict(node)
    # conditional anchors: resolve, strip wrapping if subtree survives
    anchors_exist = False
    for key in list(node.keys()):
        a = anchor.parse(key)
        if a is None or not anchor.contains_condition(a):
            continue
        value, should_delete = _delete_anchors(node[key], True,
                                               traverse_mapping)
        del node[key]
        if not should_delete:
            node[a.key] = value
            anchors_exist = True
    need_to_delete = True
    out = {}
    for key, value in node.items():
        new_value, can_delete = _delete_anchors(value, False, traverse_mapping)
        if not can_delete:
            out[key] = new_value
            need_to_delete = False
    if anchors_exist:
        need_to_delete = False
    return out, need_to_delete and not anchors_exist


def _delete_anchors_in_list(node: list, traverse_mapping: bool) -> Tuple[list, bool]:
    was_empty = len(node) == 0
    out = []
    for element in node:
        if _has_anchors(element):
            if traverse_mapping and isinstance(element, dict):
                new_elem, should_delete = _delete_anchors(element, True,
                                                          traverse_mapping)
                if not should_delete:
                    out.append(new_elem)
            # else: drop the anchored element
        else:
            new_elem, can_delete = _delete_anchors(element, False,
                                                   traverse_mapping)
            if not can_delete:
                out.append(new_elem)
    if len(out) == 0 and not was_empty:
        return out, True
    return out, False


# ---------------------------------------------------------------------------
# Stage 2: merge

def strategic_merge(base: Any, patch: Any) -> Any:
    """Pure merge: inputs are never mutated and the OUTPUT structurally
    shares unmodified subtrees with them (new containers are built only
    along patched paths — the same copy-on-write discipline as the JSON
    context's merge_patch).  Deep-copying the whole base per map level
    dominated bulk-apply profiles."""
    if isinstance(patch, dict):
        directive = patch.get('$patch')
        if directive == 'delete':
            return None
        if directive == 'replace':
            return {k: v for k, v in patch.items() if k != '$patch'}
        if not isinstance(base, dict):
            base = {}
        out = dict(base)
        for k, v in patch.items():
            if k == '$patch':
                continue
            if v is None:
                out.pop(k, None)
            elif k in out:
                merged = strategic_merge(out[k], v)
                if merged is None:
                    out.pop(k, None)
                else:
                    out[k] = merged
            else:
                cleaned = _strip_directives(v)
                if cleaned is not None:
                    out[k] = cleaned
        return out
    if isinstance(patch, list):
        if isinstance(base, list):
            key = _associative_key(base, patch)
            if key is not None:
                return _merge_associative(base, patch, key)
        return [x for x in (_strip_directives(e) for e in patch)
                if x is not None]
    return patch


def _strip_directives(v: Any) -> Any:
    if isinstance(v, dict):
        if v.get('$patch') == 'delete':
            return None
        return {k: _strip_directives(val) for k, val in v.items()
                if k != '$patch'}
    if isinstance(v, list):
        return [x for x in (_strip_directives(e) for e in v) if x is not None]
    return v


def _associative_key(base: list, patch: list) -> Optional[str]:
    elements = [e for e in list(base) + list(patch) if e is not None]
    if not elements or not all(isinstance(e, dict) for e in elements):
        return None
    patch_elements = [e for e in patch if isinstance(e, dict)]
    candidates = patch_elements or elements
    for key in ASSOCIATIVE_KEYS:
        if all(key in e for e in candidates):
            return key
    return None


def _merge_associative(base: list, patch: list, key: str) -> list:
    out = list(base)  # unmerged elements are shared, never mutated
    index = {e.get(key): i for i, e in enumerate(out)
             if isinstance(e, dict)}
    for p in patch:
        if not isinstance(p, dict):
            out.append(p)
            continue
        k = p.get(key)
        if p.get('$patch') == 'delete':
            if k in index:
                i = index[k]
                out[i] = None
            continue
        if k in index:
            out[index[k]] = strategic_merge(out[index[k]], p)
        else:
            cleaned = _strip_directives(p)
            if cleaned is not None:
                out.append(cleaned)
                index[k] = len(out) - 1
    return [e for e in out if e is not None]
