"""RFC 6902 JSON Patch: apply and diff.

Apply mirrors the reference's evanphx/json-patch usage
(reference: pkg/engine/mutate/patch/patchJSON6902.go); diff mirrors the
patch generation used after strategic merge
(reference: pkg/engine/mutate/patch/patchesUtils.go generatePatches).
"""

from __future__ import annotations

import copy
import json
from typing import Any, List, Optional, Tuple

import yaml


class JsonPatchError(Exception):
    pass


def _unescape(token: str) -> str:
    return token.replace('~1', '/').replace('~0', '~')


def _escape(token: str) -> str:
    return token.replace('~', '~0').replace('/', '~1')


def _split_pointer(pointer: str) -> List[str]:
    if pointer == '':
        return []
    if not pointer.startswith('/'):
        raise JsonPatchError(f'invalid JSON pointer {pointer!r}')
    return [_unescape(t) for t in pointer.split('/')[1:]]


def _get(doc: Any, tokens: List[str]) -> Any:
    cur = doc
    for t in tokens:
        if isinstance(cur, dict):
            if t not in cur:
                raise JsonPatchError(f'path not found: {t!r}')
            cur = cur[t]
        elif isinstance(cur, list):
            try:
                cur = cur[int(t)]
            except (ValueError, IndexError):
                raise JsonPatchError(f'invalid array index {t!r}')
        else:
            raise JsonPatchError(f'cannot traverse scalar at {t!r}')
    return cur


def _resolve_parent(doc: Any, tokens: List[str]) -> Tuple[Any, str]:
    if not tokens:
        raise JsonPatchError('cannot operate on root document')
    return _get(doc, tokens[:-1]), tokens[-1]


def apply_patch(doc: Any, operations: List[dict]) -> Any:
    """Apply an RFC 6902 operation list, returning the patched document.

    Matches the reference's evanphx/json-patch ApplyOptions
    (patchJSON6902.go:78): EnsurePathExistsOnAdd (add creates missing
    intermediate containers), AllowMissingPathOnRemove (remove of a
    missing path is a no-op), SupportNegativeIndices.
    """
    doc = copy.deepcopy(doc)
    for op in operations:
        action = op.get('op')
        path = op.get('path', '')
        tokens = _split_pointer(path)
        if action == 'add':
            doc = _op_add(doc, tokens, copy.deepcopy(op.get('value')),
                          ensure_path=True)
        elif action == 'replace':
            doc = _op_replace(doc, tokens, copy.deepcopy(op.get('value')))
        elif action == 'remove':
            doc = _op_remove(doc, tokens, allow_missing=True)
        elif action == 'move':
            from_tokens = _split_pointer(op.get('from', ''))
            value = _get(doc, from_tokens)
            doc = _op_remove(doc, from_tokens)
            doc = _op_add(doc, tokens, value)
        elif action == 'copy':
            from_tokens = _split_pointer(op.get('from', ''))
            value = copy.deepcopy(_get(doc, from_tokens))
            doc = _op_add(doc, tokens, value)
        elif action == 'test':
            if _get(doc, tokens) != op.get('value'):
                raise JsonPatchError(f'test failed at {path}')
        else:
            raise JsonPatchError(f'invalid operation {action!r}')
    return doc


def _op_add(doc: Any, tokens: List[str], value: Any,
            ensure_path: bool = False) -> Any:
    if not tokens:
        return value
    if ensure_path:
        doc = _ensure_parents(doc, tokens)
    parent, last = _resolve_parent(doc, tokens)
    if isinstance(parent, dict):
        parent[last] = value
    elif isinstance(parent, list):
        if last == '-':
            parent.append(value)
        else:
            try:
                idx = int(last)
            except ValueError:
                raise JsonPatchError(f'invalid array index {last!r}')
            if idx < 0:
                idx += len(parent)  # SupportNegativeIndices
            if idx < 0 or idx > len(parent):
                raise JsonPatchError(f'array index {last} out of bounds')
            parent.insert(idx, value)
    else:
        raise JsonPatchError('add target parent is a scalar')
    return doc


def _ensure_parents(doc: Any, tokens: List[str]) -> Any:
    """Create missing intermediate containers along an add path
    (evanphx/json-patch EnsurePathExistsOnAdd). A next token that is an
    array index or ``-`` makes the missing container a list, else a map."""
    cur = doc
    for i, t in enumerate(tokens[:-1]):
        nxt = tokens[i + 1]
        want_list = nxt == '-' or nxt.lstrip('-').isdigit()
        if isinstance(cur, dict):
            if t not in cur or cur[t] is None:
                cur[t] = [] if want_list else {}
            cur = cur[t]
        elif isinstance(cur, list):
            if t == '-':
                cur.append([] if want_list else {})
                cur = cur[-1]
            else:
                try:
                    idx = int(t)
                except ValueError:
                    raise JsonPatchError(f'invalid array index {t!r}')
                if idx < 0:
                    idx += len(cur)
                if idx == len(cur):
                    cur.append([] if want_list else {})
                if idx < 0 or idx >= len(cur):
                    raise JsonPatchError(f'array index {t} out of bounds')
                if cur[idx] is None:
                    cur[idx] = [] if want_list else {}
                cur = cur[idx]
        else:
            raise JsonPatchError(f'cannot create path under scalar at {t!r}')
    return doc


def _op_replace(doc: Any, tokens: List[str], value: Any) -> Any:
    if not tokens:
        return value
    parent, last = _resolve_parent(doc, tokens)
    if isinstance(parent, dict):
        if last not in parent:
            raise JsonPatchError(f'replace path not found: {last!r}')
        parent[last] = value
    elif isinstance(parent, list):
        try:
            parent[int(last)] = value
        except (ValueError, IndexError):
            raise JsonPatchError(f'invalid array index {last!r}')
    else:
        raise JsonPatchError('replace target parent is a scalar')
    return doc


def _op_remove(doc: Any, tokens: List[str],
               allow_missing: bool = False) -> Any:
    try:
        parent, last = _resolve_parent(doc, tokens)
    except JsonPatchError:
        if allow_missing:
            return doc
        raise
    if isinstance(parent, dict):
        if last not in parent:
            if allow_missing:
                return doc
            raise JsonPatchError(f'remove path not found: {last!r}')
        del parent[last]
    elif isinstance(parent, list):
        try:
            idx = int(last)
        except ValueError:
            raise JsonPatchError(f'invalid array index {last!r}')
        if idx < 0:
            idx += len(parent)
        if 0 <= idx < len(parent):
            del parent[idx]
        elif not allow_missing:
            raise JsonPatchError(f'invalid array index {last!r}')
    else:
        raise JsonPatchError('remove target parent is a scalar')
    return doc


def load_patches(text: str) -> List[dict]:
    """Parse a patchesJson6902 string (JSON or YAML list of ops)."""
    try:
        ops = json.loads(text)
    except ValueError:
        try:
            ops = yaml.safe_load(text)
        except yaml.YAMLError as e:
            raise JsonPatchError(f'invalid patchesJson6902: {e}')
    if not isinstance(ops, list):
        raise JsonPatchError('patchesJson6902 must be a list of operations')
    return ops


# ---------------------------------------------------------------------------
# Diff: original → patched as RFC 6902 operations

def generate_patches(original: Any, patched: Any) -> List[dict]:
    """Produce an operation list transforming original into patched."""
    ops: List[dict] = []
    _diff(original, patched, '', ops)
    return ops


def _diff(a: Any, b: Any, path: str, ops: List[dict]) -> None:
    if type(a) is not type(b) and not (
            isinstance(a, (int, float)) and isinstance(b, (int, float))
            and not isinstance(a, bool) and not isinstance(b, bool)):
        ops.append({'op': 'replace', 'path': path or '', 'value': b})
        return
    if isinstance(a, dict):
        for k in a:
            if k not in b:
                ops.append({'op': 'remove', 'path': f'{path}/{_escape(k)}'})
        for k, v in b.items():
            child = f'{path}/{_escape(k)}'
            if k not in a:
                ops.append({'op': 'add', 'path': child, 'value': v})
            elif a[k] != v:
                _diff(a[k], v, child, ops)
    elif isinstance(a, list):
        common = min(len(a), len(b))
        for i in range(common):
            if a[i] != b[i]:
                _diff(a[i], b[i], f'{path}/{i}', ops)
        if len(b) > len(a):
            for i in range(len(a), len(b)):
                ops.append({'op': 'add', 'path': f'{path}/{i}', 'value': b[i]})
        else:
            for i in reversed(range(len(b), len(a))):
                ops.append({'op': 'remove', 'path': f'{path}/{i}'})
    else:
        if a != b:
            ops.append({'op': 'replace', 'path': path or '', 'value': b})
