"""Mutation entry points and the engine mutate rule loop.

reference: pkg/engine/mutation.go (rule loop + foreach mutator),
pkg/engine/mutate/mutation.go (Mutate/ForEach handlers).
"""

from __future__ import annotations

import time
from typing import Any, List, Optional

from ...api.policy import Policy, Rule
from ...api.unstructured import Resource
from .. import operators
from .. import variables as vars_mod
from ..api import (EngineResponse, PolicyContext, RuleResponse, RuleStatus,
                   RuleType)
from ..context import Context, ContextError, InvalidVariableError
from ..match import matches_resource_description
from ..variables import SubstitutionError
from .jsonpatch import JsonPatchError, apply_patch, generate_patches, load_patches
from .strategic import (ConditionError, GlobalConditionError,
                        preprocess_pattern, strategic_merge)


class MutateResponse:
    def __init__(self, status: str, patched_resource: Optional[dict],
                 patches: Optional[List[dict]], message: str):
        self.status = status
        self.patched_resource = patched_resource
        self.patches = patches or []
        self.message = message


def _error_response(msg: str, err: Exception) -> MutateResponse:
    return MutateResponse(RuleStatus.ERROR, None, None, f'{msg}: {err}')


def mutate_rule(rule_raw: dict, ctx: Context, resource: dict) -> MutateResponse:
    """Apply one mutate rule to a resource
    (reference: pkg/engine/mutate/mutation.go:38 Mutate)."""
    try:
        if vars_mod.tree_has_variables(rule_raw):
            # substitute_all output may ALIAS the rule tree (static
            # subtrees are returned by reference via _STATIC_TREES) —
            # safe only because it is treated read-only here and every
            # downstream applier copies before mutating
            updated_rule = vars_mod.substitute_all(ctx, rule_raw)
        else:
            # constant rule: substitution is the identity, and every
            # downstream consumer copies before mutating — skip the
            # per-resource deepcopy + walk (bulk-apply hot path)
            updated_rule = rule_raw
    except (SubstitutionError, ContextError, InvalidVariableError) as e:
        return _error_response('variable substitution failed', e)
    mutation = updated_rule.get('mutate') or {}
    resp = _apply_patcher(mutation, resource, ctx)
    if resp.status != RuleStatus.PASS:
        return resp
    if not resp.patches:
        return MutateResponse(RuleStatus.SKIP, resource, None,
                              'no patches applied')
    is_mutate_existing = bool((rule_raw.get('mutate') or {}).get('targets'))
    if is_mutate_existing:
        ctx.add_target_resource(resp.patched_resource)
    else:
        ctx.add_resource(resp.patched_resource)
    return resp


def mutate_foreach_entry(name: str, foreach: dict, ctx: Context,
                         resource: dict) -> MutateResponse:
    """reference: pkg/engine/mutate/mutation.go:72 ForEach"""
    try:
        fe = vars_mod.substitute_all(ctx, foreach)
    except (SubstitutionError, ContextError, InvalidVariableError) as e:
        return _error_response('variable substitution failed', e)
    resp = _apply_patcher(fe, resource, ctx)
    if resp.status != RuleStatus.PASS:
        return resp
    if not resp.patches:
        return MutateResponse(RuleStatus.SKIP, resource, None,
                              'no patches applied')
    ctx.add_resource(resp.patched_resource)
    return resp


def _apply_patcher(mutation: dict, resource: dict, ctx: Context) -> MutateResponse:
    smp = mutation.get('patchStrategicMerge')
    json6902 = mutation.get('patchesJson6902')
    if smp is not None:
        return _apply_strategic_merge(smp, resource)
    if json6902:
        return _apply_json6902(json6902, resource)
    return MutateResponse(RuleStatus.ERROR, resource, None, 'empty mutate rule')


def _apply_strategic_merge(overlay: Any, resource: dict) -> MutateResponse:
    # reference: pkg/engine/mutate/patch/strategicMergePatch.go:18
    # preprocess_pattern never mutates the overlay (strategic.py module
    # note), so the rule-constant tree applies per resource without a
    # deepcopy; the patched output may alias overlay subtrees — the
    # substitute_all read-only contract downstream consumers already
    # honor
    try:
        try:
            processed = preprocess_pattern(overlay, resource)
        except (ConditionError, GlobalConditionError):
            processed = {}
        patched = strategic_merge(resource, processed)
        if patched is None:
            patched = {}
    except Exception as e:  # preprocessing bugs must not crash the webhook
        return MutateResponse(RuleStatus.FAIL, resource, None,
                              f'failed to apply patchStrategicMerge: {e}')
    patches = generate_patches(resource, patched)
    return MutateResponse(RuleStatus.PASS, patched, patches,
                          'applied strategic merge patch')


_PATCH_TEXT_CACHE: dict = {}


def _load_patches_cached(patch_text: str):
    """The patch text is a rule constant; parsing it per resource
    dominated bulk applies.  apply_patch treats ops read-only."""
    ops = _PATCH_TEXT_CACHE.get(patch_text)
    if ops is None:
        if len(_PATCH_TEXT_CACHE) > 1024:
            _PATCH_TEXT_CACHE.clear()
        ops = load_patches(patch_text)
        _PATCH_TEXT_CACHE[patch_text] = ops
    return ops


def _apply_json6902(patch_text: Any, resource: dict) -> MutateResponse:
    # reference: pkg/engine/mutate/patch/patchJSON6902.go
    try:
        if isinstance(patch_text, str):
            ops = _load_patches_cached(patch_text)
        else:
            ops = patch_text
        patched = apply_patch(resource, ops)
    except JsonPatchError as e:
        return MutateResponse(RuleStatus.FAIL, resource, None,
                              f'failed to apply patchesJson6902: {e}')
    patches = generate_patches(resource, patched)
    return MutateResponse(RuleStatus.PASS, patched, patches,
                          'applied patchesJson6902')


# ---------------------------------------------------------------------------
# Engine-level Mutate

def mutate(engine, pctx: PolicyContext) -> EngineResponse:
    """The engine Mutate entry (reference: pkg/engine/mutation.go:24)."""
    start = time.time()
    policy = pctx.policy
    resp = EngineResponse(policy)
    matched_resource = pctx.new_resource
    skipped_rules: List[str] = []

    pctx.json_context.checkpoint()
    try:
        apply_rules = policy.apply_rules
        for raw_rule in engine._compute_rules(policy):
            rule = Rule(raw_rule)
            if not rule.has_mutate():
                continue
            err = matches_resource_description(
                Resource(matched_resource), rule, pctx.admission_info,
                pctx.exclude_group_roles, pctx.namespace_labels,
                policy.namespace, pctx.subresource)
            if err is not None:
                skipped_rules.append(rule.name)
                continue
            exception_resp = engine._check_exceptions(pctx, rule)
            if exception_resp is not None:
                exception_resp.rule_type = RuleType.MUTATION
                resp.policy_response.rules.append(exception_resp)
                continue
            # refresh request.object in context then reset to checkpoint
            try:
                resource = pctx.json_context.query('request.object')
            except (ContextError, InvalidVariableError):
                resource = None
            pctx.json_context.reset()
            if isinstance(resource, dict):
                pctx.json_context.add_resource(resource)
            try:
                engine.context_loader.load(rule.context, pctx.json_context,
                                           policy_name=pctx.policy.name,
                                           rule_name=rule.name)
            except (ContextError, SubstitutionError, InvalidVariableError):
                continue

            rule_start = time.time()
            if (rule.mutation or {}).get('foreach') is not None:
                mutator = ForEachMutator(engine, rule, pctx,
                                         matched_resource, nesting=0)
                mutate_resp = mutator.mutate_foreach()
            else:
                mutate_resp = _mutate_resource(rule, pctx, matched_resource)

            if mutate_resp.patched_resource is not None:
                matched_resource = mutate_resp.patched_resource
            message = mutate_resp.message
            if mutate_resp.status == RuleStatus.PASS:
                # reference: mutation.go:334 buildRuleResponse →
                # :347 buildSuccessMessage
                message = _success_message(mutate_resp.patched_resource)
            rule_resp = RuleResponse(rule.name, RuleType.MUTATION,
                                     message, mutate_resp.status,
                                     patches=mutate_resp.patches)
            rule_resp.processing_time = time.time() - rule_start
            resp.policy_response.rules.append(rule_resp)
            if mutate_resp.status == RuleStatus.ERROR:
                resp.policy_response.rules_error_count += 1
            else:
                resp.policy_response.rules_applied_count += 1
            if apply_rules == 'One' and \
                    resp.policy_response.rules_applied_count > 0:
                break
    finally:
        pctx.json_context.restore()

    for r in resp.policy_response.rules:
        if r.name in skipped_rules:
            r.status = RuleStatus.SKIP

    resp.patched_resource = matched_resource
    engine._build_response(pctx, resp, start)
    return resp


def _success_message(patched: Optional[dict]) -> str:
    """reference: pkg/engine/mutation.go:347 buildSuccessMessage"""
    if not patched:
        return 'mutated resource'
    meta = patched.get('metadata') or {}
    kind = patched.get('kind', '')
    name = meta.get('name', '')
    ns = meta.get('namespace', '')
    if not ns:
        return f'mutated {kind}/{name}'
    return f'mutated {kind}/{name} in namespace {ns}'


def _mutate_resource(rule: Rule, pctx: PolicyContext,
                     resource: dict) -> MutateResponse:
    # reference: pkg/engine/mutation.go:189 mutateResource
    try:
        passed = _check_preconditions(pctx, rule.preconditions)
    except (ContextError, SubstitutionError, InvalidVariableError) as e:
        return _error_response('failed to evaluate preconditions', e)
    if not passed:
        return MutateResponse(RuleStatus.SKIP, resource, None,
                              'preconditions not met')
    return mutate_rule(rule.raw, pctx.json_context, resource)


def _check_preconditions(pctx: PolicyContext, conditions: Any) -> bool:
    if conditions is None:
        return True
    substituted = vars_mod.substitute_all_in_preconditions(
        pctx.json_context, conditions)
    return operators.evaluate_conditions(pctx.json_context, substituted)


class ForEachMutator:
    """reference: pkg/engine/mutation.go:202 forEachMutator"""

    def __init__(self, engine, rule: Rule, pctx: PolicyContext,
                 resource: dict, nesting: int):
        self.engine = engine
        self.rule = rule
        self.pctx = pctx
        self.resource = resource
        self.nesting = nesting
        self.foreach = (rule.mutation or {}).get('foreach') or []

    def mutate_foreach(self, foreach_list: Optional[List[dict]] = None) -> MutateResponse:
        apply_count = 0
        all_patches: List[dict] = []
        entries = foreach_list if foreach_list is not None else self.foreach
        for foreach in entries:
            try:
                self.engine.context_loader.load(
                    self.rule.context, self.pctx.json_context,
                    policy_name=self.pctx.policy.name,
                    rule_name=self.rule.name)
            except (ContextError, SubstitutionError, InvalidVariableError) as e:
                return _error_response('failed to load context', e)
            try:
                passed = _check_preconditions(self.pctx, self.rule.preconditions)
            except (ContextError, SubstitutionError, InvalidVariableError) as e:
                return _error_response('failed to evaluate preconditions', e)
            if not passed:
                return MutateResponse(RuleStatus.SKIP, self.resource, None,
                                      'preconditions not met')
            try:
                elements = self.pctx.json_context.query(foreach.get('list', ''))
            except (ContextError, InvalidVariableError) as e:
                return _error_response(
                    f'failed to evaluate list {foreach.get("list")}', e)
            if not isinstance(elements, list):
                elements = [elements]
            mutate_resp = self._mutate_elements(foreach, elements)
            if mutate_resp.status == RuleStatus.ERROR:
                return mutate_resp
            if mutate_resp.status != RuleStatus.SKIP:
                apply_count += 1
                if mutate_resp.patches:
                    self.resource = mutate_resp.patched_resource
                    all_patches.extend(mutate_resp.patches)
        msg = f'{apply_count} elements processed'
        status = RuleStatus.SKIP if apply_count == 0 else RuleStatus.PASS
        return MutateResponse(status, self.resource, all_patches, msg)

    def _mutate_elements(self, foreach: dict, elements: List[Any]) -> MutateResponse:
        ctx = self.pctx.json_context
        ctx.checkpoint()
        try:
            patched = self.resource
            all_patches: List[dict] = []
            if foreach.get('patchStrategicMerge') is not None:
                elements = list(reversed(elements))
            for index, element in enumerate(elements):
                if element is None:
                    continue
                ctx.reset()
                pctx = self.pctx.copy()
                ctx.add_element(element, index, self.nesting)
                try:
                    self.engine.context_loader.load(
                        foreach.get('context') or [], ctx,
                        policy_name=self.pctx.policy.name,
                        rule_name=self.rule.name)
                except (ContextError, SubstitutionError,
                        InvalidVariableError) as e:
                    return _error_response(
                        f'failed to load to mutate.foreach[{index}].context', e)
                try:
                    passed = _check_preconditions(
                        pctx, foreach.get('preconditions'))
                except (ContextError, SubstitutionError,
                        InvalidVariableError) as e:
                    return _error_response(
                        f'failed to evaluate mutate.foreach[{index}]'
                        f'.preconditions', e)
                if not passed:
                    continue
                nested = foreach.get('foreach')
                if nested is not None:
                    sub = ForEachMutator(self.engine, self.rule, self.pctx,
                                         patched, self.nesting + 1)
                    mutate_resp = sub.mutate_foreach(nested)
                else:
                    mutate_resp = mutate_foreach_entry(
                        self.rule.name, foreach, ctx, patched)
                if mutate_resp.status in (RuleStatus.FAIL, RuleStatus.ERROR):
                    return mutate_resp
                if mutate_resp.patches:
                    patched = mutate_resp.patched_resource
                    all_patches.extend(mutate_resp.patches)
            return MutateResponse(RuleStatus.PASS, patched, all_patches, '')
        finally:
            ctx.restore()
