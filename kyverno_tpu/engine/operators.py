"""Condition operators for preconditions / deny conditions.

Re-implements the 18 operators of the reference
(reference: api/kyverno/v1/common_types.go:203-246 ConditionOperators,
pkg/engine/variables/operator/*.go):

Equal(s), NotEqual(s), In, AnyIn, AllIn, NotIn, AnyNotIn, AllNotIn,
GreaterThan(OrEquals), LessThan(OrEquals), Duration* (deprecated).

Type-coercion quirks preserved: wildcard matching on strings (both
directions for the In family), duration-before-quantity for Equals,
quantity/semver/float fallbacks for numeric comparison, ranges
("1-10") inside AnyIn/AllIn string values.
"""

from __future__ import annotations

import json
import math
from typing import Any, List, Optional, Tuple

from ..utils import wildcard
from ..utils.duration import parse_duration
from ..utils.quantity import Quantity
from . import pattern as leaf_pattern


def evaluate(ctx, condition: dict) -> bool:
    """Evaluate one condition {key, operator, value}
    (reference: pkg/engine/variables/evaluate.go:11)."""
    op = str(condition.get('operator', ''))
    key = condition.get('key')
    value = condition.get('value')
    handler = _HANDLERS.get(op.lower())
    if handler is None:
        return False
    return handler(key, value)


def evaluate_conditions(ctx, conditions: Any) -> bool:
    """Evaluate any/all condition blocks, supporting both the new
    AnyAllConditions form and the legacy list-of-conditions form
    (reference: pkg/engine/variables/evaluate.go:21)."""
    if conditions is None:
        # nil conditions transform to an empty AnyAllConditions block which
        # evaluates vacuously true (reference: pkg/utils/conditions.go
        # TransformConditions + evaluate.go:42) — deny: {} always denies
        return True
    if isinstance(conditions, dict):
        return _evaluate_any_all(ctx, conditions)
    if isinstance(conditions, list):
        if all(isinstance(c, dict) and ('any' in c or 'all' in c)
               for c in conditions) and conditions:
            return all(_evaluate_any_all(ctx, c) for c in conditions)
        return all(evaluate(ctx, c) for c in conditions)
    return False


def evaluate_any_all_list(ctx, conditions: List[dict]) -> bool:
    return all(_evaluate_any_all(ctx, c) for c in conditions)


def _evaluate_any_all(ctx, conditions: dict) -> bool:
    any_conditions = conditions.get('any')
    all_conditions = conditions.get('all')
    any_result, all_result = True, True
    if any_conditions is not None:
        any_result = any(evaluate(ctx, c) for c in any_conditions)
    if all_conditions:
        all_result = all(evaluate(ctx, c) for c in all_conditions)
    return any_result and all_result


# ---------------------------------------------------------------------------
# helpers

def _is_num(v: Any) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool)


def _sprint(v: Any) -> str:
    """Go fmt.Sprint for scalars."""
    if isinstance(v, bool):
        return 'true' if v else 'false'
    if isinstance(v, float):
        if v == int(v) and abs(v) < 1e21:
            return str(int(v))
        return repr(v)
    return str(v)


def _try_duration(v: Any) -> Optional[int]:
    """Parse a duration if the value is a duration string and not '0'
    (reference: pkg/engine/variables/operator/operator.go:80 parseDuration)."""
    if isinstance(v, str) and v != '0':
        try:
            return parse_duration(v)
        except ValueError:
            return None
    return None


def _duration_pair(key: Any, value: Any) -> Optional[Tuple[float, float]]:
    kd = _try_duration(key)
    vd = _try_duration(value)
    if kd is None and vd is None:
        return None
    if kd is None:
        if _is_num(key):
            kd = int(key * 1e9)
        else:
            return None
    if vd is None:
        if _is_num(value):
            vd = int(value * 1e9)
        else:
            return None
    return kd / 1e9, vd / 1e9


def _try_quantity(v: Any) -> Optional[Quantity]:
    if isinstance(v, str):
        try:
            return Quantity.parse(v)
        except ValueError:
            return None
    return None


# ---------------------------------------------------------------------------
# Equals / NotEquals

def _equal(key: Any, value: Any) -> bool:
    # reference: pkg/engine/variables/operator/equal.go
    if isinstance(key, bool):
        return isinstance(value, bool) and key == value
    if isinstance(key, int) and not isinstance(key, bool):
        return _equal_int(key, value)
    if isinstance(key, float):
        return _equal_float(key, value)
    if isinstance(key, str):
        return _equal_string(key, value)
    if isinstance(key, dict):
        return isinstance(value, dict) and key == value
    if isinstance(key, list):
        return isinstance(value, list) and key == value
    return False


def _equal_int(key: int, value: Any) -> bool:
    if isinstance(value, bool):
        return False
    if isinstance(value, int):
        return key == value
    if isinstance(value, float):
        return value == math.trunc(value) and int(value) == key
    if isinstance(value, str):
        try:
            return float(value) == float(key)
        except ValueError:
            return False
    return False


def _equal_float(key: float, value: Any) -> bool:
    if isinstance(value, bool):
        return False
    if isinstance(value, int):
        return key == math.trunc(key) and int(key) == value
    if isinstance(value, float):
        return key == value
    if isinstance(value, str):
        try:
            return float(value) == key
        except ValueError:
            return False
    return False


def _equal_string(key: str, value: Any) -> bool:
    pair = _duration_pair(key, value)
    if pair is not None:
        return pair[0] == pair[1]
    kq = _try_quantity(key)
    if kq is not None and isinstance(value, str):
        vq = _try_quantity(value)
        if vq is None:
            return False
        return kq.cmp(vq) == 0
    if isinstance(value, str):
        return wildcard.match(value, key)
    return False


def _not_equal(key: Any, value: Any) -> bool:
    return not _equal(key, value)


# ---------------------------------------------------------------------------
# In family

def _string_slice(key: list, strict: bool) -> Optional[List[str]]:
    out = []
    for v in key:
        if strict and not isinstance(v, str):
            return None
        out.append(v if isinstance(v, str) else _sprint(v))
    return out


def _value_as_string_list(value: str) -> Optional[List[str]]:
    """A string value may itself be a JSON array of strings."""
    try:
        arr = json.loads(value)
    except ValueError:
        return None
    if isinstance(arr, list) and all(isinstance(x, str) for x in arr):
        return arr
    return None


def _key_in_array(key: str, value: Any, wildcard_both: bool = True,
                  allow_range: bool = False) -> Optional[bool]:
    """Shared 'does key exist in value' logic; None means invalid type."""
    if isinstance(value, list):
        for val in value:
            vs = _sprint(val) if not isinstance(val, str) else val
            if wildcard.match(vs, key) or (wildcard_both and wildcard.match(key, vs)):
                return True
        return False
    if isinstance(value, str):
        if wildcard.match(value, key):
            return True
        if allow_range and leaf_pattern.get_operator_from_string_pattern(value) == leaf_pattern.OP_IN_RANGE:
            return leaf_pattern.validate(key, value)
        arr = _value_as_string_list(value)
        if arr is None:
            if allow_range:
                arr = [value]
            else:
                return None
        return key in arr
    return None


def _in(key: Any, value: Any) -> bool:
    # deprecated In (reference: operator/in.go)
    if isinstance(key, str):
        return bool(_key_in_array(key, value))
    if _is_num(key):
        return bool(_key_in_array(_sprint(key), value))
    if isinstance(key, list):
        keys = _string_slice(key, strict=True)
        if keys is None:
            return False
        return _set_in(keys, value, negate=False)
    return False


def _set_in(keys: List[str], value: Any, negate: bool) -> bool:
    # reference: operator/in.go:106 setExistsInArray
    if isinstance(value, list):
        vals = []
        for v in value:
            if not isinstance(v, str):
                return False
            vals.append(v)
        vals_set = set(vals)
        missing_any = any(k not in vals_set for k in keys)
        return missing_any if negate else not missing_any
    if isinstance(value, str):
        if len(keys) == 1 and keys[0] == value:
            return not negate
        arr = _value_as_string_list(value)
        if arr is None:
            return False
        arr_set = set(arr)
        if negate:
            return any(k not in arr_set for k in keys)
        return all(k in arr_set for k in keys)
    return False


def _not_in(key: Any, value: Any) -> bool:
    if isinstance(key, str):
        r = _key_in_array(key, value)
        return (not r) if r is not None else False
    if _is_num(key):
        r = _key_in_array(_sprint(key), value)
        return (not r) if r is not None else False
    if isinstance(key, list):
        keys = _string_slice(key, strict=True)
        if keys is None:
            return False
        return _set_in(keys, value, negate=True)
    return False


def _any_in(key: Any, value: Any) -> bool:
    # reference: operator/anyin.go
    if isinstance(key, str) or _is_num(key):
        k = key if isinstance(key, str) else _sprint(key)
        r = _key_in_array(k, value, allow_range=True)
        return bool(r)
    if isinstance(key, list):
        keys = _string_slice(key, strict=False)
        return _any_set_in(keys, value, negate=False)
    return False


def _any_not_in(key: Any, value: Any) -> bool:
    if isinstance(key, str) or _is_num(key):
        k = key if isinstance(key, str) else _sprint(key)
        r = _key_in_array(k, value, allow_range=True)
        return (not r) if r is not None else False
    if isinstance(key, list):
        keys = _string_slice(key, strict=False)
        return _any_set_in(keys, value, negate=True)
    return False


def _k_in_wild(k: str, vals: List[str]) -> bool:
    """Bidirectional wildcard membership (reference: anyin.go:190 isAnyIn
    inner loop — wildcard.Match(key, val) || wildcard.Match(val, key))."""
    return any(wildcard.match(k, v) or wildcard.match(v, k) for v in vals)


def _any_set_in(keys: List[str], value: Any, negate: bool) -> bool:
    # reference: operator/anyin.go:124 anySetExistsInArray
    if isinstance(value, list):
        vals = [v if isinstance(v, str) else _sprint(v) for v in value]
        if negate:
            return any(not _k_in_wild(k, vals) for k in keys)
        return any(_k_in_wild(k, vals) for k in keys)
    if isinstance(value, str):
        if len(keys) == 1 and keys[0] == value:
            return not negate
        if leaf_pattern.get_operator_from_string_pattern(value) == leaf_pattern.OP_IN_RANGE:
            if negate:
                not_range = value.replace('-', '!-', 1)
                return any(leaf_pattern.validate(k, not_range) for k in keys)
            return any(leaf_pattern.validate(k, value) for k in keys)
        arr = _value_as_string_list(value)
        if arr is None:
            arr = [value]
        # reference parses the JSON/string form then runs the same
        # isAnyIn/isAnyNotIn wildcard membership (anyin.go:168-183)
        if negate:
            return any(not _k_in_wild(k, arr) for k in keys)
        return any(_k_in_wild(k, arr) for k in keys)
    return False


def _all_in(key: Any, value: Any) -> bool:
    # reference: operator/allin.go
    if isinstance(key, str) or _is_num(key):
        k = key if isinstance(key, str) else _sprint(key)
        r = _key_in_array(k, value, allow_range=True)
        return bool(r)
    if isinstance(key, list):
        keys = _string_slice(key, strict=False)
        return _all_set_in(keys, value, negate=False)
    return False


def _all_not_in(key: Any, value: Any) -> bool:
    if isinstance(key, str) or _is_num(key):
        k = key if isinstance(key, str) else _sprint(key)
        r = _key_in_array(k, value, allow_range=True)
        return (not r) if r is not None else False
    if isinstance(key, list):
        keys = _string_slice(key, strict=False)
        return _all_set_in(keys, value, negate=True)
    return False


def _all_set_in(keys: List[str], value: Any, negate: bool) -> bool:
    # reference: operator/allin.go:112 allSetExistsInArray.  AllNotIn is
    # universal (allin.go:192 isAllNotIn): false if ANY key element
    # matches any value element.
    if isinstance(value, list):
        vals = [v if isinstance(v, str) else _sprint(v) for v in value]
        if negate:
            return all(not _k_in_wild(k, vals) for k in keys)
        return all(_k_in_wild(k, vals) for k in keys)
    if isinstance(value, str):
        if len(keys) == 1 and keys[0] == value:
            return not negate
        if leaf_pattern.get_operator_from_string_pattern(value) == leaf_pattern.OP_IN_RANGE:
            if negate:
                return all(not leaf_pattern.validate(k, value) for k in keys)
            return all(leaf_pattern.validate(k, value) for k in keys)
        arr = _value_as_string_list(value)
        if arr is None:
            arr = [value]
        # same isAllIn/isAllNotIn wildcard membership as the list form
        # (allin.go:137-139,168-170)
        if negate:
            return all(not _k_in_wild(k, arr) for k in keys)
        return all(_k_in_wild(k, arr) for k in keys)
    return False


# ---------------------------------------------------------------------------
# Numeric comparison

def _cmp(op: str, a: float, b: float) -> bool:
    if op == 'greaterthanorequals':
        return a >= b
    if op == 'greaterthan':
        return a > b
    if op == 'lessthanorequals':
        return a <= b
    if op == 'lessthan':
        return a < b
    return False


def _numeric(op: str):
    def handler(key: Any, value: Any) -> bool:
        # reference: operator/numeric.go
        if _is_num(key):
            return _numeric_num_key(op, float(key), value)
        if isinstance(key, str):
            pair = _duration_pair(key, value)
            if pair is not None:
                return _cmp(op, pair[0], pair[1])
            kq = _try_quantity(key)
            vq = _try_quantity(value) if isinstance(value, str) else None
            if kq is not None and vq is not None:
                return _cmp(op, float(kq.cmp(vq)), 0.0)
            try:
                return _numeric_num_key(op, float(key), value)
            except (ValueError, TypeError):
                pass
            sv = _try_semver(key)
            if sv is not None and isinstance(value, str):
                vv = _try_semver(value)
                if vv is None:
                    return False
                from .jmespath.custom import _semver_cmp
                return _cmp(op, float(_semver_cmp(sv, vv)), 0.0)
            return False
        return False
    return handler


def _numeric_num_key(op: str, key: float, value: Any) -> bool:
    if isinstance(value, bool):
        return False
    if isinstance(value, (int, float)):
        return _cmp(op, key, float(value))
    if isinstance(value, str):
        pair = _duration_pair(key, value)
        if pair is not None:
            return _cmp(op, pair[0], pair[1])
        try:
            return _cmp(op, key, float(value))
        except ValueError:
            return False
    return False


def _try_semver(v: str):
    from .jmespath.custom import _SEMVER_RE, _parse_semver
    if _SEMVER_RE.match(v.strip()):
        try:
            return _parse_semver(v)
        except Exception:
            return None
    return None


# ---------------------------------------------------------------------------
# Duration operators (deprecated)

def _duration(op: str):
    core = {'durationgreaterthanorequals': 'greaterthanorequals',
            'durationgreaterthan': 'greaterthan',
            'durationlessthanorequals': 'lessthanorequals',
            'durationlessthan': 'lessthan'}[op]

    def handler(key: Any, value: Any) -> bool:
        # reference: operator/duration.go — ints are seconds
        def to_seconds(v: Any) -> Optional[float]:
            if isinstance(v, bool):
                return None
            if isinstance(v, (int, float)):
                return float(v)
            if isinstance(v, str):
                try:
                    return parse_duration(v) / 1e9
                except ValueError:
                    return None
            return None
        ks, vs = to_seconds(key), to_seconds(value)
        if ks is None or vs is None:
            return False
        return _cmp(core, ks, vs)
    return handler


_HANDLERS = {
    'equal': _equal,
    'equals': _equal,
    'notequal': _not_equal,
    'notequals': _not_equal,
    'in': _in,
    'anyin': _any_in,
    'allin': _all_in,
    'notin': _not_in,
    'anynotin': _any_not_in,
    'allnotin': _all_not_in,
    'greaterthanorequals': _numeric('greaterthanorequals'),
    'greaterthan': _numeric('greaterthan'),
    'lessthanorequals': _numeric('lessthanorequals'),
    'lessthan': _numeric('lessthan'),
    'durationgreaterthanorequals': _duration('durationgreaterthanorequals'),
    'durationgreaterthan': _duration('durationgreaterthan'),
    'durationlessthanorequals': _duration('durationlessthanorequals'),
    'durationlessthan': _duration('durationlessthan'),
}
