"""Pattern ("overlay") validation: tree-walk of a resource against a pattern.

Re-implements the reference's MatchPattern walk
(reference: pkg/engine/validate/validate.go) with anchor semantics from
``anchor.py``.  The public entry is :func:`match_pattern`, which returns None
on success and raises :class:`PatternError` on mismatch; ``PatternError.skip``
distinguishes "rule does not apply" (conditional/global anchor miss) from a
genuine validation failure.
"""

from __future__ import annotations

from typing import Any, Optional

from . import anchor
from . import pattern as leaf
from ..utils import wildcard


class PatternError(Exception):
    def __init__(self, msg: str, path: str = '', skip: bool = False):
        super().__init__(msg)
        self.path = path
        self.skip = skip


def match_pattern(resource: Any, pattern: Any) -> None:
    """Validate ``resource`` against ``pattern`` starting at root
    (reference: pkg/engine/validate/validate.go:31).  Raises PatternError."""
    ac = anchor.AnchorMap()
    try:
        _validate_element(resource, pattern, pattern, '/', ac)
    except anchor.ValidateError as err:
        if anchor.is_skip_error(err):
            raise PatternError(str(err), '', skip=True) from err
        if anchor.is_fail_error(err):
            raise PatternError(str(err), err.path, skip=False) from err
        if ac.keys_are_missing():
            raise PatternError(str(err), '', skip=False) from err
        raise PatternError(str(err), err.path, skip=False) from err


def _validate_element(resource_element: Any, pattern_element: Any,
                      origin_pattern: Any, path: str,
                      ac: anchor.AnchorMap) -> None:
    # reference: pkg/engine/validate/validate.go:71 validateResourceElement
    if isinstance(pattern_element, dict):
        if not isinstance(resource_element, dict):
            raise anchor.ValidateError(
                f'pattern and resource have different structures. Path: {path}. '
                f'Expected map, found {_type_name(resource_element)}', path)
        ac.check_anchor_in_resource(pattern_element, resource_element)
        _validate_map(resource_element, pattern_element, origin_pattern, path, ac)
    elif isinstance(pattern_element, list):
        if not isinstance(resource_element, list):
            raise anchor.ValidateError(
                f'validation rule failed at path {path}, resource does not '
                f'satisfy the expected overlay pattern', path)
        _validate_array(resource_element, pattern_element, origin_pattern, path, ac)
    elif isinstance(pattern_element, (str, float, int, bool)) or pattern_element is None:
        if isinstance(resource_element, list):
            for res in resource_element:
                if not leaf.validate(res, pattern_element):
                    raise anchor.ValidateError(
                        f"resource value '{_fmt(resource_element)}' does not "
                        f"match '{_fmt(pattern_element)}' at path {path}", path)
        else:
            if not leaf.validate(resource_element, pattern_element):
                raise anchor.ValidateError(
                    f"resource value '{_fmt(resource_element)}' does not "
                    f"match '{_fmt(pattern_element)}' at path {path}", path)
    else:
        raise anchor.ValidateError(
            f"failed at '{path}', pattern contains unknown type", path)


def _validate_map(resource_map: dict, pattern_map: dict, origin_pattern: Any,
                  path: str, ac: anchor.AnchorMap) -> None:
    # reference: pkg/engine/validate/validate.go:118 validateMap
    pattern_map = expand_metadata_wildcards(pattern_map, resource_map)
    anchors, resources = anchor.get_anchors_resources_from_map(pattern_map)

    # Phase 1: condition/existence/equality/negation anchors, sorted key order
    for key in sorted(anchors):
        anchor.handle_element(key, anchors[key], path, _validate_element,
                              resource_map, origin_pattern, ac)

    # Phase 2: plain keys + global anchors; global anchors and keys whose
    # subtree contains anchors are processed first
    for key in _sorted_nested_anchor_keys(resources):
        anchor.handle_element(key, resources[key], path, _validate_element,
                              resource_map, origin_pattern, ac)


def _validate_array(resource_array: list, pattern_array: list,
                    origin_pattern: Any, path: str,
                    ac: anchor.AnchorMap) -> None:
    # reference: pkg/engine/validate/validate.go:163 validateArray
    if len(pattern_array) == 0:
        raise anchor.ValidateError('pattern Array empty', path)
    first = pattern_array[0]
    if isinstance(first, dict):
        _validate_array_of_maps(resource_array, first, origin_pattern, path, ac)
    elif isinstance(first, (str, float, int, bool)) or first is None:
        _validate_element(resource_array, first, origin_pattern, path, ac)
    else:
        if len(resource_array) < len(pattern_array):
            raise anchor.ValidateError(
                f'validate Array failed, array length mismatch, resource Array '
                f'len is {len(resource_array)} and pattern Array len is '
                f'{len(pattern_array)}', '')
        apply_count = 0
        skip_errors = []
        for i, pattern_element in enumerate(pattern_array):
            current_path = f'{path}{i}/'
            try:
                _validate_element(resource_array[i], pattern_element,
                                  origin_pattern, current_path, ac)
            except anchor.ValidateError as err:
                if anchor.is_skip_error(err):
                    skip_errors.append(err)
                    continue
                raise
            apply_count += 1
        if apply_count == 0 and skip_errors:
            raise anchor.ConditionalAnchorError(
                '; '.join(str(e) for e in skip_errors), path)


def _validate_array_of_maps(resource_array: list, pattern_map: dict,
                            origin_pattern: Any, path: str,
                            ac: anchor.AnchorMap) -> None:
    # reference: pkg/engine/validate/validate.go:218 validateArrayOfMaps
    apply_count = 0
    skip_errors = []
    for i, resource_element in enumerate(resource_array):
        current_path = f'{path}{i}/'
        try:
            _validate_element(resource_element, pattern_map, origin_pattern,
                              current_path, ac)
        except anchor.ValidateError as err:
            if anchor.is_skip_error(err):
                skip_errors.append(err)
                continue
            raise
        apply_count += 1
    if apply_count == 0 and skip_errors:
        raise anchor.ConditionalAnchorError(
            '; '.join(str(e) for e in skip_errors), path)


# ---------------------------------------------------------------------------

def has_nested_anchors(pattern: Any) -> bool:
    if isinstance(pattern, dict):
        for key, value in pattern.items():
            if anchor.parse(key) is not None:
                return True
            if has_nested_anchors(value):
                return True
        return False
    if isinstance(pattern, list):
        return any(has_nested_anchors(v) for v in pattern)
    return False


def _sorted_nested_anchor_keys(resources: dict) -> list:
    front, back = [], []
    for k in sorted(resources):
        v = resources[k]
        if anchor.is_global(anchor.parse(k)) or has_nested_anchors(v):
            # pushed to the front in reverse-sorted order like the reference's
            # PushFront over sorted keys
            front.insert(0, k)
        else:
            back.append(k)
    return front + back


def expand_metadata_wildcards(pattern_map: dict, resource_map: dict) -> dict:
    """Expand wildcard keys under metadata.labels / metadata.annotations of a
    pattern against the resource's actual keys
    (reference: pkg/engine/wildcards/wildcards.go:62 ExpandInMetadata)."""
    meta_key, pattern_meta = _get_pattern_value('metadata', pattern_map)
    if pattern_meta is None or not isinstance(pattern_meta, dict):
        return pattern_map
    resource_meta = resource_map.get('metadata')
    if not isinstance(resource_meta, dict):
        return pattern_map
    out_meta = dict(pattern_meta)
    changed = False
    for tag in ('labels', 'annotations'):
        pk, pdata = _get_string_map(tag, pattern_meta)
        _, rdata = _get_string_map(tag, resource_meta)
        if pdata is None or rdata is None:
            continue
        expanded = {}
        for k, v in pdata.items():
            if wildcard.contains_wildcard(k):
                a = anchor.parse(k)
                bare = a.key if a else k
                match_k = next((rk for rk in rdata if wildcard.match(bare, rk)), bare)
                expanded[f'{a.modifier}({match_k})' if a else match_k] = v
            else:
                expanded[k] = v
        out_meta[pk] = expanded
        changed = True
    if not changed:
        return pattern_map
    out = dict(pattern_map)
    out[meta_key] = out_meta
    return out


def _get_pattern_value(tag: str, pattern: dict):
    for k, v in pattern.items():
        if k == tag:
            return k, v
        a = anchor.parse(k)
        if a is not None and a.key == tag:
            return k, v
    return '', None


def _get_string_map(tag: str, data: Any):
    if not isinstance(data, dict):
        return '', None
    k, v = _get_pattern_value(tag, data)
    if not isinstance(v, dict):
        return '', None
    return k, {str(kk): str(vv) for kk, vv in v.items()}


def _type_name(v: Any) -> str:
    if v is None:
        return 'nil'
    return type(v).__name__


def _fmt(v: Any) -> str:
    if v is None:
        return '<nil>'
    if isinstance(v, bool):
        return 'true' if v else 'false'
    return str(v)
