"""JMESPath tree interpreter and built-in (spec) functions.

Semantics follow the JMESPath specification; behavioral quirks follow
go-jmespath where they differ, since that is what the reference engine uses
(reference: pkg/engine/jmespath/new.go).
"""

from __future__ import annotations

import json
import math
from typing import Any, Callable, Dict, List, Optional

from .errors import (ArityError, FunctionError, JMESPathTypeError,
                     UnknownFunctionError)


class _NotFound:
    """Sentinel distinguishing a missing field from an explicit null.

    The reference's jmespath dependency is the kyverno/go-jmespath fork
    (reference: go.mod:342) whose Search returns NotFoundError when the
    expression resolves to a missing field — engine code branches on it
    (e.g. pkg/engine/variables/vars.go:395). The sentinel propagates
    through the tree like null and is converted to NotFoundError at the
    public search() boundary.
    """

    __slots__ = ()

    def __repr__(self):
        return '<not-found>'

    def __bool__(self):
        return False


NOT_FOUND = _NotFound()


def _defined(value: Any) -> Any:
    """Normalize NOT_FOUND to None for contexts that treat both as null."""
    return None if value is NOT_FOUND else value


def is_false(value: Any) -> bool:
    """JMESPath falsiness: null, empty string/array/object, and false."""
    value = _defined(value)
    return (value is None or value is False or value == '' or
            (isinstance(value, (list, dict)) and len(value) == 0))


def is_truthy(value: Any) -> bool:
    return not is_false(value)


def jp_type(value: Any) -> str:
    if value is None:
        return 'null'
    if isinstance(value, bool):
        return 'boolean'
    if isinstance(value, str):
        return 'string'
    if isinstance(value, (int, float)):
        return 'number'
    if isinstance(value, list):
        return 'array'
    if isinstance(value, dict):
        return 'object'
    if isinstance(value, ExprRef):
        return 'expref'
    return 'unknown'


def deep_equal(a: Any, b: Any) -> bool:
    """Deep equality that, unlike Python ==, distinguishes bools from numbers."""
    if isinstance(a, bool) != isinstance(b, bool):
        return False
    if isinstance(a, dict) and isinstance(b, dict):
        if a.keys() != b.keys():
            return False
        return all(deep_equal(a[k], b[k]) for k in a)
    if isinstance(a, list) and isinstance(b, list):
        return len(a) == len(b) and all(deep_equal(x, y) for x, y in zip(a, b))
    return a == b


class ExprRef:
    """A reference to an unevaluated expression (&expr)."""

    __slots__ = ('node', 'interpreter')

    def __init__(self, node: Dict, interpreter: 'TreeInterpreter'):
        self.node = node
        self.interpreter = interpreter

    def visit(self, value: Any) -> Any:
        return _defined(self.interpreter.visit(self.node, value))


class FunctionRegistry:
    """Holds function signatures + handlers; shared by builtins and the
    Kyverno custom set (reference: pkg/engine/jmespath/functions.go:118)."""

    def __init__(self):
        self._functions: Dict[str, Dict] = {}

    def register(self, name: str, signature: List[Dict],
                 handler: Callable, variadic: bool = False):
        self._functions[name] = {
            'signature': signature,
            'handler': handler,
            'variadic': variadic,
        }

    def names(self) -> List[str]:
        return sorted(self._functions)

    def call(self, interpreter: 'TreeInterpreter', name: str,
             args: List[Any]) -> Any:
        entry = self._functions.get(name)
        if entry is None:
            raise UnknownFunctionError(f'unknown function: {name}()')
        sig = entry['signature']
        if entry['variadic']:
            if len(args) < len(sig):
                raise ArityError(
                    f'{name}() takes at least {len(sig)} arguments, '
                    f'got {len(args)}')
            specs = sig + [sig[-1]] * (len(args) - len(sig))
        else:
            if len(args) != len(sig):
                raise ArityError(
                    f'{name}() takes {len(sig)} arguments, got {len(args)}')
            specs = sig
        for i, (spec, arg) in enumerate(zip(specs, args)):
            types = spec.get('types')
            if not types or 'any' in types:
                continue
            if not _type_matches(arg, types):
                raise JMESPathTypeError(name, arg, jp_type(arg), types)
        return entry['handler'](interpreter, args)


def _type_matches(arg: Any, types: List[str]) -> bool:
    t = jp_type(arg)
    for expected in types:
        if expected == t:
            return True
        if expected == 'array-string' and t == 'array' and \
                all(isinstance(x, str) for x in arg):
            return True
        if expected == 'array-number' and t == 'array' and \
                all(isinstance(x, (int, float)) and not isinstance(x, bool)
                    for x in arg):
            return True
    return False


class TreeInterpreter:
    COMPARATOR_FUNC = {
        'eq': lambda a, b: deep_equal(a, b),
        'ne': lambda a, b: not deep_equal(a, b),
    }

    def __init__(self, functions: FunctionRegistry):
        self.functions = functions

    def visit(self, node: Dict, value: Any) -> Any:
        method = getattr(self, '_visit_' + node['type'])
        return method(node, value)

    # -- leaf nodes ----------------------------------------------------------

    def _visit_literal(self, node, value):
        return node['value']

    def _visit_identity(self, node, value):
        return value

    def _visit_current(self, node, value):
        return value

    def _visit_field(self, node, value):
        if isinstance(value, dict):
            return value.get(node['value'], NOT_FOUND)
        return NOT_FOUND if value is NOT_FOUND else None

    # -- structural ----------------------------------------------------------

    def _visit_subexpression(self, node, value):
        result = value
        for child in node['children']:
            result = self.visit(child, result)
        return result

    def _visit_index(self, node, value):
        if not isinstance(value, list):
            return NOT_FOUND if value is NOT_FOUND else None
        idx = node['value']
        try:
            return value[idx]
        except IndexError:
            return None

    def _visit_slice(self, node, value):
        if not isinstance(value, list):
            return NOT_FOUND if value is NOT_FOUND else None
        start, stop, step = node['value']
        if step == 0:
            raise FunctionError('slice step cannot be 0')
        return value[slice(start, stop, step)]

    def _visit_index_expression(self, node, value):
        result = value
        for child in node['children']:
            result = self.visit(child, result)
        return result

    def _visit_projection(self, node, value):
        base = self.visit(node['children'][0], value)
        if not isinstance(base, list):
            return NOT_FOUND if base is NOT_FOUND else None
        collected = []
        for element in base:
            current = _defined(self.visit(node['children'][1], element))
            if current is not None:
                collected.append(current)
        return collected

    def _visit_value_projection(self, node, value):
        base = self.visit(node['children'][0], value)
        if not isinstance(base, dict):
            return NOT_FOUND if base is NOT_FOUND else None
        collected = []
        for element in base.values():
            current = _defined(self.visit(node['children'][1], element))
            if current is not None:
                collected.append(current)
        return collected

    def _visit_flatten(self, node, value):
        base = self.visit(node['children'][0], value)
        if not isinstance(base, list):
            return NOT_FOUND if base is NOT_FOUND else None
        merged = []
        for element in base:
            if isinstance(element, list):
                merged.extend(element)
            else:
                merged.append(element)
        return merged

    def _visit_filter_projection(self, node, value):
        base = self.visit(node['children'][0], value)
        if not isinstance(base, list):
            return NOT_FOUND if base is NOT_FOUND else None
        comparator = node['children'][2]
        collected = []
        for element in base:
            if is_truthy(self.visit(comparator, element)):
                current = _defined(self.visit(node['children'][1], element))
                if current is not None:
                    collected.append(current)
        return collected

    # -- operators -----------------------------------------------------------

    def _visit_comparator(self, node, value):
        op = node['value']
        left = _defined(self.visit(node['children'][0], value))
        right = _defined(self.visit(node['children'][1], value))
        if op in self.COMPARATOR_FUNC:
            return self.COMPARATOR_FUNC[op](left, right)
        # ordering operators are only valid for numbers
        if not _is_number(left) or not _is_number(right):
            return None
        if op == 'lt':
            return left < right
        if op == 'gt':
            return left > right
        if op == 'lte':
            return left <= right
        if op == 'gte':
            return left >= right
        raise FunctionError(f'unknown comparator {op}')

    def _visit_or_expression(self, node, value):
        matched = self.visit(node['children'][0], value)
        if is_false(matched):
            matched = self.visit(node['children'][1], value)
        return matched

    def _visit_and_expression(self, node, value):
        matched = self.visit(node['children'][0], value)
        if is_false(matched):
            return matched
        return self.visit(node['children'][1], value)

    def _visit_not_expression(self, node, value):
        return is_false(self.visit(node['children'][0], value))

    def _visit_pipe(self, node, value):
        result = self.visit(node['children'][0], value)
        return self.visit(node['children'][1], result)

    # -- multiselect ---------------------------------------------------------

    def _visit_multi_select_list(self, node, value):
        if _defined(value) is None:
            return None
        return [_defined(self.visit(child, value))
                for child in node['children']]

    def _visit_multi_select_dict(self, node, value):
        if _defined(value) is None:
            return None
        return {child['value']: _defined(self.visit(child['children'][0], value))
                for child in node['children']}

    # -- functions -----------------------------------------------------------

    def _visit_function_expression(self, node, value):
        args = [_defined(self.visit(child, value))
                for child in node['children']]
        return self.functions.call(self, node['value'], args)

    def _visit_expref(self, node, value):
        return ExprRef(node['children'][0], self)


def _is_number(v: Any) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool)


# ---------------------------------------------------------------------------
# Spec built-in functions
# ---------------------------------------------------------------------------

def _require_number_array(name, arr):
    for x in arr:
        if not _is_number(x):
            raise JMESPathTypeError(name, x, jp_type(x), ['number'])


def _fn_abs(ip, args):
    return abs(args[0])


def _fn_avg(ip, args):
    arr = args[0]
    _require_number_array('avg', arr)
    if not arr:
        return None
    return sum(arr) / len(arr)


def _fn_ceil(ip, args):
    return int(math.ceil(args[0]))


def _fn_floor(ip, args):
    return int(math.floor(args[0]))


def _fn_contains(ip, args):
    subject, search = args
    if isinstance(subject, str):
        if not isinstance(search, str):
            return False
        return search in subject
    return any(deep_equal(x, search) for x in subject)


def _fn_ends_with(ip, args):
    return args[0].endswith(args[1])


def _fn_starts_with(ip, args):
    return args[0].startswith(args[1])


def _fn_join(ip, args):
    return args[0].join(args[1])


def _fn_keys(ip, args):
    return list(args[0].keys())


def _fn_values(ip, args):
    return list(args[0].values())


def _fn_length(ip, args):
    return len(args[0])


def _fn_map(ip, args):
    expref, arr = args
    return [expref.visit(x) for x in arr]


def _fn_max(ip, args):
    arr = args[0]
    if not arr:
        return None
    _require_uniform_sortable('max', arr)
    return max(arr)


def _fn_min(ip, args):
    arr = args[0]
    if not arr:
        return None
    _require_uniform_sortable('min', arr)
    return min(arr)


def _require_uniform_sortable(name, arr):
    if all(isinstance(x, str) for x in arr):
        return
    if all(_is_number(x) for x in arr):
        return
    raise JMESPathTypeError(name, arr, 'array',
                            ['array-number', 'array-string'])


def _sort_keys(name, expref, arr):
    """Evaluate sort keys for every element, requiring a uniform
    all-string or all-number key set (like go-jmespath)."""
    keys = []
    for element in arr:
        result = expref.visit(element)
        if not (isinstance(result, str) or _is_number(result)):
            raise JMESPathTypeError(name, result, jp_type(result),
                                    ['number', 'string'])
        keys.append(result)
    if not (all(isinstance(k, str) for k in keys) or
            all(_is_number(k) for k in keys)):
        raise JMESPathTypeError(name, keys, 'array',
                                ['array-number', 'array-string'])
    return keys


def _fn_max_by(ip, args):
    arr, expref = args
    if not arr:
        return None
    keys = _sort_keys('max_by', expref, arr)
    return arr[max(range(len(arr)), key=lambda i: keys[i])]


def _fn_min_by(ip, args):
    arr, expref = args
    if not arr:
        return None
    keys = _sort_keys('min_by', expref, arr)
    return arr[min(range(len(arr)), key=lambda i: keys[i])]


def _fn_sort(ip, args):
    arr = args[0]
    _require_uniform_sortable('sort', arr)
    return sorted(arr)


def _fn_sort_by(ip, args):
    arr, expref = args
    if not arr:
        return list(arr)
    keys = _sort_keys('sort_by', expref, arr)
    order = sorted(range(len(arr)), key=lambda i: keys[i])
    return [arr[i] for i in order]


def _fn_merge(ip, args):
    merged = {}
    for obj in args:
        merged.update(obj)
    return merged


def _fn_not_null(ip, args):
    for arg in args:
        if arg is not None:
            return arg
    return None


def _fn_reverse(ip, args):
    v = args[0]
    if isinstance(v, str):
        return v[::-1]
    return list(reversed(v))


def _fn_sum(ip, args):
    arr = args[0]
    _require_number_array('sum', arr)
    return sum(arr)


def _fn_to_array(ip, args):
    v = args[0]
    if isinstance(v, list):
        return v
    return [v]


def _fn_to_string(ip, args):
    v = args[0]
    if isinstance(v, str):
        return v
    return json.dumps(v, separators=(',', ':'), ensure_ascii=False)


def _fn_to_number(ip, args):
    v = args[0]
    if isinstance(v, (int, float)) and not isinstance(v, bool):
        return v
    if isinstance(v, str):
        try:
            if '.' in v or 'e' in v or 'E' in v:
                return float(v)
            return int(v)
        except ValueError:
            return None
    return None


def _fn_type(ip, args):
    return jp_type(args[0])


def make_builtin_registry() -> FunctionRegistry:
    r = FunctionRegistry()
    S = lambda *types: {'types': list(types)}  # noqa: E731
    r.register('abs', [S('number')], _fn_abs)
    r.register('avg', [S('array')], _fn_avg)
    r.register('ceil', [S('number')], _fn_ceil)
    r.register('contains', [S('array', 'string'), S('any')], _fn_contains)
    r.register('ends_with', [S('string'), S('string')], _fn_ends_with)
    r.register('floor', [S('number')], _fn_floor)
    r.register('join', [S('string'), S('array-string')], _fn_join)
    r.register('keys', [S('object')], _fn_keys)
    r.register('length', [S('string', 'array', 'object')], _fn_length)
    r.register('map', [S('expref'), S('array')], _fn_map)
    r.register('max', [S('array')], _fn_max)
    r.register('max_by', [S('array'), S('expref')], _fn_max_by)
    r.register('merge', [S('object')], _fn_merge, variadic=True)
    r.register('min', [S('array')], _fn_min)
    r.register('min_by', [S('array'), S('expref')], _fn_min_by)
    r.register('not_null', [S('any')], _fn_not_null, variadic=True)
    r.register('reverse', [S('string', 'array')], _fn_reverse)
    r.register('sort', [S('array')], _fn_sort)
    r.register('sort_by', [S('array'), S('expref')], _fn_sort_by)
    r.register('starts_with', [S('string'), S('string')], _fn_starts_with)
    r.register('sum', [S('array')], _fn_sum)
    r.register('to_array', [S('any')], _fn_to_array)
    r.register('to_number', [S('any')], _fn_to_number)
    r.register('to_string', [S('any')], _fn_to_string)
    r.register('type', [S('any')], _fn_type)
    r.register('values', [S('object')], _fn_values)
    return r
