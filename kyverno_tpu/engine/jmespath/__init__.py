"""JMESPath engine: spec-conformant implementation + Kyverno custom functions.

Public API mirrors the usual jmespath module shape:

    from kyverno_tpu.engine import jmespath as jp
    jp.search('a.b[0]', {'a': {'b': [1, 2]}})      # -> 1
    expr = jp.compile('items(@, `"k"`, `"v"`)')
    expr.search({'x': 1})

The reference delegates to github.com/jmespath/go-jmespath plus 41 custom
functions (reference: pkg/engine/jmespath/new.go:7); here the whole language
is implemented natively so policies can also be *compiled* (see
kyverno_tpu/compiler) rather than only interpreted.
"""

from __future__ import annotations

from functools import lru_cache
from typing import Any

from .custom import register_custom_functions
from .errors import (ArityError, FunctionError, IncompleteExpressionError,
                     JMESPathError, JMESPathTypeError, LexerError,
                     NotFoundError, ParseError, UnknownFunctionError)
from .interpreter import (NOT_FOUND, FunctionRegistry, TreeInterpreter,
                          make_builtin_registry)
from .parser import parse as parse_ast

__all__ = [
    'compile', 'search', 'parse_ast', 'JMESPathError', 'LexerError',
    'ParseError', 'IncompleteExpressionError', 'ArityError',
    'JMESPathTypeError', 'UnknownFunctionError', 'FunctionError',
    'NotFoundError',
]

_REGISTRY = register_custom_functions(make_builtin_registry())
_INTERPRETER = TreeInterpreter(_REGISTRY)


class CompiledExpression:
    __slots__ = ('expression', 'ast', '_fn')

    def __init__(self, expression: str, ast: dict):
        self.expression = expression
        self.ast = ast
        self._fn = None

    def search(self, data: Any) -> Any:
        fn = self._fn
        if fn is None:
            # lower to closures on first use (closures.py); unsupported
            # nodes fall back to the tree interpreter permanently
            from .closures import UnsupportedNode, compile_closure
            try:
                fn = compile_closure(self.ast, _INTERPRETER)
            except UnsupportedNode:
                fn = lambda value: _INTERPRETER.visit(self.ast, value)  # noqa: E731
            self._fn = fn
        result = fn(data)
        if result is NOT_FOUND:
            raise NotFoundError(f'Unknown key "{self.expression}" in path')
        return result


@lru_cache(maxsize=16384)
def compile(expression: str) -> CompiledExpression:  # noqa: A001
    return CompiledExpression(expression, parse_ast(expression))


def search(expression: str, data: Any) -> Any:
    return compile(expression).search(data)


def function_names():
    return _REGISTRY.names()
