"""JMESPath lexer (spec-conformant, https://jmespath.org/specification.html).

Produces the token stream consumed by ``parser.py``.  Built from scratch for
this framework; the reference engine delegates to github.com/jmespath/go-jmespath
(reference: pkg/engine/jmespath/new.go:7).
"""

from __future__ import annotations

import json
import string
from typing import Iterator, NamedTuple

from .errors import LexerError


class Token(NamedTuple):
    type: str
    value: object
    start: int
    end: int


START_IDENT = set(string.ascii_letters + '_')
VALID_IDENT = set(string.ascii_letters + string.digits + '_')
DIGITS = set(string.digits)
WHITESPACE = set(' \t\n\r')

SIMPLE_TOKENS = {
    '.': 'dot',
    '*': 'star',
    ']': 'rbracket',
    ',': 'comma',
    ':': 'colon',
    '@': 'current',
    '(': 'lparen',
    ')': 'rparen',
    '{': 'lbrace',
    '}': 'rbrace',
}


def tokenize(expression: str) -> Iterator[Token]:
    if not expression:
        raise LexerError(0, '', 'empty expression')
    pos = 0
    chars = expression
    length = len(expression)
    while pos < length:
        ch = chars[pos]
        if ch in SIMPLE_TOKENS:
            yield Token(SIMPLE_TOKENS[ch], ch, pos, pos + 1)
            pos += 1
        elif ch in START_IDENT:
            start = pos
            pos += 1
            while pos < length and chars[pos] in VALID_IDENT:
                pos += 1
            yield Token('unquoted_identifier', chars[start:pos], start, pos)
        elif ch in WHITESPACE:
            pos += 1
        elif ch == '[':
            if pos + 1 < length and chars[pos + 1] == ']':
                yield Token('flatten', '[]', pos, pos + 2)
                pos += 2
            elif pos + 1 < length and chars[pos + 1] == '?':
                yield Token('filter', '[?', pos, pos + 2)
                pos += 2
            else:
                yield Token('lbracket', '[', pos, pos + 1)
                pos += 1
        elif ch == "'":
            start = pos
            pos += 1
            buf = []
            while pos < length and chars[pos] != "'":
                if chars[pos] == '\\' and pos + 1 < length and chars[pos + 1] in ("'", '\\'):
                    buf.append(chars[pos + 1])
                    pos += 2
                else:
                    buf.append(chars[pos])
                    pos += 1
            if pos >= length:
                raise LexerError(start, chars[start:], 'unclosed raw string')
            pos += 1
            yield Token('literal', ''.join(buf), start, pos)
        elif ch == '|':
            if pos + 1 < length and chars[pos + 1] == '|':
                yield Token('or', '||', pos, pos + 2)
                pos += 2
            else:
                yield Token('pipe', '|', pos, pos + 1)
                pos += 1
        elif ch == '&':
            if pos + 1 < length and chars[pos + 1] == '&':
                yield Token('and', '&&', pos, pos + 2)
                pos += 2
            else:
                yield Token('expref', '&', pos, pos + 1)
                pos += 1
        elif ch == '`':
            start = pos
            pos += 1
            buf = []
            while pos < length and chars[pos] != '`':
                if chars[pos] == '\\' and pos + 1 < length and chars[pos + 1] == '`':
                    buf.append('`')
                    pos += 2
                else:
                    buf.append(chars[pos])
                    pos += 1
            if pos >= length:
                raise LexerError(start, chars[start:], 'unclosed backtick literal')
            pos += 1
            raw = ''.join(buf)
            try:
                parsed = json.loads(raw)
            except ValueError:
                try:
                    # legacy: bare words inside backticks are strings
                    parsed = json.loads('"%s"' % raw.strip())
                except ValueError:
                    raise LexerError(start, raw, 'bad token %s' % raw) from None
            yield Token('literal', parsed, start, pos)
        elif ch == '"':
            start = pos
            pos += 1
            buf = []
            while pos < length and chars[pos] != '"':
                if chars[pos] == '\\' and pos + 1 < length:
                    buf.append(chars[pos])
                    buf.append(chars[pos + 1])
                    pos += 2
                else:
                    buf.append(chars[pos])
                    pos += 1
            if pos >= length:
                raise LexerError(start, chars[start:], 'unclosed quoted identifier')
            pos += 1
            raw = ''.join(buf)
            try:
                parsed = json.loads('"%s"' % raw)
            except ValueError:
                raise LexerError(start, raw, 'invalid quoted identifier') from None
            yield Token('quoted_identifier', parsed, start, pos)
        elif ch in DIGITS:
            start = pos
            while pos < length and chars[pos] in DIGITS:
                pos += 1
            yield Token('number', int(chars[start:pos]), start, pos)
        elif ch == '-':
            start = pos
            pos += 1
            if pos >= length or chars[pos] not in DIGITS:
                raise LexerError(start, ch, "unknown token '-'")
            while pos < length and chars[pos] in DIGITS:
                pos += 1
            yield Token('number', int(chars[start:pos]), start, pos)
        elif ch == '<':
            if pos + 1 < length and chars[pos + 1] == '=':
                yield Token('lte', '<=', pos, pos + 2)
                pos += 2
            else:
                yield Token('lt', '<', pos, pos + 1)
                pos += 1
        elif ch == '>':
            if pos + 1 < length and chars[pos + 1] == '=':
                yield Token('gte', '>=', pos, pos + 2)
                pos += 2
            else:
                yield Token('gt', '>', pos, pos + 1)
                pos += 1
        elif ch == '=':
            if pos + 1 < length and chars[pos + 1] == '=':
                yield Token('eq', '==', pos, pos + 2)
                pos += 2
            else:
                raise LexerError(pos, '=', "unknown token '=' (did you mean '=='?)")
        elif ch == '!':
            if pos + 1 < length and chars[pos + 1] == '=':
                yield Token('ne', '!=', pos, pos + 2)
                pos += 2
            else:
                yield Token('not', '!', pos, pos + 1)
                pos += 1
        else:
            raise LexerError(pos, ch, 'unknown token %r' % ch)
    yield Token('eof', '', length, length)
