"""JMESPath Pratt parser producing a dict-based AST.

AST node shape: ``{'type': <str>, 'children': [<node>...], 'value': <any>}``.
Node types: field, subexpression, index, slice, index_expression, projection,
value_projection, flatten, filter_projection, comparator, or_expression,
and_expression, not_expression, pipe, multi_select_list, multi_select_dict,
key_val_pair, function_expression, expref, literal, identity, current.
"""

from __future__ import annotations

from typing import Any, Dict, List

from .errors import IncompleteExpressionError, ParseError
from .lexer import tokenize

BINDING_POWER = {
    'eof': 0,
    'unquoted_identifier': 0,
    'quoted_identifier': 0,
    'literal': 0,
    'rbracket': 0,
    'rparen': 0,
    'comma': 0,
    'rbrace': 0,
    'number': 0,
    'current': 0,
    'expref': 0,
    'colon': 0,
    'pipe': 1,
    'or': 2,
    'and': 3,
    'eq': 5,
    'gt': 5,
    'lt': 5,
    'gte': 5,
    'lte': 5,
    'ne': 5,
    'flatten': 9,
    'star': 20,
    'filter': 21,
    'dot': 40,
    'not': 45,
    'lbrace': 50,
    'lbracket': 55,
    'lparen': 60,
}

_PROJECTION_STOP = 10
_COMPARATOR_TOKENS = ('eq', 'ne', 'lt', 'gt', 'lte', 'gte')


def _node(type_: str, children: List = None, value: Any = None) -> Dict:
    return {'type': type_, 'children': children or [], 'value': value}


class Parser:
    def __init__(self, expression: str):
        self.expression = expression
        self.tokens = list(tokenize(expression))
        self.index = 0

    # -- token stream helpers -------------------------------------------------

    @property
    def current(self):
        return self.tokens[self.index]

    def advance(self):
        self.index += 1

    def expect(self, token_type: str):
        tok = self.current
        if tok.type != token_type:
            if tok.type == 'eof':
                raise IncompleteExpressionError(tok.start, tok.value, tok.type)
            raise ParseError(tok.start, tok.value, tok.type,
                             f'expected {token_type}')
        self.advance()
        return tok

    # -- entry ---------------------------------------------------------------

    def parse(self) -> Dict:
        parsed = self._expression(0)
        if self.current.type != 'eof':
            tok = self.current
            raise ParseError(tok.start, tok.value, tok.type,
                             'unexpected token after expression')
        return parsed

    def _expression(self, binding_power: int) -> Dict:
        left_token = self.current
        self.advance()
        left = self._nud(left_token)
        while binding_power < BINDING_POWER[self.current.type]:
            tok = self.current
            self.advance()
            left = self._led(tok, left)
        return left

    # -- prefix (nud) --------------------------------------------------------

    def _nud(self, token) -> Dict:
        t = token.type
        if t == 'literal':
            return _node('literal', value=token.value)
        if t == 'unquoted_identifier':
            return _node('field', value=token.value)
        if t == 'quoted_identifier':
            if self.current.type == 'lparen':
                raise ParseError(token.start, token.value, token.type,
                                 'quoted identifiers cannot be function names')
            return _node('field', value=token.value)
        if t == 'star':
            left = _node('identity')
            if self.current.type == 'rbracket':
                right = _node('identity')
            else:
                right = self._parse_projection_rhs(BINDING_POWER['star'])
            return _node('value_projection', [left, right])
        if t == 'filter':
            return self._parse_filter(_node('identity'))
        if t == 'lbrace':
            return self._parse_multi_select_hash()
        if t == 'lparen':
            expr = self._expression(0)
            self.expect('rparen')
            return expr
        if t == 'flatten':
            left = _node('flatten', [_node('identity')])
            right = self._parse_projection_rhs(BINDING_POWER['flatten'])
            return _node('projection', [left, right])
        if t == 'not':
            return _node('not_expression', [self._expression(BINDING_POWER['not'])])
        if t == 'lbracket':
            if self.current.type in ('number', 'colon'):
                right = self._parse_index_expression()
                return self._project_if_slice(_node('identity'), right)
            if self.current.type == 'star' and \
                    self.tokens[self.index + 1].type == 'rbracket':
                self.advance()
                self.advance()
                right = self._parse_projection_rhs(BINDING_POWER['star'])
                return _node('projection', [_node('identity'), right])
            return self._parse_multi_select_list()
        if t == 'current':
            return _node('current')
        if t == 'expref':
            return _node('expref', [self._expression(BINDING_POWER['expref'])])
        if t == 'eof':
            raise IncompleteExpressionError(token.start, token.value, token.type)
        raise ParseError(token.start, token.value, token.type)

    # -- infix (led) ---------------------------------------------------------

    def _led(self, token, left: Dict) -> Dict:
        t = token.type
        if t == 'dot':
            if self.current.type != 'star':
                right = self._parse_dot_rhs(BINDING_POWER['dot'])
                if left['type'] == 'subexpression':
                    left['children'].append(right)
                    return left
                return _node('subexpression', [left, right])
            # creates a value projection
            self.advance()
            right = self._parse_projection_rhs(BINDING_POWER['star'])
            return _node('value_projection', [left, right])
        if t == 'pipe':
            right = self._expression(BINDING_POWER['pipe'])
            return _node('pipe', [left, right])
        if t == 'or':
            right = self._expression(BINDING_POWER['or'])
            return _node('or_expression', [left, right])
        if t == 'and':
            right = self._expression(BINDING_POWER['and'])
            return _node('and_expression', [left, right])
        if t == 'lparen':
            if left['type'] != 'field':
                prev = self.tokens[self.index - 2]
                raise ParseError(prev.start, prev.value, prev.type,
                                 'invalid function name')
            name = left['value']
            args = []
            if self.current.type != 'rparen':
                args.append(self._expression(0))
                while self.current.type == 'comma':
                    self.advance()
                    args.append(self._expression(0))
            self.expect('rparen')
            return _node('function_expression', args, value=name)
        if t == 'filter':
            return self._parse_filter(left)
        if t in _COMPARATOR_TOKENS:
            right = self._expression(BINDING_POWER[t])
            return _node('comparator', [left, right], value=t)
        if t == 'flatten':
            new_left = _node('flatten', [left])
            right = self._parse_projection_rhs(BINDING_POWER['flatten'])
            return _node('projection', [new_left, right])
        if t == 'lbracket':
            if self.current.type in ('number', 'colon'):
                right = self._parse_index_expression()
                if left['type'] == 'index_expression' and right['type'] == 'index':
                    left['children'].append(right)
                    return left
                return self._project_if_slice(left, right)
            self.expect('star')
            self.expect('rbracket')
            right = self._parse_projection_rhs(BINDING_POWER['star'])
            return _node('projection', [left, right])
        raise ParseError(token.start, token.value, token.type)

    # -- helpers -------------------------------------------------------------

    def _parse_index_expression(self) -> Dict:
        # either a slice or an index
        if self.current.type == 'colon' or \
                self.tokens[self.index + 1].type == 'colon':
            return self._parse_slice_expression()
        node = _node('index', value=self.current.value)
        self.advance()
        self.expect('rbracket')
        return node

    def _parse_slice_expression(self) -> Dict:
        parts = [None, None, None]
        index = 0
        while self.current.type != 'rbracket' and index < 3:
            if self.current.type == 'colon':
                index += 1
                if index == 3:
                    tok = self.current
                    raise ParseError(tok.start, tok.value, tok.type,
                                     'too many colons in slice')
                self.advance()
            elif self.current.type == 'number':
                parts[index] = self.current.value
                self.advance()
            else:
                tok = self.current
                raise ParseError(tok.start, tok.value, tok.type,
                                 'invalid slice expression')
        self.expect('rbracket')
        return _node('slice', value=tuple(parts))

    def _project_if_slice(self, left: Dict, right: Dict) -> Dict:
        index_expr = _node('index_expression', [left, right])
        if right['type'] == 'slice':
            rhs = self._parse_projection_rhs(BINDING_POWER['star'])
            return _node('projection', [index_expr, rhs])
        return index_expr

    def _parse_filter(self, left: Dict) -> Dict:
        condition = self._expression(0)
        self.expect('rbracket')
        if self.current.type == 'flatten':
            right = _node('identity')
        else:
            right = self._parse_projection_rhs(BINDING_POWER['filter'])
        return _node('filter_projection', [left, right, condition])

    def _parse_multi_select_list(self) -> Dict:
        expressions = []
        while True:
            expressions.append(self._expression(0))
            if self.current.type == 'rbracket':
                break
            self.expect('comma')
        self.expect('rbracket')
        return _node('multi_select_list', expressions)

    def _parse_multi_select_hash(self) -> Dict:
        pairs = []
        while True:
            key_token = self.current
            if key_token.type not in ('quoted_identifier', 'unquoted_identifier'):
                raise ParseError(key_token.start, key_token.value,
                                 key_token.type, 'invalid key in multi-select hash')
            self.advance()
            self.expect('colon')
            value = self._expression(0)
            pairs.append(_node('key_val_pair', [value], value=key_token.value))
            if self.current.type == 'rbrace':
                break
            self.expect('comma')
        self.expect('rbrace')
        return _node('multi_select_dict', pairs)

    def _parse_projection_rhs(self, binding_power: int) -> Dict:
        t = self.current.type
        if BINDING_POWER[t] < _PROJECTION_STOP:
            return _node('identity')
        if t == 'lbracket':
            return self._expression(binding_power)
        if t == 'filter':
            return self._expression(binding_power)
        if t == 'dot':
            self.advance()
            return self._parse_dot_rhs(binding_power)
        tok = self.current
        raise ParseError(tok.start, tok.value, tok.type,
                         'invalid projection right-hand side')

    def _parse_dot_rhs(self, binding_power: int) -> Dict:
        t = self.current.type
        if t in ('unquoted_identifier', 'quoted_identifier', 'star'):
            return self._expression(binding_power)
        if t == 'lbracket':
            self.advance()
            return self._parse_multi_select_list()
        if t == 'lbrace':
            self.advance()
            return self._parse_multi_select_hash()
        tok = self.current
        raise ParseError(tok.start, tok.value, tok.type,
                         'invalid token after dot')


def parse(expression: str) -> Dict:
    return Parser(expression).parse()
