"""Kyverno custom JMESPath functions.

Re-implements the 41 custom functions the reference registers on top of
go-jmespath (reference: pkg/engine/jmespath/functions.go:53-81 and time.go).
Function-by-function semantics follow the Go handlers; arithmetic operand
typing (scalar/quantity/duration) follows pkg/engine/jmespath/arithmetic.go.
"""

from __future__ import annotations

import base64
import binascii
import datetime
import json
import math
import posixpath
import random as _random
import re
from fractions import Fraction
from typing import Any, List, Optional, Tuple

import yaml

from ...utils import wildcard
from ...utils.duration import DurationError, format_duration, parse_duration
from ...utils.quantity import Quantity
from .errors import FunctionError
from .interpreter import FunctionRegistry, jp_type


def _err(fname: str, msg: str) -> FunctionError:
    return FunctionError(f"JMESPath function '{fname}': {msg}")


def _arg_str(fname: str, args, i) -> str:
    v = args[i]
    if not isinstance(v, str):
        raise _err(fname, f'{i + 1} argument is expected of string type')
    return v


def _arg_num(fname: str, args, i) -> float:
    v = args[i]
    if isinstance(v, bool) or not isinstance(v, (int, float)):
        raise _err(fname, f'{i + 1} argument is expected of number type')
    return v


def _iface_to_string(v: Any) -> str:
    """reference: pkg/engine/jmespath/functions.go:1060 ifaceToString"""
    if isinstance(v, bool):
        return 'true' if v else 'false'
    if isinstance(v, int):
        return str(v)
    if isinstance(v, float):
        # Go strconv.FormatFloat(i, 'f', -1, 32)
        if v == int(v) and abs(v) < 1e15:
            return str(int(v))
        return repr(v)
    if isinstance(v, str):
        return v
    raise FunctionError('error, undefined type cast')


# -- string functions --------------------------------------------------------

def _fn_compare(ip, args):
    a = _arg_str('compare', args, 0)
    b = _arg_str('compare', args, 1)
    return (a > b) - (a < b)


def _fn_equal_fold(ip, args):
    a = _arg_str('equal_fold', args, 0)
    b = _arg_str('equal_fold', args, 1)
    return a.casefold() == b.casefold()


def _fn_replace(ip, args):
    s = _arg_str('replace', args, 0)
    old = _arg_str('replace', args, 1)
    new = _arg_str('replace', args, 2)
    n = int(_arg_num('replace', args, 3))
    if n < 0:
        return s.replace(old, new)
    return s.replace(old, new, n)


def _fn_replace_all(ip, args):
    s = _arg_str('replace_all', args, 0)
    return s.replace(_arg_str('replace_all', args, 1),
                     _arg_str('replace_all', args, 2))


def _fn_to_upper(ip, args):
    return _arg_str('to_upper', args, 0).upper()


def _fn_to_lower(ip, args):
    return _arg_str('to_lower', args, 0).lower()


def _fn_trim(ip, args):
    return _arg_str('trim', args, 0).strip(_arg_str('trim', args, 1))


def _fn_split(ip, args):
    s = _arg_str('split', args, 0)
    sep = _arg_str('split', args, 1)
    if sep == '':
        return list(s)  # Go strings.Split splits into characters
    return s.split(sep)


def _fn_path_canonicalize(ip, args):
    # Go filepath.Join(p) == filepath.Clean(p) on a single element (Linux)
    p = _arg_str('path_canonicalize', args, 0)
    if p == '':
        return '.'
    out = posixpath.normpath(p)
    return out


def _fn_truncate(ip, args):
    s = _arg_str('truncate', args, 0)
    length = int(max(0.0, _arg_num('truncate', args, 1)))
    return s[:length]


# -- regex -------------------------------------------------------------------

def _go_template_to_python(repl: str) -> str:
    """Convert a Go regexp replacement template ($1, ${name}) to Python re
    syntax (\\1, \\g<name>)."""
    out = []
    i = 0
    while i < len(repl):
        c = repl[i]
        if c == '\\':
            out.append('\\\\')
            i += 1
        elif c == '$':
            if i + 1 < len(repl) and repl[i + 1] == '$':
                out.append('$')
                i += 2
            elif i + 1 < len(repl) and repl[i + 1] == '{':
                j = repl.find('}', i + 2)
                if j == -1:
                    out.append('$')
                    i += 1
                else:
                    out.append(f'\\g<{repl[i + 2:j]}>')
                    i = j + 1
            else:
                j = i + 1
                while j < len(repl) and (repl[j].isalnum() or repl[j] == '_'):
                    j += 1
                if j == i + 1:
                    out.append('$')
                    i += 1
                else:
                    out.append(f'\\g<{repl[i + 1:j]}>')
                    i = j
        else:
            out.append(c)
            i += 1
    return ''.join(out)


def _fn_regex_replace_all(ip, args):
    pattern = _arg_str('regex_replace_all', args, 0)
    src = _iface_to_string(args[1])
    repl = _iface_to_string(args[2])
    try:
        rx = re.compile(pattern)
    except re.error as e:
        raise _err('regex_replace_all', str(e))
    return rx.sub(_go_template_to_python(repl), src)


def _fn_regex_replace_all_literal(ip, args):
    pattern = _arg_str('regex_replace_all_literal', args, 0)
    src = _iface_to_string(args[1])
    repl = _iface_to_string(args[2])
    try:
        rx = re.compile(pattern)
    except re.error as e:
        raise _err('regex_replace_all_literal', str(e))
    return rx.sub(repl.replace('\\', '\\\\'), src)


def _fn_regex_match(ip, args):
    pattern = _arg_str('regex_match', args, 0)
    src = _iface_to_string(args[1])
    try:
        return re.search(pattern, src) is not None
    except re.error as e:
        raise _err('regex_match', str(e))


def _fn_pattern_match(ip, args):
    pattern = _arg_str('pattern_match', args, 0)
    src = _iface_to_string(args[1])
    return wildcard.match(pattern, src)


def _fn_label_match(ip, args):
    selector, labels = args[0], args[1]
    if not isinstance(selector, dict):
        raise _err('label_match', '1 argument is expected of object type')
    if not isinstance(labels, dict):
        raise _err('label_match', '2 argument is expected of object type')
    for k, v in selector.items():
        if k not in labels or labels[k] != v:
            return False
    return True


# -- arithmetic --------------------------------------------------------------
# Operand model (reference: pkg/engine/jmespath/arithmetic.go):
#   number           -> Scalar
#   string           -> Quantity if parseable, else Duration if parseable
#   mixing Quantity and Duration is an error

_SCALAR, _QUANTITY, _DURATION = 0, 1, 2


def _parse_operand(fname: str, v: Any) -> Tuple[int, Any]:
    if isinstance(v, bool):
        raise _err(fname, 'invalid operands')
    if isinstance(v, (int, float)):
        return _SCALAR, float(v)
    if isinstance(v, str):
        try:
            return _QUANTITY, Quantity.parse(v)
        except ValueError:
            pass
        try:
            return _DURATION, parse_duration(v)
        except DurationError:
            pass
    raise _err(fname, 'invalid operands')


def _parse_operands(fname: str, args) -> Tuple[int, Any, int, Any]:
    t1, v1 = _parse_operand(fname, args[0])
    t2, v2 = _parse_operand(fname, args[1])
    if {t1, t2} == {_QUANTITY, _DURATION}:
        raise _err(fname, 'invalid operands')
    return t1, v1, t2, v2


def _format_quantity(value: Fraction, prefer_binary: bool) -> str:
    """Canonical k8s quantity formatting: largest suffix giving an integer
    mantissa (mirrors resource.Quantity.String() canonicalization)."""
    if value == 0:
        return '0'
    sign = '-' if value < 0 else ''
    v = abs(value)
    if prefer_binary and v.denominator == 1:
        n = v.numerator
        for suffix, mult in (('Ei', 2 ** 60), ('Pi', 2 ** 50), ('Ti', 2 ** 40),
                             ('Gi', 2 ** 30), ('Mi', 2 ** 20), ('Ki', 2 ** 10)):
            if n % mult == 0:
                return f'{sign}{n // mult}{suffix}'
        return f'{sign}{n}'
    # decimal: find the largest power-of-1000 suffix with integer mantissa
    for suffix, exp in (('E', 18), ('P', 15), ('T', 12), ('G', 9), ('M', 6),
                        ('k', 3), ('', 0), ('m', -3), ('u', -6), ('n', -9)):
        scaled = v / Fraction(10) ** exp
        if scaled.denominator == 1:
            return f'{sign}{scaled.numerator}{suffix}'
    # not representable with k8s suffixes: fall back to decimal string
    return f'{sign}{float(v):g}'


def _is_binary(q: Quantity) -> bool:
    return q.suffix in ('Ki', 'Mi', 'Gi', 'Ti', 'Pi', 'Ei')


def _fn_add(ip, args):
    t1, v1, t2, v2 = _parse_operands('add', args)
    if t1 == _QUANTITY and t2 == _QUANTITY:
        return _format_quantity(v1.value + v2.value, _is_binary(v1) or _is_binary(v2))
    if t1 == _DURATION and t2 == _DURATION:
        return format_duration(v1 + v2)
    if t1 == _SCALAR and t2 == _SCALAR:
        return v1 + v2
    raise _err('add', 'types mismatch')


def _fn_subtract(ip, args):
    t1, v1, t2, v2 = _parse_operands('subtract', args)
    if t1 == _QUANTITY and t2 == _QUANTITY:
        return _format_quantity(v1.value - v2.value, _is_binary(v1) or _is_binary(v2))
    if t1 == _DURATION and t2 == _DURATION:
        return format_duration(v1 - v2)
    if t1 == _SCALAR and t2 == _SCALAR:
        return v1 - v2
    raise _err('subtract', 'types mismatch')


def _fn_multiply(ip, args):
    t1, v1, t2, v2 = _parse_operands('multiply', args)
    if t1 == _SCALAR and t2 == _SCALAR:
        return v1 * v2
    if {t1, t2} == {_QUANTITY, _SCALAR}:
        q, s = (v1, v2) if t1 == _QUANTITY else (v2, v1)
        return _format_quantity(q.value * Fraction(str(s)), _is_binary(q))
    if {t1, t2} == {_DURATION, _SCALAR}:
        d, s = (v1, v2) if t1 == _DURATION else (v2, v1)
        seconds = (d / 1e9) * s
        return format_duration(int(seconds * 1e9))
    raise _err('multiply', 'types mismatch')


def _quo_round_down(num: Fraction, den: Fraction, scale: int) -> Fraction:
    """Reference quantity division (arithmetic.go:197 Quantity.Divide):
    inf.Dec QuoRound to ``scale`` (max of the operands' AsDec scales —
    NEGATIVE for decimal-SI suffixes, see Quantity.inf_scale), RoundDown
    (truncation toward zero, the java-style DOWN rounder)."""
    step = Fraction(10) ** scale
    trunc = int(num / den * step)  # Fraction.__int__ truncates toward 0
    return Fraction(trunc) / step


def _fn_divide(ip, args):
    from ...utils.quantity import _fraction_scale
    t1, v1, t2, v2 = _parse_operands('divide', args)
    if t1 == _QUANTITY and t2 == _QUANTITY:
        if v2.value == 0:
            raise _err('divide', 'Zero divisor passed')
        scale = max(v1.inf_scale(), v2.inf_scale())
        return float(_quo_round_down(v1.value, v2.value, scale))
    if t1 == _QUANTITY and t2 == _SCALAR:
        if v2 == 0:
            raise _err('divide', 'Zero divisor passed')
        # the reference reparses the scalar as a quantity ('%v' of the
        # float), whose scale is its decimal-digit count
        f2 = Fraction(str(v2))
        scale = max(v1.inf_scale(), _fraction_scale(f2))
        return _format_quantity(
            _quo_round_down(v1.value, f2, scale), _is_binary(v1))
    if t1 == _DURATION and t2 == _DURATION:
        if v2 == 0:
            raise _err('divide', 'Undefined quotient')
        return (v1 / 1e9) / (v2 / 1e9)
    if t1 == _DURATION and t2 == _SCALAR:
        if v2 == 0:
            raise _err('divide', 'Undefined quotient')
        seconds = (v1 / 1e9) / v2
        return format_duration(int(seconds * 1e9))
    if t1 == _SCALAR and t2 == _SCALAR:
        if v2 == 0:
            raise _err('divide', 'Zero divisor passed')
        return v1 / v2
    raise _err('divide', 'types mismatch')


def _fn_modulo(ip, args):
    t1, v1, t2, v2 = _parse_operands('modulo', args)
    if t1 == _QUANTITY and t2 == _QUANTITY:
        if v1.value.denominator != 1 or v2.value.denominator != 1:
            raise _err('modulo', 'Non-integer argument(s) passed for modulo')
        if v2.value == 0:
            raise _err('modulo', 'Zero divisor passed')
        q = _trunc_mod(int(v1.value), int(v2.value))
        return _format_quantity(Fraction(q), _is_binary(v1) or _is_binary(v2))
    if t1 == _DURATION and t2 == _DURATION:
        if v2 == 0:
            raise _err('modulo', 'Zero divisor passed')
        return format_duration(int(math.fmod(v1, v2)))
    if t1 == _SCALAR and t2 == _SCALAR:
        if v1 != int(v1) or v2 != int(v2):
            raise _err('modulo', 'Non-integer argument(s) passed for modulo')
        if v2 == 0:
            raise _err('modulo', 'Zero divisor passed')
        return float(_trunc_mod(int(v1), int(v2)))
    raise _err('modulo', 'types mismatch')


def _trunc_mod(a: int, b: int) -> int:
    """Exact integer modulo with Go semantics (result takes dividend's sign)."""
    t = abs(a) // abs(b)
    if (a < 0) != (b < 0):
        t = -t
    return a - b * t


# -- encoding ----------------------------------------------------------------

def _fn_base64_decode(ip, args):
    s = _arg_str('base64_decode', args, 0)
    try:
        # surrogateescape round-trips non-UTF-8 bytes like Go's string()
        return base64.b64decode(s, validate=True).decode('utf-8', 'surrogateescape')
    except (binascii.Error, ValueError) as e:
        raise _err('base64_decode', str(e))


def _fn_base64_encode(ip, args):
    s = _arg_str('base64_encode', args, 0)
    return base64.b64encode(s.encode('utf-8')).decode('ascii')


def _fn_parse_json(ip, args):
    s = _arg_str('parse_json', args, 0)
    try:
        return json.loads(s)
    except ValueError as e:
        raise _err('parse_json', str(e))


def _fn_parse_yaml(ip, args):
    s = _arg_str('parse_yaml', args, 0)
    try:
        return yaml.safe_load(s)
    except yaml.YAMLError as e:
        raise _err('parse_yaml', str(e))


def _fn_items(ip, args):
    obj = args[0]
    if not isinstance(obj, dict):
        raise _err('items', '1 argument is expected of object type')
    key_name = _arg_str('items', args, 1)
    val_name = _arg_str('items', args, 2)
    return [{key_name: k, val_name: obj[k]} for k in sorted(obj)]


def _fn_object_from_lists(ip, args):
    keys, values = args[0], args[1]
    if not isinstance(keys, list):
        raise _err('object_from_lists', '1 argument is expected of array type')
    if not isinstance(values, list):
        raise _err('object_from_lists', '2 argument is expected of array type')
    out = {}
    for i, k in enumerate(keys):
        key = _iface_to_string(k)
        out[key] = values[i] if i < len(values) else None
    return out


# -- semver ------------------------------------------------------------------

_SEMVER_RE = re.compile(
    r'^(?P<major>\d+)\.(?P<minor>\d+)\.(?P<patch>\d+)'
    r'(?:-(?P<pre>[0-9A-Za-z.-]+))?(?:\+(?P<build>[0-9A-Za-z.-]+))?$')


def _parse_semver(s: str):
    m = _SEMVER_RE.match(s.strip())
    if not m:
        raise _err('semver_compare', f'invalid semver {s!r}')
    pre = m.group('pre')
    pre_ids: Tuple = ()
    if pre:
        parts = []
        for p in pre.split('.'):
            if p.isdigit():
                parts.append((0, int(p)))
            else:
                parts.append((1, p))
        pre_ids = tuple(parts)
    return (int(m.group('major')), int(m.group('minor')),
            int(m.group('patch')), pre_ids)


def _semver_cmp(a, b) -> int:
    if a[:3] != b[:3]:
        return -1 if a[:3] < b[:3] else 1
    ap, bp = a[3], b[3]
    if ap == bp:
        return 0
    if not ap:
        return 1   # no prerelease > prerelease
    if not bp:
        return -1
    return -1 if ap < bp else (1 if ap > bp else 0)


def _expand_wildcard(op: str, vs: str) -> List[Tuple[str, str]]:
    """Expand x/* wildcard versions in ranges like blang/semver does."""
    parts = vs.split('.')
    wild_at = None
    for i, p in enumerate(parts):
        if p in ('x', 'X', '*'):
            wild_at = i
            break
    if wild_at is None:
        return [(op, vs)]
    base = [p if i < wild_at else '0' for i, p in enumerate(parts)]
    while len(base) < 3:
        base.append('0')
    lo = '.'.join(base[:3])
    if wild_at == 0:
        return [('>=', '0.0.0')] if op in ('', '=', '>=') else [(op, '0.0.0')]
    bump = base[:3]
    bump[wild_at - 1] = str(int(bump[wild_at - 1]) + 1)
    hi = '.'.join(bump)
    if op in ('', '='):
        return [('>=', lo), ('<', hi)]
    if op == '>':
        return [('>=', hi)]
    if op == '>=':
        return [('>=', lo)]
    if op == '<':
        return [('<', lo)]
    if op == '<=':
        return [('<', hi)]
    if op in ('!=', '!'):
        # blang/semver expands !X.x to "<lo AND >=hi", an unsatisfiable
        # range — reproduced bug-for-bug (its own test expects false for
        # any input; reference: pkg/engine/jmespath/functions_test.go:1300)
        return [('<', lo), ('>=', hi)]
    return [(op, lo)]


def _parse_range(rng: str):
    """Parse a blang/semver-style range: ||-separated OR groups of
    space-separated AND comparators."""
    or_groups = []
    for group in rng.split('||'):
        comparators = []
        tokens = group.split()
        i = 0
        while i < len(tokens):
            term = tokens[i]
            # blang/semver accepts a space between operator and version
            if re.fullmatch(r'>=|<=|!=|==|=|>|<|!', term) and i + 1 < len(tokens):
                term = term + tokens[i + 1]
                i += 2
            else:
                i += 1
            m = re.match(r'^(>=|<=|!=|==|=|>|<|!)?\s*(.+)$', term)
            op = m.group(1) or '='
            if op == '!':
                op = '!='
            vs = m.group(2)
            for op2, vs2 in _expand_wildcard(op if op != '==' else '=', vs):
                v = _parse_semver(vs2)
                comparators.append((op2 if op2 != '==' else '=', v))
        or_groups.append(comparators)

    def check(version) -> bool:
        for comps in or_groups:
            ok = True
            for op, v in comps:
                c = _semver_cmp(version, v)
                if op == '=' and c != 0:
                    ok = False
                elif op == '!=' and c == 0:
                    ok = False
                elif op == '>' and c <= 0:
                    ok = False
                elif op == '>=' and c < 0:
                    ok = False
                elif op == '<' and c >= 0:
                    ok = False
                elif op == '<=' and c > 0:
                    ok = False
                if not ok:
                    break
            if ok:
                return True
        return False

    return check


def _fn_semver_compare(ip, args):
    v = _arg_str('semver_compare', args, 0)
    r = _arg_str('semver_compare', args, 1)
    try:
        version = _parse_semver(v)
    except FunctionError:
        # reference ignores parse errors on the version (semver.Parse result
        # unchecked) -> compare with zero version
        version = (0, 0, 0, ())
    return _parse_range(r)(version)


# -- random ------------------------------------------------------------------

def _fn_random(ip, args):
    pattern = args[0]
    if not isinstance(pattern, str) or pattern == '':
        raise _err('random', 'no pattern provided')
    return _generate_from_regex(pattern)


def _generate_from_regex(pattern: str) -> str:
    """Tiny regex-driven string generator covering the subset used in
    policies: literals, [..] classes, \\d \\w, {n}/{n,m}, + * ?, (a|b)."""
    rng = _random.SystemRandom()

    def parse_class(s: str, i: int) -> Tuple[List[str], int]:
        chars: List[str] = []
        assert s[i] == '['
        i += 1
        negate = False
        if i < len(s) and s[i] == '^':
            negate = True
            i += 1
        while i < len(s) and s[i] != ']':
            if i + 2 < len(s) and s[i + 1] == '-' and s[i + 2] != ']':
                chars.extend(chr(c) for c in range(ord(s[i]), ord(s[i + 2]) + 1))
                i += 3
            elif s[i] == '\\' and i + 1 < len(s):
                chars.extend(_ESCAPES.get(s[i + 1], s[i + 1]))
                i += 2
            else:
                chars.append(s[i])
                i += 1
        if i >= len(s):
            raise FunctionError('unterminated character class')
        i += 1
        if negate:
            import string as _string
            allowed = [c for c in _string.printable[:95] if c not in chars]
            chars = allowed
        return chars, i

    def parse_count(s: str, i: int) -> Tuple[int, int]:
        if i < len(s) and s[i] == '{':
            j = s.find('}', i)
            if j == -1:
                raise FunctionError('unterminated quantifier')
            spec = s[i + 1:j]
            if ',' in spec:
                lo, hi = spec.split(',', 1)
                n = rng.randint(int(lo), int(hi or int(lo) + 10))
            else:
                n = int(spec)
            return n, j + 1
        if i < len(s) and s[i] == '+':
            return rng.randint(1, 10), i + 1
        if i < len(s) and s[i] == '*':
            return rng.randint(0, 10), i + 1
        if i < len(s) and s[i] == '?':
            return rng.randint(0, 1), i + 1
        return 1, i

    def gen(s: str) -> str:
        # handle top-level alternation in groups only
        out = []
        i = 0
        while i < len(s):
            c = s[i]
            if c == '[':
                chars, i = parse_class(s, i)
                n, i = parse_count(s, i)
                out.extend(rng.choice(chars) for _ in range(n))
            elif c == '\\' and i + 1 < len(s):
                chars = _ESCAPES.get(s[i + 1], s[i + 1])
                i += 2
                n, i = parse_count(s, i)
                out.extend(rng.choice(chars) for _ in range(n))
            elif c == '(':
                depth = 1
                j = i + 1
                while j < len(s) and depth:
                    if s[j] == '(':
                        depth += 1
                    elif s[j] == ')':
                        depth -= 1
                    j += 1
                if depth:
                    raise FunctionError('unterminated group')
                inner = s[i + 1:j - 1]
                alts = _split_alternation(inner)
                i = j
                n, i = parse_count(s, i)
                out.extend(gen(rng.choice(alts)) for _ in range(n))
            elif c in '^$':
                i += 1
            else:
                i += 1
                n, i = parse_count(s, i)
                out.extend(c for _ in range(n))
        return ''.join(out)

    return gen(pattern)


_ESCAPES = {
    'd': '0123456789',
    'w': 'abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789_',
    's': ' \t',
}


def _split_alternation(s: str) -> List[str]:
    alts, depth, cur = [], 0, []
    for c in s:
        if c == '(':
            depth += 1
        elif c == ')':
            depth -= 1
        if c == '|' and depth == 0:
            alts.append(''.join(cur))
            cur = []
        else:
            cur.append(c)
    alts.append(''.join(cur))
    return alts


# -- x509 --------------------------------------------------------------------

def _fn_x509_decode(ip, args):
    s = _arg_str('x509_decode', args, 0)
    try:
        from cryptography import x509 as cx509
        from cryptography.hazmat.primitives.asymmetric import rsa
    except ImportError:  # pragma: no cover
        raise _err('x509_decode', 'x509 support unavailable')
    try:
        cert = cx509.load_pem_x509_certificate(s.encode())
    except ValueError as e:
        raise _err('x509_decode', f'invalid certificate: {e}')

    def name_to_obj(name):
        # mirrors Go pkix.Name JSON shape (subset)
        from cryptography.x509.oid import NameOID
        def get_all(oid):
            return [a.value for a in name.get_attributes_for_oid(oid)]
        cn = get_all(NameOID.COMMON_NAME)
        return {
            'CommonName': cn[0] if cn else '',
            'Country': get_all(NameOID.COUNTRY_NAME),
            'Organization': get_all(NameOID.ORGANIZATION_NAME),
            'OrganizationalUnit': get_all(NameOID.ORGANIZATIONAL_UNIT_NAME),
            'Locality': get_all(NameOID.LOCALITY_NAME),
            'Province': get_all(NameOID.STATE_OR_PROVINCE_NAME),
            'SerialNumber': '',
            'Names': None,
            'ExtraNames': None,
            'StreetAddress': None, 'PostalCode': None,
        }

    pub = cert.public_key()
    public_key = None
    if isinstance(pub, rsa.RSAPublicKey):
        nums = pub.public_numbers()
        public_key = {'N': str(nums.n), 'E': nums.e}

    def ts(t: datetime.datetime) -> str:
        return t.strftime('%Y-%m-%dT%H:%M:%SZ')

    return {
        'SerialNumber': cert.serial_number,
        'Issuer': name_to_obj(cert.issuer),
        'Subject': name_to_obj(cert.subject),
        'NotBefore': ts(cert.not_valid_before_utc),
        'NotAfter': ts(cert.not_valid_after_utc),
        'Version': cert.version.value + 1,
        'IsCA': _cert_is_ca(cert),
        'PublicKey': public_key,
        'PublicKeyAlgorithm': 'RSA' if public_key else '',
    }


def _cert_is_ca(cert) -> bool:
    from cryptography import x509 as cx509
    try:
        bc = cert.extensions.get_extension_for_class(cx509.BasicConstraints)
        return bool(bc.value.ca)
    except cx509.ExtensionNotFound:
        return False


# -- time --------------------------------------------------------------------

RFC3339 = '%Y-%m-%dT%H:%M:%S%z'


def _parse_rfc3339(fname: str, s: str) -> datetime.datetime:
    try:
        t = datetime.datetime.fromisoformat(s.replace('Z', '+00:00'))
        if t.tzinfo is None:
            raise ValueError('missing timezone')
        return t
    except ValueError as e:
        raise _err(fname, f'cannot parse time {s!r}: {e}')


def _format_rfc3339(t: datetime.datetime) -> str:
    s = t.isoformat(timespec='seconds')
    return s.replace('+00:00', 'Z')


_GO_LAYOUT_MAP = [
    ('2006', '%Y'), ('01', '%m'), ('02', '%d'), ('15', '%H'), ('04', '%M'),
    ('05', '%S'), ('January', '%B'), ('Jan', '%b'), ('Monday', '%A'),
    ('Mon', '%a'), ('PM', '%p'), ('pm', '%p'), ('03', '%I'),
    ('-07:00', '%z'), ('-0700', '%z'), ('Z07:00', '%z'), ('Z0700', '%z'),
    ('MST', '%Z'), ('.000', ''), ('.999999999', ''), ('.999', ''), ('06', '%y'),
]


def _go_layout_to_strptime(layout: str) -> str:
    out = layout
    for go, py in _GO_LAYOUT_MAP:
        out = out.replace(go, py)
    return out


def _parse_with_layout(fname: str, layout: str, s: str) -> datetime.datetime:
    if layout == '' or layout == RFC3339:
        return _parse_rfc3339(fname, s)
    fmt = _go_layout_to_strptime(layout)
    try:
        t = datetime.datetime.strptime(s, fmt)
    except ValueError as e:
        raise _err(fname, str(e))
    if t.tzinfo is None:
        t = t.replace(tzinfo=datetime.timezone.utc)
    return t


def _fn_time_since(ip, args):
    layout = _arg_str('time_since', args, 0)
    ts1 = _arg_str('time_since', args, 1)
    ts2 = _arg_str('time_since', args, 2)
    t1 = _parse_with_layout('time_since', layout, ts1)
    if ts2 != '':
        t2 = _parse_with_layout('time_since', layout, ts2)
    else:
        t2 = datetime.datetime.now(datetime.timezone.utc)
    return format_duration(int((t2 - t1).total_seconds() * 1e9))


def _fn_time_now(ip, args):
    return _format_rfc3339(datetime.datetime.now().astimezone())


def _fn_time_now_utc(ip, args):
    return _format_rfc3339(datetime.datetime.now(datetime.timezone.utc))


def _fn_time_to_cron(ip, args):
    t = _parse_rfc3339('time_to_cron', _arg_str('time_to_cron', args, 0))
    # Go Weekday: Sunday=0; Python: Monday=0
    weekday = (t.weekday() + 1) % 7
    return f'{t.minute} {t.hour} {t.day} {t.month} {weekday}'


def _fn_time_add(ip, args):
    t = _parse_rfc3339('time_add', _arg_str('time_add', args, 0))
    try:
        d = parse_duration(_arg_str('time_add', args, 1))
    except DurationError as e:
        raise _err('time_add', str(e))
    return _format_rfc3339(t + datetime.timedelta(microseconds=d / 1000))


def _fn_time_parse(ip, args):
    layout = _arg_str('time_parse', args, 0)
    ts = _arg_str('time_parse', args, 1)
    return _format_rfc3339(_parse_with_layout('time_parse', layout, ts))


def _fn_time_utc(ip, args):
    t = _parse_rfc3339('time_utc', _arg_str('time_utc', args, 0))
    return _format_rfc3339(t.astimezone(datetime.timezone.utc))


def _fn_time_diff(ip, args):
    t1 = _parse_rfc3339('time_diff', _arg_str('time_diff', args, 0))
    t2 = _parse_rfc3339('time_diff', _arg_str('time_diff', args, 1))
    return format_duration(int((t2 - t1).total_seconds() * 1e9))


def _fn_time_before(ip, args):
    t1 = _parse_rfc3339('time_before', _arg_str('time_before', args, 0))
    t2 = _parse_rfc3339('time_before', _arg_str('time_before', args, 1))
    return t1 < t2


def _fn_time_after(ip, args):
    t1 = _parse_rfc3339('time_after', _arg_str('time_after', args, 0))
    t2 = _parse_rfc3339('time_after', _arg_str('time_after', args, 1))
    return t1 > t2


def _fn_time_between(ip, args):
    t = _parse_rfc3339('time_between', _arg_str('time_between', args, 0))
    start = _parse_rfc3339('time_between', _arg_str('time_between', args, 1))
    end = _parse_rfc3339('time_between', _arg_str('time_between', args, 2))
    return start < t < end


def _fn_time_truncate(ip, args):
    t = _parse_rfc3339('time_truncate', _arg_str('time_truncate', args, 0))
    try:
        d = parse_duration(_arg_str('time_truncate', args, 1))
    except DurationError as e:
        raise _err('time_truncate', str(e))
    if d <= 0:
        return _format_rfc3339(t)
    epoch_ns = int(t.timestamp() * 1e9)
    truncated = epoch_ns - (epoch_ns % d)
    out = datetime.datetime.fromtimestamp(truncated / 1e9, t.tzinfo)
    return _format_rfc3339(out)


# ---------------------------------------------------------------------------

def register_custom_functions(r: FunctionRegistry) -> FunctionRegistry:
    """Register all Kyverno custom functions
    (reference: pkg/engine/jmespath/functions.go:118 GetFunctions)."""
    A = lambda *types: {'types': list(types)}  # noqa: E731
    r.register('compare', [A('string'), A('string')], _fn_compare)
    r.register('equal_fold', [A('string'), A('string')], _fn_equal_fold)
    r.register('replace', [A('string'), A('string'), A('string'), A('number')], _fn_replace)
    r.register('replace_all', [A('string'), A('string'), A('string')], _fn_replace_all)
    r.register('to_upper', [A('string')], _fn_to_upper)
    r.register('to_lower', [A('string')], _fn_to_lower)
    r.register('trim', [A('string'), A('string')], _fn_trim)
    r.register('split', [A('string'), A('string')], _fn_split)
    r.register('regex_replace_all', [A('string'), A('string', 'number'), A('string', 'number')], _fn_regex_replace_all)
    r.register('regex_replace_all_literal', [A('string'), A('string', 'number'), A('string', 'number')], _fn_regex_replace_all_literal)
    r.register('regex_match', [A('string'), A('string', 'number')], _fn_regex_match)
    r.register('pattern_match', [A('string'), A('string', 'number')], _fn_pattern_match)
    r.register('label_match', [A('object'), A('object')], _fn_label_match)
    r.register('add', [A('any'), A('any')], _fn_add)
    r.register('subtract', [A('any'), A('any')], _fn_subtract)
    r.register('multiply', [A('any'), A('any')], _fn_multiply)
    r.register('divide', [A('any'), A('any')], _fn_divide)
    r.register('modulo', [A('any'), A('any')], _fn_modulo)
    r.register('base64_decode', [A('string')], _fn_base64_decode)
    r.register('base64_encode', [A('string')], _fn_base64_encode)
    r.register('path_canonicalize', [A('string')], _fn_path_canonicalize)
    r.register('truncate', [A('string'), A('number')], _fn_truncate)
    r.register('semver_compare', [A('string'), A('string')], _fn_semver_compare)
    r.register('parse_json', [A('string')], _fn_parse_json)
    r.register('parse_yaml', [A('string')], _fn_parse_yaml)
    r.register('items', [A('object'), A('string'), A('string')], _fn_items)
    r.register('object_from_lists', [A('array'), A('array')], _fn_object_from_lists)
    r.register('random', [A('string')], _fn_random)
    r.register('x509_decode', [A('string')], _fn_x509_decode)
    r.register('time_since', [A('string'), A('string'), A('string')], _fn_time_since)
    r.register('time_now', [], _fn_time_now)
    r.register('time_now_utc', [], _fn_time_now_utc)
    r.register('time_add', [A('string'), A('string')], _fn_time_add)
    r.register('time_parse', [A('string'), A('string')], _fn_time_parse)
    r.register('time_to_cron', [A('string')], _fn_time_to_cron)
    r.register('time_utc', [A('string')], _fn_time_utc)
    r.register('time_diff', [A('string'), A('string')], _fn_time_diff)
    r.register('time_before', [A('string'), A('string')], _fn_time_before)
    r.register('time_after', [A('string'), A('string')], _fn_time_after)
    r.register('time_between', [A('string'), A('string'), A('string')], _fn_time_between)
    r.register('time_truncate', [A('string'), A('string')], _fn_time_truncate)
    return r
