"""JMESPath error types."""

from __future__ import annotations


class JMESPathError(ValueError):
    """Base error for all JMESPath failures."""


class LexerError(JMESPathError):
    def __init__(self, position: int, token: str, message: str):
        super().__init__(f'{message} (at position {position})')
        self.position = position
        self.token = token


class ParseError(JMESPathError):
    def __init__(self, position: int, token: object, token_type: str,
                 message: str = 'invalid token'):
        super().__init__(
            f'{message}: unexpected token {token!r} ({token_type}) at position {position}')
        self.position = position
        self.token = token
        self.token_type = token_type


class IncompleteExpressionError(ParseError):
    def __init__(self, position: int, token: object, token_type: str):
        super().__init__(position, token, token_type, 'incomplete expression')


class ArityError(JMESPathError):
    pass


class JMESPathTypeError(JMESPathError):
    def __init__(self, function_name, current_value, actual_type, expected_types):
        self.function_name = function_name
        self.current_value = current_value
        self.actual_type = actual_type
        self.expected_types = expected_types
        super().__init__(
            f'In function {function_name}(), invalid type for value: '
            f'{current_value!r}, expected one of: {expected_types}, '
            f'received: "{actual_type}"')


class UnknownFunctionError(JMESPathError):
    pass


class FunctionError(JMESPathError):
    """Raised by custom function implementations on bad input."""


class NotFoundError(JMESPathError):
    """The expression resolved to a missing field (kyverno/go-jmespath fork
    behavior — reference: go.mod:342, pkg/engine/variables/vars.go:395)."""

