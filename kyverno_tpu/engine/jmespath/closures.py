"""AST → closure compiler for the JMESPath interpreter.

The tree interpreter (interpreter.py TreeInterpreter) dispatches through
``getattr(self, '_visit_' + type)`` and re-reads ``node['children']`` on
every evaluation; batch encoding (compiler/encode.py) runs the same
small set of expressions over every resource, so that per-node overhead
dominates.  ``compile_closure`` lowers each AST node once into a nested
Python closure with the children/values bound in cell variables —
semantics are a line-for-line mirror of the corresponding ``_visit_*``
method, verified by the conformance corpus running through both paths.

Unknown node types raise ``UnsupportedNode`` at compile time; callers
fall back to the interpreter (closures are an optimization, never a
semantic fork).
"""

from __future__ import annotations

from typing import Any, Callable

from .errors import FunctionError
from .interpreter import (NOT_FOUND, ExprRef, _defined, deep_equal,
                          is_false, is_truthy)

_Fn = Callable[[Any], Any]


class UnsupportedNode(Exception):
    pass


def compile_closure(node: dict, interpreter) -> _Fn:
    """Compile ``node`` to a closure; ``interpreter`` supplies the
    function registry and is handed to ExprRefs (function arguments that
    are expression references evaluate through the interpreter)."""
    ctor = _COMPILERS.get(node['type'])
    if ctor is None:
        raise UnsupportedNode(node['type'])
    return ctor(node, interpreter)


def _children(node, interpreter):
    return [compile_closure(c, interpreter) for c in node['children']]


def _c_literal(node, interp):
    v = node['value']
    return lambda value: v


def _c_identity(node, interp):
    return lambda value: value


def _c_field(node, interp):
    k = node['value']

    def field(value):
        if isinstance(value, dict):
            return value.get(k, NOT_FOUND)
        return NOT_FOUND if value is NOT_FOUND else None
    return field


def _c_subexpression(node, interp):
    fns = _children(node, interp)
    if len(fns) == 2:
        a, b = fns
        return lambda value: b(a(value))

    def subexpr(value):
        for fn in fns:
            value = fn(value)
        return value
    return subexpr


def _c_index(node, interp):
    idx = node['value']

    def index(value):
        if not isinstance(value, list):
            return NOT_FOUND if value is NOT_FOUND else None
        try:
            return value[idx]
        except IndexError:
            return None
    return index


def _c_slice(node, interp):
    start, stop, step = node['value']

    def slc(value):
        if not isinstance(value, list):
            return NOT_FOUND if value is NOT_FOUND else None
        if step == 0:
            raise FunctionError('slice step cannot be 0')
        return value[slice(start, stop, step)]
    return slc


def _c_projection(node, interp):
    left, right = _children(node, interp)

    def projection(value):
        base = left(value)
        if not isinstance(base, list):
            return NOT_FOUND if base is NOT_FOUND else None
        collected = []
        for element in base:
            current = right(element)
            if current is NOT_FOUND:
                current = None
            if current is not None:
                collected.append(current)
        return collected
    return projection


def _c_value_projection(node, interp):
    left, right = _children(node, interp)

    def vprojection(value):
        base = left(value)
        if not isinstance(base, dict):
            return NOT_FOUND if base is NOT_FOUND else None
        collected = []
        for element in base.values():
            current = right(element)
            if current is NOT_FOUND:
                current = None
            if current is not None:
                collected.append(current)
        return collected
    return vprojection


def _c_flatten(node, interp):
    [inner] = _children(node, interp)

    def flatten(value):
        base = inner(value)
        if not isinstance(base, list):
            return NOT_FOUND if base is NOT_FOUND else None
        merged = []
        for element in base:
            if isinstance(element, list):
                merged.extend(element)
            else:
                merged.append(element)
        return merged
    return flatten


def _c_filter_projection(node, interp):
    left, right, comparator = _children(node, interp)

    def fprojection(value):
        base = left(value)
        if not isinstance(base, list):
            return NOT_FOUND if base is NOT_FOUND else None
        collected = []
        for element in base:
            if is_truthy(comparator(element)):
                current = right(element)
                if current is NOT_FOUND:
                    current = None
                if current is not None:
                    collected.append(current)
        return collected
    return fprojection


def _c_comparator(node, interp):
    op = node['value']
    left, right = _children(node, interp)
    if op == 'eq':
        return lambda value: deep_equal(_defined(left(value)),
                                        _defined(right(value)))
    if op == 'ne':
        return lambda value: not deep_equal(_defined(left(value)),
                                            _defined(right(value)))
    import operator
    cmp = {'lt': operator.lt, 'gt': operator.gt,
           'lte': operator.le, 'gte': operator.ge}.get(op)
    if cmp is None:
        raise UnsupportedNode(f'comparator {op}')

    def ordering(value):
        a = _defined(left(value))
        b = _defined(right(value))
        if not _is_number(a) or not _is_number(b):
            return None
        return cmp(a, b)
    return ordering


def _is_number(v) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool)


def _c_or_expression(node, interp):
    left, right = _children(node, interp)

    def or_expr(value):
        matched = left(value)
        if is_false(matched):
            matched = right(value)
        return matched
    return or_expr


def _c_and_expression(node, interp):
    left, right = _children(node, interp)

    def and_expr(value):
        matched = left(value)
        if is_false(matched):
            return matched
        return right(value)
    return and_expr


def _c_not_expression(node, interp):
    [inner] = _children(node, interp)
    return lambda value: is_false(inner(value))


def _c_pipe(node, interp):
    left, right = _children(node, interp)
    return lambda value: right(left(value))


def _c_multi_select_list(node, interp):
    fns = _children(node, interp)

    def msl(value):
        if _defined(value) is None:
            return None
        return [_defined(fn(value)) for fn in fns]
    return msl


def _c_multi_select_dict(node, interp):
    pairs = [(child['value'],
              compile_closure(child['children'][0], interp))
             for child in node['children']]

    def msd(value):
        if _defined(value) is None:
            return None
        return {k: _defined(fn(value)) for k, fn in pairs}
    return msd


def _c_function_expression(node, interp):
    name = node['value']
    fns = _children(node, interp)
    functions = interp.functions

    def call(value):
        return functions.call(interp, name, [_defined(fn(value))
                                             for fn in fns])
    return call


def _c_expref(node, interp):
    child = node['children'][0]
    return lambda value: ExprRef(child, interp)


_COMPILERS = {
    'literal': _c_literal,
    'identity': _c_identity,
    'current': _c_identity,
    'field': _c_field,
    'subexpression': _c_subexpression,
    'index': _c_index,
    'slice': _c_slice,
    'index_expression': _c_subexpression,
    'projection': _c_projection,
    'value_projection': _c_value_projection,
    'flatten': _c_flatten,
    'filter_projection': _c_filter_projection,
    'comparator': _c_comparator,
    'or_expression': _c_or_expression,
    'and_expression': _c_and_expression,
    'not_expression': _c_not_expression,
    'pipe': _c_pipe,
    'multi_select_list': _c_multi_select_list,
    'multi_select_dict': _c_multi_select_dict,
    'function_expression': _c_function_expression,
    'expref': _c_expref,
}
