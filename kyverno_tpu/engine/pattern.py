"""Leaf pattern validation: scalar value vs pattern.

Re-implements the reference's leaf comparison semantics
(reference: pkg/engine/pattern/pattern.go, pkg/engine/operator/operator.go):

* pattern types: bool / int / float / nil / map (existence only) / string
* string pattern grammar: ``|``-separated OR of ``&``-separated AND terms;
  each term optionally prefixed by an operator ``>= <= > < !`` or a range
  ``x-y`` (in range) / ``x!-y`` (not in range)
* string terms compare as Go duration, then k8s quantity, then wildcard string
* cross-type coercions (string-int, float-int, nil-zero) follow the reference.
"""

from __future__ import annotations

import math
import re
from typing import Any

from ..utils import wildcard
from ..utils.duration import parse_duration
from ..utils.quantity import Quantity

# Operators, ordered so longer prefixes are tried first.
OP_EQUAL = ''
OP_MORE_EQUAL = '>='
OP_LESS_EQUAL = '<='
OP_NOT_EQUAL = '!'
OP_MORE = '>'
OP_LESS = '<'
OP_IN_RANGE = '-'
OP_NOT_IN_RANGE = '!-'

IN_RANGE_RE = re.compile(r'^([-|+]?\d+(?:\.\d+)?[A-Za-z]*)-([-|+]?\d+(?:\.\d+)?[A-Za-z]*)$')
NOT_IN_RANGE_RE = re.compile(r'^([-|+]?\d+(?:\.\d+)?[A-Za-z]*)!-([-|+]?\d+(?:\.\d+)?[A-Za-z]*)$')


def get_operator_from_string_pattern(pattern: str) -> str:
    """Parse the leading operator from a string pattern
    (reference: pkg/engine/operator/operator.go:36)."""
    if len(pattern) < 2:
        return OP_EQUAL
    if pattern.startswith(OP_MORE_EQUAL):
        return OP_MORE_EQUAL
    if pattern.startswith(OP_LESS_EQUAL):
        return OP_LESS_EQUAL
    if pattern.startswith(OP_MORE):
        return OP_MORE
    if pattern.startswith(OP_LESS):
        return OP_LESS
    if pattern.startswith(OP_NOT_EQUAL):
        return OP_NOT_EQUAL
    if NOT_IN_RANGE_RE.match(pattern):
        return OP_NOT_IN_RANGE
    if IN_RANGE_RE.match(pattern):
        return OP_IN_RANGE
    return OP_EQUAL


def validate(value: Any, pattern: Any) -> bool:
    """Validate a scalar resource value against a pattern leaf
    (reference: pkg/engine/pattern/pattern.go:26)."""
    if isinstance(pattern, bool):  # bool before int: Python bool is int
        return _validate_bool(value, pattern)
    if isinstance(pattern, int):
        return _validate_int(value, pattern)
    if isinstance(pattern, float):
        return _validate_float(value, pattern)
    if pattern is None:
        return _validate_nil(value)
    if isinstance(pattern, dict):
        return isinstance(value, dict)
    if isinstance(pattern, str):
        return _validate_string_patterns(value, pattern)
    if isinstance(pattern, list):
        return False  # arrays are not supported as patterns
    return False


def _validate_bool(value: Any, pattern: bool) -> bool:
    return isinstance(value, bool) and value == pattern


def _validate_int(value: Any, pattern: int) -> bool:
    if isinstance(value, bool):
        return False
    if isinstance(value, int):
        return value == pattern
    if isinstance(value, float):
        if value != math.trunc(value):
            return False
        return int(value) == pattern
    if isinstance(value, str):
        try:
            return int(value, 10) == pattern
        except ValueError:
            return False
    return False


def _validate_float(value: Any, pattern: float) -> bool:
    if isinstance(value, bool):
        return False
    if isinstance(value, int):
        if pattern != math.trunc(pattern):
            return False
        return int(pattern) == value
    if isinstance(value, float):
        return value == pattern
    if isinstance(value, str):
        try:
            return float(value) == pattern
        except ValueError:
            return False
    return False


def _validate_nil(value: Any) -> bool:
    if value is None:
        return True
    if isinstance(value, bool):
        return not value
    if isinstance(value, float):
        return value == 0.0
    if isinstance(value, int):
        return value == 0
    if isinstance(value, str):
        return value == ''
    return False


def _validate_string_patterns(value: Any, pattern: str) -> bool:
    if value == pattern:
        return True
    for condition in pattern.split('|'):
        if _check_and_conditions(value, condition.strip(' ')):
            return True
    return False


def _check_and_conditions(value: Any, pattern: str) -> bool:
    return all(
        _validate_string_pattern(value, c.strip(' '))
        for c in pattern.split('&')
    )


def _validate_string_pattern(value: Any, pattern: str) -> bool:
    op = get_operator_from_string_pattern(pattern)
    if op == OP_IN_RANGE:
        m = IN_RANGE_RE.match(pattern)
        if not m:
            return False
        return (_validate_string_pattern(value, f'>= {m.group(1)}')
                and _validate_string_pattern(value, f'<= {m.group(2)}'))
    if op == OP_NOT_IN_RANGE:
        m = NOT_IN_RANGE_RE.match(pattern)
        if not m:
            return False
        return (_validate_string_pattern(value, f'< {m.group(1)}')
                or _validate_string_pattern(value, f'> {m.group(2)}'))
    term = pattern[len(op):].strip(' ')
    return _validate_string(value, term, op)


def _validate_string(value: Any, pattern: str, op: str) -> bool:
    return (_compare_duration(value, pattern, op)
            or _compare_quantity(value, pattern, op)
            or _compare_string(value, pattern, op))


def _number_to_string(value: Any):
    if value is None:
        return '0'
    if isinstance(value, bool):
        return None
    if isinstance(value, str):
        return value
    if isinstance(value, float):
        return f'{value:f}'
    if isinstance(value, int):
        return str(value)
    return None


_CMP = {
    OP_EQUAL: lambda c: c == 0,
    OP_NOT_EQUAL: lambda c: c != 0,
    OP_MORE: lambda c: c > 0,
    OP_LESS: lambda c: c < 0,
    OP_MORE_EQUAL: lambda c: c >= 0,
    OP_LESS_EQUAL: lambda c: c <= 0,
}


def _compare_duration(value: Any, pattern: str, op: str) -> bool:
    try:
        p = parse_duration(pattern)
    except ValueError:
        return False
    v = _number_to_string(value)
    if v is None:
        return False
    try:
        v = parse_duration(v)
    except ValueError:
        return False
    f = _CMP.get(op)
    return bool(f and f((v > p) - (v < p)))


def _compare_quantity(value: Any, pattern: str, op: str) -> bool:
    try:
        p = Quantity.parse(pattern)
    except ValueError:
        return False
    v = _number_to_string(value)
    if v is None:
        return False
    try:
        v = Quantity.parse(v)
    except ValueError:
        return False
    f = _CMP.get(op)
    return bool(f and f(v.cmp(p)))


def _compare_string(value: Any, pattern: str, op: str) -> bool:
    if op not in (OP_EQUAL, OP_NOT_EQUAL):
        return False  # ordering operators don't apply to plain strings
    if isinstance(value, bool):
        s = 'true' if value else 'false'
    elif isinstance(value, float):
        # Go strconv.FormatFloat(v, 'E', -1, 64)
        s = _go_format_float_e(value)
    elif isinstance(value, int):
        s = str(value)
    elif isinstance(value, str):
        s = value
    else:
        return False
    result = wildcard.match(pattern, s)
    return (not result) if op == OP_NOT_EQUAL else result


def _go_format_float_e(v: float) -> str:
    """Go strconv.FormatFloat(v,'E',-1,64): shortest repr in E-notation."""
    s = repr(v)  # shortest round-trip decimal
    mant, _, exp = s.partition('e')
    if exp:
        e = int(exp)
    else:
        e = 0
    # normalize mantissa to d.ddd
    neg = mant.startswith('-')
    if neg:
        mant = mant[1:]
    int_part, _, frac = mant.partition('.')
    digits = (int_part + frac).lstrip('0') or '0'
    point = len(int_part.lstrip('0')) if int_part.lstrip('0') else -(len(frac) - len(frac.lstrip('0')))
    if digits == '0':
        norm, e2 = '0', 0
    else:
        norm = digits[0] + ('.' + digits[1:].rstrip('0') if digits[1:].rstrip('0') else '')
        e2 = e + point - 1
    sign = '-' if e2 < 0 else '+'
    return f"{'-' if neg else ''}{norm}E{sign}{abs(e2):02d}"
