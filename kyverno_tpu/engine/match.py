"""Match/exclude evaluation: does a rule apply to a resource?

Re-implements MatchesResourceDescription and its helpers
(reference: pkg/engine/utils.go:185, pkg/utils/match/*.go):

* match block: AND across attributes, OR inside list attributes
* any/all lists of resource filters
* exclude block: resource excluded if the block matches
* user info (roles / clusterRoles / subjects) matching
* label selectors with wildcard expansion
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from ..api.unstructured import (Resource, get_kind_from_gvk,
                                group_version_matches)
from ..utils import wildcard


class MatchError(Exception):
    pass


def matches_resource_description(resource: Resource, rule, admission_info: Optional[dict],
                                 exclude_group_roles: List[str],
                                 namespace_labels: Dict[str, str],
                                 policy_namespace: str,
                                 subresource_in_review: str = '',
                                 subresources_in_policy: Optional[List[dict]] = None) -> Optional[str]:
    """Return None if the rule matches, else a reason string
    (reference: pkg/engine/utils.go:185 MatchesResourceDescription)."""
    if policy_namespace and policy_namespace != resource.namespace:
        return (' The policy and resource namespace are different.'
                ' Therefore, policy skip this resource.')

    match = rule.match if not isinstance(rule, dict) else (rule.get('match') or {})
    exclude = rule.exclude if not isinstance(rule, dict) else (rule.get('exclude') or {})
    rule_name = rule.name if not isinstance(rule, dict) else rule.get('name', '')

    reasons: List[str] = []

    def match_filter(f):
        return _check_filter(f, resource, admission_info, exclude_group_roles,
                             namespace_labels, subresource_in_review,
                             allow_ephemeral=True, mode='match',
                             subresources_in_policy=subresources_in_policy)

    def exclude_filter(f):
        return _check_filter(f, resource, admission_info, exclude_group_roles,
                             namespace_labels, subresource_in_review,
                             allow_ephemeral=True, mode='exclude',
                             subresources_in_policy=subresources_in_policy)

    any_filters = match.get('any') or []
    all_filters = match.get('all') or []
    if any_filters:
        if not any(not match_filter(f) for f in any_filters):
            reasons.append('no resource matched')
    elif all_filters:
        for f in all_filters:
            reasons.extend(match_filter(f))
    else:
        f = {'resources': match.get('resources') or {},
             'roles': match.get('roles'), 'clusterRoles': match.get('clusterRoles'),
             'subjects': match.get('subjects')}
        reasons.extend(match_filter(f))

    ex_any = exclude.get('any') or []
    ex_all = exclude.get('all') or []
    if ex_any:
        for f in ex_any:
            if not exclude_filter(f):
                reasons.append('resource excluded since one of the criteria excluded it')
    elif ex_all:
        if all(not exclude_filter(f) for f in ex_all):
            reasons.append('resource excluded since the combination of all criteria exclude it')
    elif exclude:
        f = {'resources': exclude.get('resources') or {},
             'roles': exclude.get('roles'), 'clusterRoles': exclude.get('clusterRoles'),
             'subjects': exclude.get('subjects')}
        if not _filter_is_empty(f):
            if not exclude_filter(f):
                reasons.append('resource excluded since one of the criteria excluded it')

    if reasons:
        msg = f'rule {rule_name} not matched:'
        for i, r in enumerate(reasons):
            msg += '\n ' + str(i + 1) + '. ' + r
        return msg
    return None


def _filter_is_empty(f: dict) -> bool:
    res = f.get('resources') or {}
    return not any([res, f.get('roles'), f.get('clusterRoles'), f.get('subjects')])


def _check_filter(f: dict, resource: Resource, admission_info: Optional[dict],
                  exclude_group_roles: List[str],
                  namespace_labels: Dict[str, str],
                  subresource_in_review: str,
                  allow_ephemeral: bool = False,
                  mode: str = 'match',
                  subresources_in_policy: Optional[List[dict]] = None) -> List[str]:
    """Return list of mismatch reasons (empty == filter matched).

    ``mode='match'`` mirrors matchesResourceDescriptionMatchHelper
    (reference: pkg/engine/utils.go:261): user info is ignored when there is
    no admission info, and an empty filter is a non-match ("match cannot be
    empty"). ``mode='exclude'`` mirrors the exclude helper (utils.go:276):
    user info always applies and an empty filter never excludes."""
    errs: List[str] = []
    user_info = {'roles': f.get('roles'), 'clusterRoles': f.get('clusterRoles'),
                 'subjects': f.get('subjects')}
    has_user_info = any(user_info.values())
    res_desc = f.get('resources') or {}
    if mode == 'match' and (admission_info is None or not admission_info):
        has_user_info = False
        user_info = {}
    if res_desc or has_user_info:
        errs.extend(_check_resource_description(
            res_desc, resource, namespace_labels, subresource_in_review,
            allow_ephemeral, subresources_in_policy))
        if has_user_info:
            errs.extend(_check_user_info(user_info, admission_info or {},
                                         exclude_group_roles))
    else:
        # empty filter: never matches (match) / never excludes (exclude)
        errs.append('match cannot be empty' if mode == 'match'
                    else 'exclude filter is empty')
    return errs


def _check_resource_description(block: dict, resource: Resource,
                                namespace_labels: Dict[str, str],
                                subresource_in_review: str,
                                allow_ephemeral: bool,
                                subresources_in_policy: Optional[List[dict]] = None) -> List[str]:
    # reference: pkg/engine/utils.go:72 doesResourceMatchConditionBlock
    errs: List[str] = []
    kinds = block.get('kinds') or []
    if kinds:
        if not check_kind(kinds, resource, subresource_in_review,
                          allow_ephemeral, subresources_in_policy):
            errs.append(f'kind does not match {kinds}')
    resource_name = resource.name or resource.generate_name
    name = block.get('name') or ''
    if name:
        if not wildcard.match(name, resource_name):
            errs.append('name does not match')
    names = block.get('names') or []
    if names and not any(wildcard.match(n, resource_name) for n in names):
        errs.append('none of the names match')
    namespaces = block.get('namespaces') or []
    if namespaces and not _check_namespaces(namespaces, resource):
        errs.append('namespace does not match')
    annotations = block.get('annotations') or {}
    if annotations and not check_annotations(annotations, resource.annotations):
        errs.append('annotations does not match')
    selector = block.get('selector')
    if selector is not None:
        try:
            if not check_selector(selector, resource.labels):
                errs.append('selector does not match')
        except MatchError as e:
            errs.append(f'failed to parse selector: {e}')
    ns_selector = block.get('namespaceSelector')
    if ns_selector is not None and resource.kind != 'Namespace' and resource.kind != '':
        try:
            if not check_selector(ns_selector, namespace_labels):
                errs.append('namespace selector does not match')
        except MatchError as e:
            errs.append(f'failed to parse namespace selector: {e}')
    return errs


def _check_namespaces(namespaces: List[str], resource: Resource) -> bool:
    ns = resource.namespace
    if resource.kind == 'Namespace':
        ns = resource.name
    return any(wildcard.match(n, ns) for n in namespaces)


def check_kind(kinds: List[str], resource: Resource,
               subresource_in_review: str = '',
               allow_ephemeral: bool = False,
               subresources_in_policy: Optional[List[dict]] = None) -> bool:
    """Kind matching incl. group/version prefixes and subresources
    (reference: pkg/utils/match/kind.go:14 CheckKind; the subresource
    lookup map is built per-policy from CLI values when there is no
    cluster, reference: pkg/engine/common.go:12
    GetSubresourceGVKToAPIResourceMap)."""
    for k in kinds:
        if k == '*':
            return True
        gv, kind = get_kind_from_gvk(k)
        api_resource = _subresource_api_resource(k, subresources_in_policy)
        if api_resource is not None:
            if (api_resource.get('group', '') == resource.group and
                    (api_resource.get('version', '') == resource.version or
                     '*' in gv) and
                    api_resource.get('kind', '') == resource.kind):
                return True
            continue
        from ..api.unstructured import split_subresource
        parent_kind, sub = split_subresource(kind)
        if sub:
            # cluster path for 'Parent/subresource' rule kinds: the
            # review carries the subresource name and the parent kind
            # (reference: pkg/utils/match/kind.go CheckKind resolving
            # via the discovery subresource map)
            if parent_kind == resource.kind and \
                    subresource_in_review.lower() == sub.lower():
                if not gv or group_version_matches(gv,
                                                   resource.group_version):
                    return True
            continue
        result = kind == resource.kind and (
            subresource_in_review == '' or
            (allow_ephemeral and subresource_in_review == 'ephemeralcontainers'))
        if gv:
            result = result and group_version_matches(gv, resource.group_version)
        if result:
            return True
    return False


def _subresource_api_resource(gvk_str: str,
                              subresources_in_policy: Optional[List[dict]]
                              ) -> Optional[dict]:
    """reference: pkg/engine/common.go:12 — resolve a rule kind like
    'Deployment/scale' or a standalone subresource kind like
    'PodExecOptions' against the CLI-provided subresource list."""
    if not subresources_in_policy:
        return None
    from ..api.unstructured import split_subresource
    gv, k = get_kind_from_gvk(gvk_str)
    parent_kind, subresource = split_subresource(k)
    for entry in subresources_in_policy:
        api_resource = entry.get('subresource') or entry.get('apiResource') or {}
        parent = entry.get('parentResource') or {}
        if subresource:
            parent_gv = (f"{parent.get('group')}/{parent.get('version', '')}"
                         if parent.get('group') else parent.get('version', ''))
            if gv and not group_version_matches(gv, parent_gv):
                continue
            if parent_kind != parent.get('kind'):
                continue
            name_parts = (api_resource.get('name', '') or '').split('/')
            if len(name_parts) > 1 and subresource.lower() == name_parts[1]:
                return api_resource
        else:
            if (k == api_resource.get('kind') and
                    k != parent.get('kind')):
                sub_gv = (f"{api_resource.get('group')}/"
                          f"{api_resource.get('version', '')}"
                          if api_resource.get('group')
                          else api_resource.get('version', ''))
                if gv == '' or group_version_matches(gv, sub_gv):
                    return api_resource
    return None


def check_annotations(expected: Dict[str, str], actual: Dict[str, str]) -> bool:
    # reference: pkg/utils/match/annotations.go:7
    for k, v in expected.items():
        if not any(wildcard.match(k, k1) and wildcard.match(str(v), v1)
                   for k1, v1 in actual.items()):
            return False
    return True


def check_selector(selector: dict, labels: Dict[str, str]) -> bool:
    """Kubernetes LabelSelector semantics with kyverno wildcard expansion
    (reference: pkg/utils/match/labels.go:10 CheckSelector,
    pkg/engine/wildcards/wildcards.go:14 ReplaceInSelector)."""
    match_labels = dict(selector.get('matchLabels') or {})
    # wildcard expansion: wildcard keys/values replaced by matching real ones
    expanded = {}
    for k, v in match_labels.items():
        v = str(v)
        if wildcard.contains_wildcard(k) or wildcard.contains_wildcard(v):
            replaced = False
            for k1, v1 in labels.items():
                if wildcard.match(k, k1) and wildcard.match(v, v1):
                    expanded[k1] = v1
                    replaced = True
                    break
            if not replaced:
                expanded[k.replace('*', '0').replace('?', '0')] = \
                    v.replace('*', '0').replace('?', '0')
        else:
            expanded[k] = v
    for k, v in expanded.items():
        if labels.get(k) != v:
            return False
    for expr in selector.get('matchExpressions') or []:
        key = expr.get('key', '')
        op = expr.get('operator', '')
        values = expr.get('values') or []
        if op == 'In':
            if labels.get(key) not in values:
                return False
        elif op == 'NotIn':
            if labels.get(key) in values:
                return False
        elif op == 'Exists':
            if key not in labels:
                return False
        elif op == 'DoesNotExist':
            if key in labels:
                return False
        else:
            raise MatchError(f'invalid selector operator {op!r}')
    return True


def _check_user_info(user_info: dict, admission_info: dict,
                     exclude_group_roles: List[str]) -> List[str]:
    # reference: pkg/engine/utils.go:139-160
    errs: List[str] = []
    admission_user = (admission_info or {}).get('userInfo') or {}
    keys = list(admission_user.get('groups') or []) + [admission_user.get('username', '')]
    excluded = any(k in (exclude_group_roles or []) for k in keys)
    roles = user_info.get('roles') or []
    if roles and not excluded:
        if not any(r in roles for r in (admission_info.get('roles') or [])):
            errs.append('user info does not match roles for the given conditionBlock')
    cluster_roles = user_info.get('clusterRoles') or []
    if cluster_roles and not excluded:
        if not any(r in cluster_roles for r in (admission_info.get('clusterRoles') or [])):
            errs.append('user info does not match clustersRoles for the given conditionBlock')
    subjects = user_info.get('subjects') or []
    if subjects:
        if not check_subjects(subjects, admission_user, exclude_group_roles):
            errs.append('user info does not match subject for the given conditionBlock')
    return errs


def check_subjects(rule_subjects: List[dict], user_info: dict,
                   exclude_group_roles: List[str]) -> bool:
    # reference: pkg/utils/match/subjects.go:10
    sa_prefix = 'system:serviceaccount:'
    username = user_info.get('username', '') or ''
    user_groups = list(user_info.get('groups') or []) + [username]
    subjects = list(rule_subjects)
    for e in exclude_group_roles or []:
        subjects.append({'kind': 'Group', 'name': e})
    for subject in subjects:
        kind = subject.get('kind', '')
        if kind == 'ServiceAccount':
            if len(username) <= len(sa_prefix):
                continue
            expected = f"{subject.get('namespace', '')}:{subject.get('name', '')}"
            if username[len(sa_prefix):] == expected:
                return True
        elif kind in ('User', 'Group'):
            if subject.get('name') in user_groups:
                return True
    return False
