"""Background rule filtering (reference: pkg/engine/background.go,
pkg/engine/generation.go).

``filter_background_rules`` decides which generate / mutate-existing rules
of a policy apply to a trigger resource (reference name:
ApplyBackgroundChecks; renamed here because ``Engine.apply_background_checks``
is the background-scan validate entry); the background controller then
materializes the applicable rules (kyverno_tpu.background.generate).
``generate_response`` is the UpdateRequest-driven variant used when
replaying a UR (reference: pkg/engine/generation.go:14 GenerateResponse).
"""

from __future__ import annotations

import time
from typing import Optional

from ..api.policy import Rule
from ..api.unstructured import Resource
from .api import EngineResponse, PolicyContext, RuleResponse, RuleStatus, RuleType
from .match import matches_resource_description
from .variables import (
    substitute_all_in_preconditions,
)
from .operators import evaluate_conditions


def is_mutate_existing(rule: Rule) -> bool:
    """reference: api/kyverno/v1/rule_types.go IsMutateExisting"""
    return bool(rule.mutation.get('targets'))


def filter_background_rules(engine, pctx: PolicyContext) -> EngineResponse:
    """reference: pkg/engine/background.go:20 ApplyBackgroundChecks"""
    start = time.time()
    resp = EngineResponse(pctx.policy)
    apply_rules = pctx.policy.apply_rules
    for raw_rule in engine._compute_rules(pctx.policy):
        rule = Rule(raw_rule)
        rule_resp = _filter_rule(engine, rule, pctx)
        if rule_resp is not None:
            resp.policy_response.rules.append(rule_resp)
            if apply_rules == 'One' and rule_resp.status != RuleStatus.SKIP:
                break
    engine._build_response(pctx, resp, start)
    return resp


def generate_response(engine, pctx: PolicyContext, ur: dict) -> EngineResponse:
    """reference: pkg/engine/generation.go:14 GenerateResponse — filters the
    generate rules of the UR's policy against the trigger resource."""
    start = time.time()
    resp = EngineResponse(pctx.policy)
    for raw_rule in engine._compute_rules(pctx.policy):
        rule = Rule(raw_rule)
        if not rule.has_generate():
            continue
        rule_resp = _filter_rule(engine, rule, pctx)
        if rule_resp is not None:
            resp.policy_response.rules.append(rule_resp)
    engine._build_response(pctx, resp, start)
    return resp


def _filter_rule(engine, rule: Rule,
                 pctx: PolicyContext) -> Optional[RuleResponse]:
    """reference: pkg/engine/background.go:77 filterRule"""
    if not rule.has_generate() and not is_mutate_existing(rule):
        return None
    rule_type = RuleType.GENERATION if rule.has_generate() else RuleType.MUTATION

    exception_resp = engine._check_exceptions(pctx, rule)
    if exception_resp is not None:
        return exception_resp

    new_res = Resource(pctx.new_resource)
    err = matches_resource_description(
        new_res, rule, pctx.admission_info, pctx.exclude_group_roles,
        pctx.namespace_labels, '', pctx.subresource)
    if err is not None:
        if rule_type == RuleType.GENERATION and pctx.old_resource:
            # the old resource matched: report Fail so the controller can
            # delete the downstream resources of the retired trigger
            # (reference: background.go:115-126)
            old_err = matches_resource_description(
                Resource(pctx.old_resource), rule, pctx.admission_info,
                pctx.exclude_group_roles, pctx.namespace_labels, '',
                pctx.subresource)
            if old_err is None:
                return RuleResponse(rule.name, rule_type, '', RuleStatus.FAIL)
        return None

    ctx = pctx.json_context
    ctx.checkpoint()
    try:
        try:
            engine.context_loader.load(rule.context, ctx,
                                       policy_name=pctx.policy.name,
                                       rule_name=rule.name)
        except Exception:
            return None
        try:
            conditions = substitute_all_in_preconditions(
                ctx, rule.preconditions)
        except Exception:
            return None
        if conditions is not None and not evaluate_conditions(ctx, conditions):
            return RuleResponse(rule.name, rule_type, '', RuleStatus.SKIP)
        return RuleResponse(rule.name, rule_type, '', RuleStatus.PASS)
    finally:
        ctx.restore()
