"""Variable ``{{...}}`` and reference ``$(...)`` substitution.

Re-implements the reference's substitution walk
(reference: pkg/engine/variables/vars.go):

* ``{{ expr }}`` — JMESPath evaluated against the context; if a string leaf
  is exactly one variable, the raw (possibly non-string) value replaces the
  leaf; otherwise the JSON-encoded value is spliced into the string
* nested variables are resolved by re-scanning after each substitution round
* ``\\{{ ... }}`` escapes to a literal ``{{ ... }}``
* ``$(./../path)`` — relative references into the same document (used in
  validate patterns); resolved against the origin pattern with an optional
  leading operator preserved
* the preconditions resolver swallows resolution failures and substitutes
  the error (returning the value unchanged downstream)
"""

from __future__ import annotations

import json
import re
from typing import Any, Callable, Optional, Tuple

from .context import Context, ContextError, InvalidVariableError

# reference: pkg/engine/variables/vars.go:22-34
RE_VARIABLES = re.compile(r'(^|[^\\])(\{\{(?:\{[^{}]*\}|[^{}])*\}\})')
RE_ESC_VARIABLES = re.compile(r'\\\{\{(?:\{[^{}]*\}|[^{}])*\}\}')
RE_REFERENCES = re.compile(r'^\$\(.[^\ ]*\)|[^\\]\$\(.[^\ ]*\)')
RE_ESC_REFERENCES = re.compile(r'\\\$\(.[^\ ]*\)')
RE_VARIABLE_INIT = re.compile(r'^\{\{(?:\{[^{}]*\}|[^{}])*\}\}')
RE_ELEMENT_INDEX = re.compile(r'{{\s*elementIndex\d*\s*}}')


class SubstitutionError(Exception):
    def __init__(self, msg: str, path: str = ''):
        super().__init__(msg)
        self.path = path


class NotResolvedReferenceError(SubstitutionError):
    pass


def is_variable(value: str) -> bool:
    return bool(RE_VARIABLES.search(value))


def is_reference(value: str) -> bool:
    return bool(RE_REFERENCES.search(value))


def _find_variables(value: str):
    """Return the list of {{...}} occurrences including a possible leading
    non-escape char (mirrors RegexVariables group behavior)."""
    return [m.group(0) for m in RE_VARIABLES.finditer(value)]


def replace_all_vars(src: str, repl: Callable[[str], str]) -> str:
    """Replace each {{...}} occurrence using ``repl`` (reference:
    pkg/engine/variables/vars.go:50 ReplaceAllVars)."""
    def wrapper(m: re.Match) -> str:
        return m.group(1) + repl(m.group(2))
    return RE_VARIABLES.sub(wrapper, src)


def _strip_braces(v: str) -> str:
    return v.replace('{{', '').replace('}}', '').strip()


# A resolver takes (context, variable_expr) and returns the value.
Resolver = Callable[[Context, str], Any]


def default_resolver(ctx: Context, variable: str) -> Any:
    return ctx.query(variable)


def tree_has_variables(document: Any) -> bool:
    """True when any string in the tree carries a ``{{..}}`` variable or
    a ``$(..)`` reference — var-free rule trees skip the per-resource
    deepcopy + substitution walk entirely (bulk-apply hot path).
    Memoized by identity: rule dicts are immutable for a policy's
    lifetime."""
    doc_id = id(document)
    hit = _VARFREE_CACHE.get(doc_id)
    if hit is not None and hit[0] is document:
        return hit[1]
    result = _scan_vars(document)
    if len(_VARFREE_CACHE) > 4096:
        _VARFREE_CACHE.clear()
    _VARFREE_CACHE[doc_id] = (document, result)
    return result


_VARFREE_CACHE: dict = {}


def _scan_vars(doc: Any) -> bool:
    if isinstance(doc, str):
        return '{{' in doc or '$(' in doc
    if isinstance(doc, dict):
        return any(_scan_vars(k) or _scan_vars(v) for k, v in doc.items())
    if isinstance(doc, list):
        return any(_scan_vars(v) for v in doc)
    return False


def substitute_all(ctx: Context, document: Any) -> Any:
    """Substitute references then variables across a JSON document
    (reference: pkg/engine/variables/vars.go:82 SubstituteAll).

    The output is READ-ONLY and may alias ``document``: subtrees with
    no variables/references are returned by reference (the
    ``_STATIC_TREES`` fast path in ``_traverse``), so mutating the
    result in place would corrupt the shared rule tree for every later
    resource.  Consumers must copy before mutating (the engine's
    appliers all do)."""
    document = substitute_references(document)
    return substitute_vars(ctx, document, default_resolver)


def substitute_all_in_preconditions(ctx: Context, document: Any) -> Any:
    # the preconditions resolver tolerates failures: unresolved vars raise,
    # caller treats that as "condition not met" (reference vars.go:66)
    document = substitute_references(document)
    return substitute_vars(ctx, document, default_resolver)


def substitute_vars(ctx: Optional[Context], document: Any,
                    resolver: Resolver) -> Any:
    # hoisted per call: querying request.operation per LEAF dominated
    # bulk substitution
    is_delete = _is_delete_request(ctx)
    return _traverse(document, document, '',
                     lambda leaf, doc, path: _substitute_vars_leaf(
                         ctx, leaf, resolver, path, is_delete))


def substitute_references(document: Any) -> Any:
    return _traverse(document, document, '',
                     lambda leaf, doc, path: _substitute_refs_leaf(
                         leaf, doc, path))


#: static-subtree memo for _traverse: rule trees are constants shared
#: across resources/elements, so a subtree with no variables and no
#: references is returned AS-IS (by reference).  Consumers treat
#: substitution output as read-only (the same contract context documents
#: already have), so the sharing is never observable.  The node object is
#: pinned in the value to guard against id() reuse.
_STATIC_TREES: dict = {}


def _tree_static(node: Any) -> bool:
    if isinstance(node, str):
        return '{{' not in node and '$(' not in node
    if isinstance(node, (int, float, bool)) or node is None:
        return True
    if isinstance(node, (dict, list)):
        key = id(node)
        hit = _STATIC_TREES.get(key)
        if hit is not None and hit[0] is node:
            return hit[1]
        if isinstance(node, dict):
            static = all(_tree_static(k) and _tree_static(v)
                         for k, v in node.items())
        else:
            static = all(_tree_static(v) for v in node)
        if len(_STATIC_TREES) > 16384:
            _STATIC_TREES.clear()
        _STATIC_TREES[key] = (node, static)
        return static
    return False


def _traverse(element: Any, document: Any, path: str,
              leaf_action: Callable[[Any, Any, str], Any]) -> Any:
    """Walk a JSON document applying ``leaf_action`` to leaves and map keys
    (reference: pkg/engine/jsonutils/traverse.go)."""
    if isinstance(element, (dict, list)) and _tree_static(element):
        return element
    if isinstance(element, dict):
        out = {}
        for key, value in element.items():
            new_key = leaf_action(key, document, path)
            if not isinstance(new_key, str):
                new_key = key
            # JSON-pointer escaping: a key containing '/' (label/
            # annotation domains) must stay one path component
            esc = str(key).replace('~', '~0').replace('/', '~1')
            out[new_key] = _traverse(value, document, f'{path}/{esc}',
                                     leaf_action)
        return out
    if isinstance(element, list):
        return [_traverse(v, document, f'{path}/{i}', leaf_action)
                for i, v in enumerate(element)]
    return leaf_action(element, document, path)


def _substitute_vars_leaf(ctx: Optional[Context], value: Any,
                          resolver: Resolver, path: str,
                          is_delete: Optional[bool] = None) -> Any:
    if not isinstance(value, str):
        return value
    if is_delete is None:
        is_delete = _is_delete_request(ctx)
    variables = _find_variables(value)
    while variables:
        original_pattern = value
        for occurrence in variables:
            initial = bool(RE_VARIABLE_INIT.match(occurrence))
            old = occurrence
            v = occurrence if initial else occurrence[1:]
            variable = _strip_braces(v)

            if variable == '@':
                variable = _at_to_path(ctx, path)

            if is_delete:
                variable = variable.replace('request.object', 'request.oldObject')

            try:
                substituted = resolver(ctx, variable)
            except (InvalidVariableError, ContextError) as e:
                raise SubstitutionError(
                    f'failed to resolve {variable} at path {path}: {e}',
                    path) from e

            if original_pattern == v:
                # whole leaf is one variable: return raw value
                return substituted

            prefix = '' if initial else old[0]
            value = _splice(prefix, value, v, substituted, variable, path)
        variables = _find_variables(value)

    value = RE_ESC_VARIABLES.sub(lambda m: m.group(0)[1:], value)
    return value


def _splice(prefix: str, pattern: str, variable_text: str, value: Any,
            variable: str, path: str) -> str:
    if isinstance(value, str):
        s = value
    else:
        try:
            s = json.dumps(value, separators=(',', ':'))
        except (TypeError, ValueError) as e:
            raise SubstitutionError(
                f'failed to resolve {variable} at path {path}: {e}', path)
    return pattern.replace(prefix + variable_text, prefix + s, 1)


def _at_to_path(ctx: Optional[Context], path: str) -> str:
    """Translate the ``@`` self-reference into an absolute JMESPath
    (reference: pkg/engine/variables/vars.go:367-380)."""
    prefix = 'request.object'
    if ctx is not None:
        try:
            if ctx.query('target') is not None:
                prefix = 'target'
        except (ContextError, InvalidVariableError):
            pass
    parts = [p.replace('~1', '/').replace('~0', '~')
             for p in path.split('/') if p != '']
    # skip past "foreach" if present, then the leading two elements
    if 'foreach' in parts:
        parts = parts[parts.index('foreach') + 1:]
    parts = parts[2:]
    segments = prefix.split('.')
    for p in parts:
        if p.isdigit():
            if segments:
                segments[-1] = f'{segments[-1]}[{p}]'
        else:
            if not re.fullmatch(r'[A-Za-z_][A-Za-z0-9_]*', p):
                # quoted identifier for keys JMESPath cannot take bare
                # (reference: pkg/utils/jsonpointer/pointer.go:139
                # JMESPath())
                p = '"' + p.replace('\\', '\\\\').replace('"', '\\"') + '"'
            segments.append(p)
    return '.'.join(segments)


def _is_delete_request(ctx: Optional[Context]) -> bool:
    if ctx is None:
        return False
    try:
        return ctx.query('request.operation') == 'DELETE'
    except (ContextError, InvalidVariableError):
        return False


# ---------------------------------------------------------------------------
# $(...) references

def _substitute_refs_leaf(value: Any, document: Any, path: str) -> Any:
    if not isinstance(value, str):
        return value
    for m in list(RE_REFERENCES.finditer(value)):
        occurrence = m.group(0)
        initial = occurrence.startswith('$(')
        old = occurrence
        ref = occurrence if initial else occurrence[1:]
        resolved = _resolve_reference(document, ref, path)
        if resolved is None:
            raise SubstitutionError(
                f'got nil resolved variable {ref} at path {path}', path)
        if isinstance(resolved, str):
            replacement = ('' if initial else old[0]) + resolved
            value = value.replace(old, replacement, 1)
            continue
        raise NotResolvedReferenceError(
            f'NotResolvedReferenceErr,reference {ref} not resolved at path '
            f'{path}', path)
    value = RE_ESC_REFERENCES.sub(lambda m2: m2.group(0)[1:], value)
    return value


def _resolve_reference(document: Any, reference: str, absolute_path: str) -> Any:
    from . import pattern as leaf_pattern
    path = reference.strip('$()')
    op = leaf_pattern.get_operator_from_string_pattern(path)
    path = path[len(op):]
    if not path:
        raise SubstitutionError('expected path, found empty reference')
    path = _form_absolute_path(path, absolute_path)
    value = _get_value_by_pointer(document, path)
    if op == '':
        return value
    if isinstance(value, str):
        return op + value
    if isinstance(value, bool):
        raise SubstitutionError(
            f'incorrect expression: operator {op} does not match with value '
            f'{value}')
    if isinstance(value, int):
        return f'{op}{value}'
    if isinstance(value, float):
        return f'{op}{value:f}'
    raise SubstitutionError(
        f'incorrect expression: operator {op} does not match with value {value}')


def _form_absolute_path(reference_path: str, absolute_path: str) -> str:
    import posixpath
    if reference_path.startswith('/'):
        return reference_path
    return posixpath.normpath(posixpath.join(absolute_path, reference_path))


def _get_value_by_pointer(document: Any, pointer: str) -> Any:
    from .anchor import remove_anchor
    cur = document
    # traversal paths are JSON-pointer escaped (~1 = '/', ~0 = '~')
    for part in [p.replace('~1', '/').replace('~0', '~')
                 for p in pointer.split('/') if p]:
        if isinstance(cur, dict):
            if part in cur:
                cur = cur[part]
                continue
            # try anchored keys
            found = False
            for k in cur:
                bare, _mod = remove_anchor(k)
                if bare == part:
                    cur = cur[k]
                    found = True
                    break
            if not found:
                raise SubstitutionError(
                    f'failed to resolve reference: path {pointer} not found')
        elif isinstance(cur, list):
            try:
                cur = cur[int(part)]
            except (ValueError, IndexError):
                raise SubstitutionError(
                    f'failed to resolve reference: path {pointer} not found')
        else:
            raise SubstitutionError(
                f'failed to resolve reference: path {pointer} not found')
    return cur


def validate_element_in_foreach(document: Any) -> None:
    """Raise if element/elementIndex variables appear outside a foreach block
    (reference: pkg/engine/variables/vars.go:252 ValidateElementInForEach)."""
    def leaf(value, doc, path):
        if isinstance(value, str):
            for occurrence in _find_variables(value):
                v = occurrence if RE_VARIABLE_INIT.match(occurrence) else occurrence[1:]
                variable = _strip_braces(v)
                is_element = variable.startswith('element') or variable == 'elementIndex'
                if is_element and '/foreach/' not in path:
                    raise SubstitutionError(
                        f"variable '{variable}' present outside of foreach at "
                        f"path {path}", path)
        return value
    _traverse(document, document, '', leaf)
