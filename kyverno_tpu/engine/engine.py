"""The policy engine: stateless Validate entry (Mutate lives in mutate/).

Re-implements the reference's validation flow
(reference: pkg/engine/validation.go): autogen expansion → per-rule
match/exclude → policy exceptions → context loading → preconditions →
deny / pattern / anyPattern / podSecurity / foreach dispatch, with
bit-compatible rule messages and statuses.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..api.policy import Policy, Rule
from ..api.unstructured import Resource
from ..autogen.autogen import compute_rules
from . import operators
from . import variables as vars_mod
from .api import (EngineResponse, PolicyContext, RuleResponse, RuleStatus,
                  RuleType)
from .context import Context, ContextError, InvalidVariableError
from .match import matches_resource_description, check_kind
from .match import check_selector  # noqa: F401  (re-exported for callers)
from .validate_pattern import PatternError, match_pattern
from .variables import SubstitutionError


class ContextLoader:
    """Loads rule ``context:`` entries into the JSON context
    (reference: pkg/engine/jsonContext.go:126 LoadContext).

    ``configmap_resolver(name, namespace) -> dict`` and
    ``api_call(entry, ctx) -> Any`` are pluggable; the defaults raise, which
    surfaces as a rule error exactly like a failed network call would.
    """

    def __init__(self,
                 configmap_resolver: Optional[Callable[[str, str], Optional[dict]]] = None,
                 api_call: Optional[Callable[[dict, Context], Any]] = None,
                 image_data: Optional[Callable[[dict, Context], Any]] = None):
        self.configmap_resolver = configmap_resolver
        self.api_call = api_call
        self.image_data = image_data

    def load(self, entries: List[dict], ctx: Context,
             policy_name: str = '', rule_name: str = '') -> None:
        """``policy_name``/``rule_name`` identify the calling rule so mock
        loaders (CLI values files, reference: pkg/engine/jsonContext.go:88)
        can inject per-rule variables; the real loader ignores them."""
        del policy_name, rule_name
        for entry in entries:
            name = entry.get('name', '')
            if entry.get('configMap') is not None:
                self._load_configmap(entry, ctx)
            elif entry.get('apiCall') is not None:
                if self.api_call is None:
                    raise ContextError(
                        f'failed to load context entry {name}: no API client')
                data = self.api_call(entry, ctx)
                ctx.add_context_entry(name, data)
            elif entry.get('imageRegistry') is not None:
                if self.image_data is None:
                    raise ContextError(
                        f'failed to load context entry {name}: no registry client')
                data = self.image_data(entry, ctx)
                ctx.add_context_entry(name, data)
            elif entry.get('variable') is not None:
                self._load_variable(entry, ctx)

    def _load_variable(self, entry: dict, ctx: Context) -> None:
        # reference: pkg/engine/jsonContext.go:130 loadVariable
        name = entry.get('name', '')
        var = entry.get('variable') or {}
        path = ''
        if var.get('jmesPath'):
            path = vars_mod.substitute_all(ctx, var['jmesPath'])
        default_value = None
        if var.get('default') is not None:
            default_value = vars_mod.substitute_all(ctx, var['default'])
        output = default_value
        if var.get('value') is not None:
            value = vars_mod.substitute_all(ctx, var['value'])
            if path:
                try:
                    from . import jmespath as jp
                    output = jp.search(path, value)
                except jp.JMESPathError as e:
                    if default_value is None:
                        raise ContextError(
                            f'failed to apply jmespath {path} to variable '
                            f'{var["value"]}: {e}') from e
            else:
                output = value
        elif path:
            try:
                result = ctx.query(path)
                if result is not None:
                    output = result
                elif default_value is None:
                    output = result
            except (ContextError, InvalidVariableError) as e:
                if default_value is None:
                    raise ContextError(
                        f'failed to apply jmespath {path} to variable: {e}') from e
        if output is None:
            raise ContextError(
                f'unable to add context entry for variable {name} since it '
                f'evaluated to nil')
        ctx.replace_context_entry(name, output)

    def _load_configmap(self, entry: dict, ctx: Context) -> None:
        name = entry.get('name', '')
        cm = entry.get('configMap') or {}
        cm_name = vars_mod.substitute_all(ctx, cm.get('name', ''))
        cm_ns = vars_mod.substitute_all(ctx, cm.get('namespace', '') or 'default')
        if self.configmap_resolver is None:
            raise ContextError(
                f'failed to load context entry {name}: no ConfigMap resolver')
        try:
            data = self.configmap_resolver(cm_name, cm_ns)
        except ContextError:
            raise
        except Exception as e:  # noqa: BLE001 - a missing ConfigMap is a
            # context-load failure, not an engine crash (reference:
            # jsonContext.go:307 'failed to retrieve config map...')
            raise ContextError(
                f'failed to retrieve config map for context entry '
                f'{name}: {e}')
        if data is None:
            raise ContextError(
                f'failed to get configmap {cm_ns}/{cm_name}')
        ctx.replace_context_entry(name, data)


class Engine:
    """Stateless policy engine (reference: pkg/engine)."""

    def __init__(self, context_loader: Optional[ContextLoader] = None,
                 pss_evaluator: Optional[Callable] = None):
        self.context_loader = context_loader or ContextLoader()
        if pss_evaluator is None:
            from ..pss.evaluate import evaluate_pod_security
            pss_evaluator = evaluate_pod_security
        self.pss_evaluator = pss_evaluator
        # autogen expansion memo: policies are immutable during evaluation
        self._rules_cache: Dict[int, Tuple[dict, List[dict]]] = {}

    _RULES_CACHE_MAX = 512

    def _compute_rules(self, policy: Policy) -> List[dict]:
        # the cache entry holds a strong reference to the keyed dict so the
        # id cannot be recycled; identity is re-verified on every hit and
        # the cache is bounded (FIFO eviction) for long-lived engines
        key = id(policy.raw)
        entry = self._rules_cache.get(key)
        if entry is not None and entry[0] is policy.raw:
            return entry[1]
        rules = compute_rules(policy)
        if len(self._rules_cache) >= self._RULES_CACHE_MAX:
            # webhook threads share one engine: two threads evicting at
            # once can race next(iter)/pop — eviction is best-effort
            try:
                self._rules_cache.pop(next(iter(self._rules_cache)))
            except (KeyError, StopIteration, RuntimeError):
                pass
        self._rules_cache[key] = (policy.raw, rules)
        return rules

    # -- public entry points -------------------------------------------------

    def validate(self, policy_context: PolicyContext) -> EngineResponse:
        """reference: pkg/engine/validation.go:39 Validate"""
        start = time.time()
        resp = self._validate_resource(policy_context)
        resp.namespace_labels = policy_context.namespace_labels
        self._build_response(policy_context, resp, start)
        return resp

    def mutate(self, policy_context: PolicyContext) -> EngineResponse:
        """reference: pkg/engine/mutation.go:24 Mutate"""
        from .mutate.mutate import mutate as mutate_impl
        return mutate_impl(self, policy_context)

    def apply_background_checks(self, policy_context: PolicyContext) -> EngineResponse:
        """Background-scan entry: same as validate but only if the policy has
        background enabled (reference: pkg/engine/background.go:20)."""
        if not policy_context.policy.background:
            resp = EngineResponse(policy_context.policy)
            self._build_response(policy_context, resp, time.time())
            return resp
        return self.validate(policy_context)

    def filter_background_rules(self, policy_context: PolicyContext) -> EngineResponse:
        """Filter generate / mutate-existing rules applicable to a trigger
        (reference: pkg/engine/background.go:20 ApplyBackgroundChecks)."""
        from .background import filter_background_rules as impl
        return impl(self, policy_context)

    def generate_response(self, policy_context: PolicyContext,
                          ur: dict) -> EngineResponse:
        """reference: pkg/engine/generation.go:14 GenerateResponse"""
        from .background import generate_response as impl
        return impl(self, policy_context, ur)

    def verify_and_patch_images(self, policy_context: PolicyContext,
                                rclient=None):
        """reference: pkg/engine/imageVerify.go:69 VerifyAndPatchImages —
        returns (EngineResponse, ImageVerificationMetadata)."""
        from .image_verify import verify_and_patch_images as impl
        return impl(self, policy_context, rclient)

    # -- internals -----------------------------------------------------------

    def _build_response(self, pctx: PolicyContext, resp: EngineResponse,
                        start: float) -> None:
        if resp.patched_resource is None:
            resp.patched_resource = pctx.new_resource or pctx.old_resource
        policy = pctx.policy
        resp.policy = policy
        pr = resp.policy_response
        pr.policy_name = policy.name
        pr.policy_namespace = policy.namespace
        patched = Resource(resp.patched_resource)
        pr.resource_name = patched.name
        pr.resource_namespace = patched.namespace
        pr.resource_kind = patched.kind
        pr.resource_api_version = patched.api_version
        pr.validation_failure_action = policy.validation_failure_action
        pr.validation_failure_action_overrides = \
            policy.validation_failure_action_overrides
        pr.processing_time = time.time() - start
        pr.timestamp = int(start)

    def _validate_resource(self, pctx: PolicyContext) -> EngineResponse:
        # reference: pkg/engine/validation.go:106 validateResource
        resp = EngineResponse(pctx.policy)
        pctx.json_context.checkpoint()
        try:
            rules = self._compute_rules(pctx.policy)
            apply_rules = pctx.policy.apply_rules
            policy = pctx.policy

            if policy.is_namespaced:
                pol_ns = policy.namespace
                new_r, old_r = Resource(pctx.new_resource), Resource(pctx.old_resource)
                if pctx.new_resource and (new_r.namespace != pol_ns or new_r.namespace == ''):
                    return resp
                if pctx.old_resource and (old_r.namespace != pol_ns or old_r.namespace == ''):
                    return resp

            from ..observability import tracing
            for raw_rule in rules:
                rule = Rule(raw_rule)
                pctx.json_context.reset()
                start = time.time()
                # per-rule child span (reference: pkg/engine/validation.go:139
                # via pkg/tracing/childspan.go ChildSpan1)
                with tracing.start_span(
                        'kyverno/engine/rule',
                        {'policy': policy.name, 'rule': rule.name}) as span:
                    rule_resp = self._process_rule(pctx, rule)
                    if rule_resp is not None:
                        span.set_attribute('status', rule_resp.status)
                if rule_resp is not None:
                    self._add_rule_response(resp, rule_resp, start)
                    if apply_rules == 'One' and \
                            resp.policy_response.rules_applied_count > 0:
                        break
            return resp
        finally:
            pctx.json_context.restore()

    def _process_rule(self, pctx: PolicyContext,
                      rule: Rule) -> Optional[RuleResponse]:
        has_validate = rule.has_validate()
        # reference: api/kyverno/v1/rule_types.go:107
        # HasImagesValidationChecks (verifyDigest/required default true)
        has_validate_image = any(
            iv.get('verifyDigest', True) or iv.get('required', True)
            for iv in rule.verify_images)
        if not has_validate and not has_validate_image:
            return None
        if not self._matches(rule, pctx):
            return None
        exception_resp = self._check_exceptions(pctx, rule)
        if exception_resp is not None:
            return exception_resp
        pctx.json_context.reset()
        if has_validate:
            # manifests rules also flow through Validator so context
            # loading and preconditions run first
            # (reference: pkg/engine/validation.go:185)
            return Validator(self, pctx, rule).validate()
        if has_validate_image:
            from .image_verify import process_image_validation_rule
            return process_image_validation_rule(self, pctx, rule)
        return None

    def _matches(self, rule: Rule, pctx: PolicyContext) -> bool:
        # reference: pkg/engine/validation.go:600 matches
        err = matches_resource_description(
            Resource(pctx.new_resource), rule, pctx.admission_info,
            pctx.exclude_group_roles, pctx.namespace_labels, '',
            pctx.subresource, pctx.subresources_in_policy)
        if err is None:
            return True
        if pctx.old_resource:
            err = matches_resource_description(
                Resource(pctx.old_resource), rule, pctx.admission_info,
                pctx.exclude_group_roles, pctx.namespace_labels, '',
                pctx.subresource, pctx.subresources_in_policy)
            if err is None:
                return True
        return False

    def _check_exceptions(self, pctx: PolicyContext,
                          rule: Rule) -> Optional[RuleResponse]:
        # reference: pkg/engine/validation.go:826 hasPolicyExceptions
        from .match import _check_filter  # reuse filter matching
        for exception in pctx.find_exceptions(rule.name):
            match = (exception.get('spec') or {}).get('match') or {}
            matched = False
            any_f = match.get('any') or []
            all_f = match.get('all') or []
            res = Resource(pctx.new_resource)
            if any_f:
                matched = any(not _check_filter(
                    f, res, pctx.admission_info, pctx.exclude_group_roles,
                    pctx.namespace_labels, pctx.subresource) for f in any_f)
            elif all_f:
                matched = all(not _check_filter(
                    f, res, pctx.admission_info, pctx.exclude_group_roles,
                    pctx.namespace_labels, pctx.subresource) for f in all_f)
            if matched:
                meta = exception.get('metadata') or {}
                key = f"{meta.get('namespace', '')}/{meta.get('name', '')}" \
                    if meta.get('namespace') else meta.get('name', '')
                return RuleResponse(
                    rule.name, RuleType.VALIDATION,
                    f'rule skipped due to policy exception {key}',
                    RuleStatus.SKIP)
        return None

    def _add_rule_response(self, resp: EngineResponse,
                           rule_resp: RuleResponse, start: float) -> None:
        rule_resp.processing_time = time.time() - start
        rule_resp.timestamp = int(start)
        if rule_resp.status in (RuleStatus.PASS, RuleStatus.FAIL):
            resp.policy_response.rules_applied_count += 1
        elif rule_resp.status == RuleStatus.ERROR:
            resp.policy_response.rules_error_count += 1
        resp.policy_response.rules.append(rule_resp)


def _rule_response(rule: Rule, rule_type: str, message: str,
                   status: str) -> RuleResponse:
    return RuleResponse(rule.name, rule_type, message, status)


def _rule_error(rule: Rule, rule_type: str, message: str,
                err: Exception) -> RuleResponse:
    return RuleResponse(rule.name, rule_type, f'{message}: {err}',
                        RuleStatus.ERROR)


class Validator:
    """Per-rule validator (reference: pkg/engine/validation.go:210)."""

    def __init__(self, engine: Engine, pctx: PolicyContext, rule: Rule,
                 foreach_entry: Optional[dict] = None, nesting: int = 0):
        self.engine = engine
        self.pctx = pctx
        # no deep copy: the rule dict is never mutated (substitution builds
        # new objects; self.pattern is rebound, not written through)
        self.rule = rule
        self.nesting = nesting
        if foreach_entry is None:
            v = self.rule.validation
            self.context_entries = self.rule.context
            self.any_all_conditions = self.rule.preconditions
            self.pattern = v.get('pattern')
            self.any_pattern = v.get('anyPattern')
            self.deny = v.get('deny')
            self.pod_security = v.get('podSecurity')
            self.manifests = v.get('manifests')
            self.foreach = v.get('foreach')
        else:
            self.context_entries = foreach_entry.get('context') or []
            self.any_all_conditions = foreach_entry.get('preconditions')
            self.pattern = foreach_entry.get('pattern')
            self.any_pattern = foreach_entry.get('anyPattern')
            self.deny = foreach_entry.get('deny')
            self.pod_security = None
            self.manifests = None
            self.foreach = foreach_entry.get('foreach')

    # -- entry ---------------------------------------------------------------

    def validate(self) -> Optional[RuleResponse]:
        # reference: pkg/engine/validation.go:276 validate
        try:
            self.engine.context_loader.load(
                self.context_entries, self.pctx.json_context,
                policy_name=self.pctx.policy.name, rule_name=self.rule.name)
        except (ContextError, SubstitutionError, InvalidVariableError) as e:
            return _rule_error(self.rule, RuleType.VALIDATION,
                               'failed to load context', e)
        try:
            passed = self._check_preconditions()
        except (ContextError, SubstitutionError, InvalidVariableError) as e:
            return _rule_error(self.rule, RuleType.VALIDATION,
                               'failed to evaluate preconditions', e)
        if not passed:
            return _rule_response(self.rule, RuleType.VALIDATION,
                                  'preconditions not met', RuleStatus.SKIP)
        if self.deny is not None:
            return self._validate_deny()
        if self.pattern is not None or self.any_pattern is not None:
            try:
                self._substitute_patterns()
            except (SubstitutionError, ContextError, InvalidVariableError) as e:
                return _rule_error(self.rule, RuleType.VALIDATION,
                                   'variable substitution failed', e)
            return self._validate_resource_with_rule()
        if self.pod_security is not None:
            if not self._is_delete_request():
                return self._validate_pod_security()
        if self.manifests is not None:
            # reference: pkg/engine/validation.go processYAMLValidationRule
            from .k8smanifest import process_yaml_validation_rule
            return process_yaml_validation_rule(self.pctx, self.rule)
        if self.foreach is not None:
            return self._validate_foreach()
        return None

    # -- preconditions -------------------------------------------------------

    def _check_preconditions(self) -> bool:
        # reference: pkg/engine/utils.go:328 checkPreconditions
        conditions = self.any_all_conditions
        if conditions is None:
            return True
        substituted = vars_mod.substitute_all_in_preconditions(
            self.pctx.json_context, conditions)
        return operators.evaluate_conditions(self.pctx.json_context,
                                             substituted)

    # -- deny ----------------------------------------------------------------

    def _validate_deny(self) -> RuleResponse:
        # reference: pkg/engine/validation.go:437 validateDeny
        try:
            conditions = vars_mod.substitute_all(
                self.pctx.json_context, (self.deny or {}).get('conditions'))
        except (SubstitutionError, ContextError, InvalidVariableError) as e:
            return _rule_error(self.rule, RuleType.VALIDATION,
                               'failed to substitute variables in deny '
                               'conditions', e)
        deny = operators.evaluate_conditions(self.pctx.json_context,
                                             conditions)
        if deny:
            return _rule_response(self.rule, RuleType.VALIDATION,
                                  self._deny_message(True), RuleStatus.FAIL)
        return _rule_response(self.rule, RuleType.VALIDATION,
                              self._deny_message(False), RuleStatus.PASS)

    def _deny_message(self, deny: bool) -> str:
        # reference: pkg/engine/validation.go:460 getDenyMessage
        if not deny:
            return f"validation rule '{self.rule.name}' passed."
        msg = self.rule.validation.get('message', '')
        if not msg:
            return f'validation error: rule {self.rule.name} failed'
        try:
            raw = vars_mod.substitute_all(self.pctx.json_context, msg)
        except (SubstitutionError, ContextError, InvalidVariableError):
            return msg
        if isinstance(raw, str):
            return raw
        return ("the produced message didn't resolve to a string, check your "
                "policy definition.")

    # -- patterns ------------------------------------------------------------

    def _substitute_patterns(self) -> None:
        if self.pattern is not None:
            self.pattern = vars_mod.substitute_all(self.pctx.json_context,
                                                   self.pattern)
        elif self.any_pattern is not None:
            self.any_pattern = vars_mod.substitute_all(self.pctx.json_context,
                                                       self.any_pattern)

    def _is_delete_request(self) -> bool:
        return not self.pctx.new_resource

    def _validate_resource_with_rule(self) -> Optional[RuleResponse]:
        element = self.pctx.element
        if element:
            return self._validate_patterns(element)
        if self._is_delete_request():
            return None
        return self._validate_patterns(self.pctx.new_resource)

    def _validate_patterns(self, resource: dict) -> RuleResponse:
        # reference: pkg/engine/validation.go:618 validatePatterns
        rule = self.rule
        if self.pattern is not None:
            try:
                match_pattern(resource, self.pattern)
            except PatternError as pe:
                if pe.skip:
                    return _rule_response(rule, RuleType.VALIDATION, str(pe),
                                          RuleStatus.SKIP)
                if pe.path == '':
                    return _rule_response(rule, RuleType.VALIDATION,
                                          self._error_message(pe, ''),
                                          RuleStatus.ERROR)
                return _rule_response(rule, RuleType.VALIDATION,
                                      self._error_message(pe, pe.path),
                                      RuleStatus.FAIL)
            return _rule_response(
                rule, RuleType.VALIDATION,
                f"validation rule '{rule.name}' passed.", RuleStatus.PASS)

        if self.any_pattern is not None:
            failed, skipped = [], []
            patterns = self.any_pattern
            if not isinstance(patterns, list):
                return _rule_response(
                    rule, RuleType.VALIDATION,
                    'failed to deserialize anyPattern, expected type array',
                    RuleStatus.ERROR)
            for idx, pattern in enumerate(patterns):
                try:
                    match_pattern(resource, pattern)
                    return _rule_response(
                        rule, RuleType.VALIDATION,
                        f"validation rule '{rule.name}' anyPattern[{idx}] "
                        f"passed.", RuleStatus.PASS)
                except PatternError as pe:
                    if pe.skip:
                        skipped.append(
                            f'rule {rule.name}[{idx}] skipped: {pe}')
                    else:
                        if pe.path == '':
                            failed.append(
                                f'rule {rule.name}[{idx}] failed: {pe}')
                        else:
                            failed.append(
                                f'rule {rule.name}[{idx}] failed at path '
                                f'{pe.path}')
            if skipped and not failed:
                return _rule_response(rule, RuleType.VALIDATION,
                                      ' '.join(skipped), RuleStatus.SKIP)
            if failed:
                return _rule_response(
                    rule, RuleType.VALIDATION,
                    self._any_pattern_message(failed), RuleStatus.FAIL)

        return _rule_response(rule, RuleType.VALIDATION,
                              self.rule.validation.get('message', ''),
                              RuleStatus.PASS)

    def _error_message(self, err: Exception, path: str) -> str:
        # reference: pkg/engine/validation.go:722 buildErrorMessage
        rule = self.rule
        msg = rule.validation.get('message', '')
        if not msg:
            if path:
                return f'validation error: rule {rule.name} failed at path {path}'
            return (f'validation error: rule {rule.name} execution error: '
                    f'{err}')
        try:
            msg = vars_mod.substitute_all(self.pctx.json_context, msg)
        except (SubstitutionError, ContextError, InvalidVariableError):
            return (f'validation error: variables substitution error in rule '
                    f'{rule.name} execution error: {err}')
        if not isinstance(msg, str):
            msg = str(msg)
        if not msg.endswith('.'):
            msg += '.'
        if path:
            return f'validation error: {msg} rule {rule.name} failed at path {path}'
        return f'validation error: {msg} rule {rule.name} execution error: {err}'

    def _any_pattern_message(self, errors: List[str]) -> str:
        # reference: pkg/engine/validation.go:746 buildAnyPatternErrorMessage
        err_str = ' '.join(errors)
        msg = self.rule.validation.get('message', '')
        if not msg:
            return f'validation error: {err_str}'
        if msg.endswith('.'):
            return f'validation error: {msg} {err_str}'
        return f'validation error: {msg}. {err_str}'

    # -- pod security --------------------------------------------------------

    def _validate_pod_security(self) -> RuleResponse:
        # reference: pkg/engine/validation.go:535 validatePodSecurity
        from ..pss.evaluate import extract_pod_spec
        rule = self.rule
        try:
            pod = extract_pod_spec(self.pctx.new_resource)
        except ValueError as e:
            return _rule_error(rule, RuleType.VALIDATION,
                               'Error while getting new resource', e)
        try:
            allowed, checks = self.engine.pss_evaluator(self.pod_security, pod)
        except ValueError as e:
            return _rule_error(rule, RuleType.VALIDATION,
                               'failed to parse pod security api version', e)
        level = self.pod_security.get('level', '')
        version = self.pod_security.get('version', '')
        psc = {'level': level, 'version': version, 'checks': checks}
        if allowed:
            r = _rule_response(rule, RuleType.VALIDATION,
                               f"Validation rule '{rule.name}' passed.",
                               RuleStatus.PASS)
        else:
            from ..pss.evaluate import format_checks_print
            r = _rule_response(
                rule, RuleType.VALIDATION,
                f"Validation rule '{rule.name}' failed. It violates "
                f'PodSecurity "{level}:{version}": '
                f'{format_checks_print(checks)}', RuleStatus.FAIL)
        r.pod_security_checks = psc
        return r

    # -- foreach -------------------------------------------------------------

    def _validate_foreach(self) -> Optional[RuleResponse]:
        # reference: pkg/engine/validation.go:319 validateForEach
        apply_count = 0
        for foreach in self.foreach or []:
            try:
                elements = self._evaluate_list(foreach.get('list', ''))
            except (ContextError, InvalidVariableError):
                continue
            resp, count = self._validate_elements(foreach, elements,
                                                  foreach.get('elementScope'))
            if resp.status != RuleStatus.PASS:
                return resp
            apply_count += count
        if apply_count == 0:
            if not self.foreach:
                return None
            return _rule_response(self.rule, RuleType.VALIDATION,
                                  'rule skipped', RuleStatus.SKIP)
        return _rule_response(self.rule, RuleType.VALIDATION, 'rule passed',
                              RuleStatus.PASS)

    def _evaluate_list(self, jmespath_expr: str) -> List[Any]:
        result = self.pctx.json_context.query(jmespath_expr)
        if isinstance(result, list):
            return result
        return [result]

    def _validate_elements(self, foreach: dict, elements: List[Any],
                           element_scope: Optional[bool]):
        # reference: pkg/engine/validation.go:347 validateElements
        ctx = self.pctx.json_context
        ctx.checkpoint()
        try:
            apply_count = 0
            for index, element in enumerate(elements):
                if element is None:
                    continue
                ctx.reset()
                pctx = self.pctx.copy()
                try:
                    _add_element_to_context(pctx, element, index, self.nesting,
                                            element_scope)
                except ValueError as e:
                    return (_rule_error(self.rule, RuleType.VALIDATION,
                                        'failed to process foreach', e),
                            apply_count)
                sub = Validator(self.engine, pctx, self.rule,
                                foreach_entry=foreach,
                                nesting=self.nesting + 1)
                r = sub.validate()
                if r is None or r.status == RuleStatus.SKIP:
                    continue
                if r.status != RuleStatus.PASS:
                    if r.status == RuleStatus.ERROR and index < len(elements) - 1:
                        continue
                    return (_rule_response(
                        self.rule, RuleType.VALIDATION,
                        f'validation failure: {r.message}', r.status),
                        apply_count)
                apply_count += 1
            return (_rule_response(self.rule, RuleType.VALIDATION, '',
                                   RuleStatus.PASS), apply_count)
        finally:
            ctx.restore()


def _add_element_to_context(pctx: PolicyContext, element: Any, index: int,
                            nesting: int, element_scope: Optional[bool]) -> None:
    # reference: pkg/engine/validation.go:391 addElementToContext
    pctx.json_context.add_element(element, index, nesting)
    is_map = isinstance(element, dict)
    scoped = is_map
    if element_scope is not None:
        if element_scope and not is_map:
            raise ValueError(
                'cannot use elementScope=true foreach rules for elements that '
                f'are not maps, expected type=map got type={type(element).__name__}')
        scoped = element_scope
    if scoped:
        pctx.set_element(element)
