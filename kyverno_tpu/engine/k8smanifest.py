"""``validate.manifests`` — sigstore k8s-manifest signature verification.

Reference: pkg/engine/k8smanifest.go (processYAMLValidationRule:38,
verifyManifest:59, verifyManifestAttestorSet:155). The signing scheme
(sigstore/k8s-manifest-sigstore): the resource carries annotations

    cosign.sigstore.dev/message    = base64(gzip(tar.gz(manifest yaml)))
    cosign.sigstore.dev/signature  = base64(ASN.1 ECDSA-P256-SHA256 sig)
    cosign.sigstore.dev/signature_1, _2 ...  (multi-sig)

where the signed blob is the once-gunzipped message (the inner tar.gz
bytes). Verification is fully offline: check the signature(s) against the
attestor public keys, then diff the manifest inside the message against
the admitted resource modulo the ignore-field config
(reference: pkg/engine/resources/default-config.yaml).
"""

from __future__ import annotations

import base64
import fnmatch
import gzip
import io
import tarfile
from typing import Any, Dict, List, Optional, Tuple

import yaml

DEFAULT_ANNOTATION_DOMAIN = 'cosign.sigstore.dev'

# reference: pkg/engine/resources/default-config.yaml (kyverno's extra
# ignore fields) + k8s-manifest-sigstore default-config.yaml semantics —
# fields added by the API server / kubectl that must not count as mutation
_DEFAULT_IGNORE_FIELDS: List[Tuple[List[str], List[str]]] = [
    (['*'], [
        'metadata.namespace',
        'spec.containers.*.imagePullPolicy',
        'spec.containers.*.terminationMessagePath',
        'spec.containers.*.terminationMessagePolicy',
        'spec.dnsPolicy',
        'spec.restartPolicy',
        'spec.schedulerName',
        'spec.terminationGracePeriodSeconds',
        'metadata.labels.app.kubernetes.io/instance',
        'metadata.managedFields.*',
        'metadata.resourceVersion',
        'metadata.selfLink',
        'metadata.annotations.control-plane.alpha.kubernetes.io/leader',
        'metadata.annotations.kubectl.kubernetes.io/'
        'last-applied-configuration',
        'metadata.finalizers*',
        'metadata.annotations.namespace',
        'metadata.annotations.deprecated.daemonset.template.generation',
        'metadata.creationTimestamp',
        'metadata.uid',
        'metadata.generation',
        'status',
        'metadata.annotations.deployment.kubernetes.io/revision',
    ]),
    (['Pod'], [
        'spec.volumes.*.name',
        'spec.volumes.*.projected.*',
        'spec.volumes.*.configMap.defaultMode',
        'spec.containers.*.volumeMounts.*',
        'spec.tolerations.*',
        'spec.enableServiceLinks',
        'spec.preemptionPolicy',
        'spec.priority',
        'spec.serviceAccount',
        'spec.nodeName',
    ]),
    (['Deployment'], [
        'spec.progressDeadlineSeconds',
        'spec.revisionHistoryLimit',
        'spec.strategy.*',
        'spec.template.metadata.creationTimestamp',
        'spec.containers.*.ports.*.protocol',
        'spec.containers.*.resources',
        'spec.securityContext',
    ]),
    (['Service'], [
        'spec.ports.*.nodePort',
        'spec.ports.*.protocol',
        'spec.clusterIP',
        'spec.clusterIPs.0',
        'spec.sessionAffinity',
        'spec.type',
        'spec.ipFamilies.*',
        'spec.ipFamilyPolicy',
        'spec.internalTrafficPolicy',
    ]),
    (['ClusterPolicy', 'Policy'], [
        'metadata.annotations.pod-policies.kyverno.io/autogen-controllers',
        'spec.failurePolicy',
        'spec.background',
        'spec.validationFailureAction',
    ]),
    (['ServiceAccount'], [
        'secrets.*.name',
        'imagePullSecrets.*.name',
    ]),
]


class ManifestError(Exception):
    pass


def process_yaml_validation_rule(pctx, rule) -> Optional['RuleResponse']:
    """reference: k8smanifest.go:38 processYAMLValidationRule"""
    from .api import RuleResponse, RuleStatus, RuleType
    if pctx.new_resource == {} and pctx.old_resource:
        return None  # delete request
    manifests = (rule.validation or {}).get('manifests') or {}
    try:
        verified, reason = verify_manifest(
            pctx.new_resource, manifests)
    except ManifestError as exc:
        return RuleResponse(rule.name, RuleType.VALIDATION,
                            'error occurred during manifest verification: '
                            f'{exc}', RuleStatus.ERROR)
    status = RuleStatus.PASS if verified else RuleStatus.FAIL
    return RuleResponse(rule.name, RuleType.VALIDATION, reason, status)


def verify_manifest(resource: dict, manifests: dict) -> Tuple[bool, str]:
    """reference: k8smanifest.go:59 verifyManifest"""
    domain = manifests.get('annotationDomain') or DEFAULT_ANNOTATION_DOMAIN
    ignore_fields = list(manifests.get('ignoreFields') or [])
    verified_msgs = []
    for i, attestor_set in enumerate(manifests.get('attestors') or []):
        verified, reason = _verify_attestor_set(
            resource, attestor_set, domain, ignore_fields,
            path=f'.attestors[{i}]')
        if not verified:
            return False, reason
        verified_msgs.append(reason)
    return True, 'verified manifest signatures; ' + ','.join(verified_msgs)


def _expand_static_keys(attestor_set: dict) -> List[dict]:
    """Split multi-PEM key entries into one entry per key
    (reference: k8smanifest.go expandStaticKeys)."""
    out = []
    for entry in attestor_set.get('entries') or []:
        keys = entry.get('keys') or {}
        pem_blob = keys.get('publicKeys') or ''
        if pem_blob.count('-----BEGIN') > 1:
            for block in _split_pem(pem_blob):
                e = dict(entry)
                e['keys'] = dict(keys, publicKeys=block)
                out.append(e)
        else:
            out.append(entry)
    return out


def _split_pem(blob: str) -> List[str]:
    blocks, current = [], []
    for line in blob.splitlines():
        current.append(line)
        if line.startswith('-----END'):
            blocks.append('\n'.join(current))
            current = []
    return blocks


def _required_count(attestor_set: dict, entries: List[dict]) -> int:
    count = attestor_set.get('count')
    if count is None or count == 0:
        return len(entries)
    return int(count)


def _verify_attestor_set(resource: dict, attestor_set: dict, domain: str,
                         ignore_fields: List[dict], path: str
                         ) -> Tuple[bool, str]:
    """reference: k8smanifest.go:155 verifyManifestAttestorSet"""
    entries = _expand_static_keys(attestor_set)
    required = _required_count(attestor_set, entries)
    verified_count = 0
    verified_msgs, failed_msgs = [], []
    for i, entry in enumerate(entries):
        entry_path = f'{path}.entries[{i}]'
        if entry.get('attestor') is not None:
            verified, reason = _verify_attestor_set(
                resource, entry['attestor'], domain, ignore_fields,
                entry_path + '.attestor')
        elif entry.get('keys') is not None:
            verified, reason = _verify_with_key(
                resource, entry['keys'], domain, ignore_fields, entry_path)
        else:
            raise ManifestError(
                f'attestor entry at {entry_path} has no keys; only static '
                'key verification is supported offline')
        if verified:
            verified_count += 1
            verified_msgs.append(reason)
        else:
            failed_msgs.append(reason)
        if verified_count >= required:
            return True, (f'manifest verification succeeded; verifiedCount '
                          f'{verified_count}; requiredCount {required}; '
                          f'message {",".join(verified_msgs)}')
    return False, (f'manifest verification failed; verifiedCount '
                   f'{verified_count}; requiredCount {required}; '
                   f'message {",".join(failed_msgs)}')


def _signatures(annotations: Dict[str, str], domain: str) -> List[bytes]:
    sigs = []
    base = f'{domain}/signature'
    if annotations.get(base):
        sigs.append(base64.b64decode(annotations[base]))
    i = 1
    while annotations.get(f'{base}_{i}'):
        sigs.append(base64.b64decode(annotations[f'{base}_{i}']))
        i += 1
    return sigs


def _verify_with_key(resource: dict, keys: dict, domain: str,
                     ignore_fields: List[dict], entry_path: str
                     ) -> Tuple[bool, str]:
    annotations = (resource.get('metadata') or {}).get('annotations') or {}
    msg_b64 = annotations.get(f'{domain}/message')
    if not msg_b64:
        return False, (f'failed to verify signature: annotation '
                       f'{domain}/message not found in the resource')
    sigs = _signatures(annotations, domain)
    if not sigs:
        return False, (f'failed to verify signature: annotation '
                       f'{domain}/signature not found in the resource')
    try:
        blob = gzip.decompress(base64.b64decode(msg_b64))
    except Exception as exc:  # noqa: BLE001
        raise ManifestError(f'failed to decode message: {exc}') from exc

    try:
        from cryptography.exceptions import InvalidSignature
        from cryptography.hazmat.primitives import hashes, serialization
        from cryptography.hazmat.primitives.asymmetric import ec, padding
    except ImportError as exc:  # pragma: no cover
        raise ManifestError('cryptography package unavailable') from exc

    pem = (keys.get('publicKeys') or '').encode()
    try:
        key = serialization.load_pem_public_key(pem)
    except Exception as exc:  # noqa: BLE001
        raise ManifestError(f'failed to load public key: {exc}') from exc

    signature_ok = False
    for sig in sigs:
        try:
            if isinstance(key, ec.EllipticCurvePublicKey):
                key.verify(sig, blob, ec.ECDSA(hashes.SHA256()))
            else:
                key.verify(sig, blob, padding.PKCS1v15(), hashes.SHA256())
            signature_ok = True
            break
        except InvalidSignature:
            continue
    if not signature_ok:
        return False, 'failed to verify signature: signature mismatch'

    manifest = _manifest_from_blob(blob)
    diffs = manifest_diff(manifest, resource, resource.get('kind', ''),
                          ignore_fields, domain)
    if diffs:
        return False, ('failed to verify signature; diff found: ' +
                       ', '.join(diffs[:5]))
    return True, f'singed by a valid signer: {entry_path}'


def _manifest_from_blob(blob: bytes) -> dict:
    for mode in ('r:gz', 'r:'):
        try:
            with tarfile.open(fileobj=io.BytesIO(blob), mode=mode) as tf:
                for member in tf.getmembers():
                    if member.isfile():
                        f = tf.extractfile(member)
                        if f is not None:
                            return yaml.safe_load(f.read()) or {}
        except (tarfile.TarError, OSError):
            continue
    # not a tarball: the blob may be the YAML itself (optionally gzipped)
    try:
        return yaml.safe_load(gzip.decompress(blob)) or {}
    except (OSError, yaml.YAMLError):
        pass
    try:
        return yaml.safe_load(blob) or {}
    except yaml.YAMLError as exc:
        raise ManifestError(
            f'no manifest found inside signed message: {exc}') from exc


# -- mutation diff ----------------------------------------------------------

def manifest_diff(manifest: Any, resource: Any, kind: str,
                  extra_ignore_fields: List[dict], domain: str) -> List[str]:
    """Dotted paths where the signed manifest and the live resource differ,
    minus the ignore-field config (reference: k8smanifest VerifyResource
    mutation check with DisableDryRun)."""
    patterns = [f'metadata.annotations.{domain}/*']
    for kinds, fields in _DEFAULT_IGNORE_FIELDS:
        if '*' in kinds or kind in kinds:
            patterns.extend(fields)
    for binding in extra_ignore_fields or []:
        objects = binding.get('objects') or []
        applies = not objects or any(
            (o.get('kind') in ('*', kind)) for o in objects)
        if applies:
            patterns.extend(binding.get('fields') or [])
    diffs: List[str] = []
    _walk_diff(manifest, resource, '', diffs)
    return [d for d in diffs if not _ignored(d, patterns)]


def _walk_diff(want: Any, have: Any, path: str, out: List[str]) -> None:
    if isinstance(want, dict) and isinstance(have, dict):
        for k in set(want) | set(have):
            sub = f'{path}.{k}' if path else str(k)
            if k not in want:
                _walk_added(have[k], sub, out)
            elif k not in have:
                out.append(sub)
            else:
                _walk_diff(want[k], have[k], sub, out)
    elif isinstance(want, list) and isinstance(have, list):
        for i in range(max(len(want), len(have))):
            sub = f'{path}.{i}'
            if i >= len(want):
                _walk_added(have[i], sub, out)
            elif i >= len(have):
                out.append(sub)
            else:
                _walk_diff(want[i], have[i], sub, out)
    elif want != have:
        out.append(path or '.')


def _walk_added(have: Any, path: str, out: List[str]) -> None:
    """Record leaf paths for content present only in the resource, so
    server-added defaults can be matched by leaf-level ignore patterns."""
    if isinstance(have, dict) and have:
        for k, v in have.items():
            _walk_added(v, f'{path}.{k}' if path else str(k), out)
    elif isinstance(have, list) and have:
        for i, v in enumerate(have):
            _walk_added(v, f'{path}.{i}', out)
    else:
        out.append(path)


def _ignored(path: str, patterns: List[str]) -> bool:
    for pattern in patterns:
        if _field_match(pattern, path):
            return True
    return False


def _field_match(pattern: str, path: str) -> bool:
    """Segment-wise glob match; a pattern also matches any deeper path
    (``status`` ignores ``status.foo.bar``)."""
    p_segs = pattern.split('.')
    f_segs = path.split('.')
    if len(f_segs) < len(p_segs):
        return False
    for ps, fs in zip(p_segs, f_segs):
        if not fnmatch.fnmatchcase(fs, ps):
            return False
    return True
